"""Persistent XLA compilation cache.

Every service process jit-compiles the same estimator programs; on a
small-CPU host a cold tree-fit compile costs minutes of wall-clock per
process (measured: 113 s -> 1.7 s with the cache warm on a tunneled
v5e). The reference ships no analogue — Spark redistributes jars, but
every request still pays JVM/codegen warmup (reference
model_builder.py:69-92 builds a fresh SparkSession per request). JAX's
persistent cache is keyed by program + compiler version + topology, so
sharing the directory between processes and across restarts is safe.

``LO_JIT_CACHE`` overrides the directory; empty string disables.
"""

from __future__ import annotations

import os

_ENABLED = False


def enable_compile_cache(default_dir: str | None = None) -> str | None:
    """Idempotently point JAX's persistent compilation cache at
    ``LO_JIT_CACHE`` (or ``default_dir``). Returns the directory used,
    or None when disabled. Call before the first jitted execution —
    already-compiled programs are not retroactively cached."""
    global _ENABLED
    cache_dir = os.environ.get("LO_JIT_CACHE")
    if cache_dir is None:
        cache_dir = default_dir
    if not cache_dir:
        return None
    if _ENABLED:
        return cache_dir
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # default min compile time (1 s) skips trivial programs; keep it
    _ENABLED = True
    return cache_dir
