"""Persistent XLA compilation cache.

Every service process jit-compiles the same estimator programs; on a
small-CPU host a cold tree-fit compile costs minutes of wall-clock per
process (measured: 113 s -> 1.7 s with the cache warm on a tunneled
v5e). The reference ships no analogue — Spark redistributes jars, but
every request still pays JVM/codegen warmup (reference
model_builder.py:69-92 builds a fresh SparkSession per request). JAX's
persistent cache is keyed by program + compiler version + topology, so
sharing the directory between processes and across restarts is safe.

``LO_JIT_CACHE`` overrides the directory; empty string disables.
"""

from __future__ import annotations

import contextlib
import contextvars
import os

_ACTIVE_DIR: str | None = None

# Ambient compile source for the jax.monitoring listeners below:
# "jit" = a request-path trace compiled on demand, "aot" = the boot
# precompile pass (compile/aot.py), "fleetcache" = the warm pass
# replaying programs satisfied from fleet-fetched artifacts. A
# contextvar, not a global: the AOT pass runs on its own background
# thread while request threads keep compiling with source="jit".
_COMPILE_SOURCE: contextvars.ContextVar[tuple[str, str | None]] = (
    contextvars.ContextVar("lo_compile_source", default=("jit", None))
)


@contextlib.contextmanager
def compile_source(source: str, key: str | None = None):
    """Attribute every compile jax.monitoring reports inside the block
    to ``source`` (and optionally a manifest ``key``) — the PR 8
    listener otherwise books boot compiles onto whatever job happens
    to be ambient, which made AOT warmup indistinguishable from a
    request-path compile storm in the flight recorder."""
    token = _COMPILE_SOURCE.set((source, key))
    try:
        yield
    finally:
        _COMPILE_SOURCE.reset(token)

# Live counters behind cache_stats() — registered once with
# jax.monitoring so "the cache didn't help" is a measured fact
# (VERDICT r4 weak #1: nothing recorded hits vs misses, so a 1550 s
# compile-bound run could not be diagnosed from its artifact).
_STATS = {
    "persistent_cache_hits": 0,
    "persistent_cache_misses": 0,
    "backend_compile_s": 0.0,
    "trace_s": 0.0,
}
_LISTENERS_ON = False


def _on_event(name: str, **_kw) -> None:
    # both are plain events in jax 0.9 (compiler.py records hits via
    # record_event, not a duration)
    if name == "/jax/compilation_cache/cache_misses":
        _STATS["persistent_cache_misses"] += 1
        _account_compile(result="miss")
    elif name == "/jax/compilation_cache/cache_hits":
        _STATS["persistent_cache_hits"] += 1
        _account_compile(result="hit")


def _on_duration(name: str, duration_secs: float, **_kw) -> None:
    if name == "/jax/core/compile/backend_compile_duration":
        _STATS["backend_compile_s"] += duration_secs
        _account_compile(seconds=duration_secs, span_name="compile:backend")
    elif name == "/jax/core/compile/jaxpr_trace_duration":
        _STATS["trace_s"] += duration_secs


def _account_compile(result=None, seconds=None, span_name=None) -> None:
    """Feed the flight recorder (telemetry/profile.py): compile events
    become ``lo_compile_*`` counters and — when a trace is active on
    the compiling thread, which it is for every scheduled job — an
    already-finished span on the job timeline, so a compile-bound
    build shows WHERE the compiler ate its wall-clock. AOT/warmup
    compiles get their OWN span name + manifest-key attribute (the
    ambient :func:`compile_source`), so the recorder separates boot
    compiles from request-path compiles instead of booking both onto
    whatever job is ambient. Listener context: must never raise into
    jax.monitoring."""
    try:
        from learningorchestra_tpu.telemetry import profile, tracing

        source, manifest_key = _COMPILE_SOURCE.get()
        profile.account_compile(
            result=result, seconds=seconds, source=source
        )
        if span_name is not None and seconds is not None:
            if source != "jit":
                meta = {"compile": True, "source": source}
                if manifest_key is not None:
                    meta["manifest_key"] = manifest_key
                tracing.record_span("compile:aot", seconds, **meta)
            else:
                tracing.record_span(span_name, seconds, compile=True)
        elif result is not None:
            # typed hit/miss counts on the enclosing span (fit, build…)
            tracing.add_attr(f"compile_{result}", 1)
    except Exception:  # noqa: BLE001 — observability never breaks compiles
        pass


def _register_listeners() -> None:
    global _LISTENERS_ON
    if _LISTENERS_ON:
        return
    import jax.monitoring

    jax.monitoring.register_event_listener(_on_event)
    jax.monitoring.register_event_duration_secs_listener(_on_duration)
    _LISTENERS_ON = True


def raw_stats() -> dict:
    """Unrounded live counters — what the telemetry registry's jitcache
    collector reads at scrape time (telemetry/metrics.py). Importing
    this module stays jax-free until the cache is enabled, so /metrics
    can report zeros before the first compile."""
    return dict(_STATS)


def cache_stats() -> dict:
    """Snapshot of persistent-cache hits/misses and compile seconds for
    this process, floats pre-rounded for reporting. A miss means the
    program was compiled and written; a hit means the serialized
    executable was loaded. ``backend_compile_s`` totals time inside the
    compiler (hits keep it near zero)."""
    return {
        k: round(v, 2) if isinstance(v, float) else v
        for k, v in _STATS.items()
    }


def enable_compile_cache(default_dir: str | None = None) -> str | None:
    """Idempotently point JAX's persistent compilation cache at
    ``LO_JIT_CACHE`` (or ``default_dir``, or ``<LO_DATA_DIR>/jit_cache``
    — the same data-dir root every service derives its paths from, so
    scripts and services share one cache). Returns the directory
    actually configured (the FIRST enabled dir — JAX's cache pointer is
    process-global), or None when disabled. Call before the first
    jitted execution — already-compiled programs are not retroactively
    cached."""
    global _ACTIVE_DIR
    _register_listeners()  # count hits/misses even on repeat calls
    if _ACTIVE_DIR is not None:
        return _ACTIVE_DIR
    # lo: allow[LO301,LO305] free-form cache-dir path, read once here
    cache_dir = os.environ.get("LO_JIT_CACHE")
    if cache_dir is None:
        cache_dir = default_dir
    if cache_dir is None:
        # lo: allow[LO305] same data-dir fallback the runner resolves
        data_dir = os.environ.get(
            "LO_DATA_DIR", os.path.join(os.getcwd(), "lo_data")
        )
        cache_dir = os.path.join(data_dir, "jit_cache")
    if not cache_dir:
        return None
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # The default ("xla_gpu_per_fusion_autotune_cache_dir") writes an
    # ABSOLUTE path under cache_dir into debug_options, and the cache
    # key hashes debug_options without clearing that field — so every
    # cache key silently binds to this machine's cache-dir path, and an
    # executable published through the fleet cache (compile/fleetcache)
    # could never hit on a runner with a different data dir. The knob
    # only feeds GPU autotune/kernel caches, irrelevant here; off it
    # goes, and keys depend on program + versions + backend alone.
    jax.config.update("jax_persistent_cache_enable_xla_caches", "")
    # default min compile time (1 s) skips trivial programs; keep it
    _ACTIVE_DIR = cache_dir
    return cache_dir
