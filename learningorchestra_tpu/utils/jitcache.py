"""Persistent XLA compilation cache.

Every service process jit-compiles the same estimator programs; on a
small-CPU host a cold tree-fit compile costs minutes of wall-clock per
process (measured: 113 s -> 1.7 s with the cache warm on a tunneled
v5e). The reference ships no analogue — Spark redistributes jars, but
every request still pays JVM/codegen warmup (reference
model_builder.py:69-92 builds a fresh SparkSession per request). JAX's
persistent cache is keyed by program + compiler version + topology, so
sharing the directory between processes and across restarts is safe.

``LO_JIT_CACHE`` overrides the directory; empty string disables.
"""

from __future__ import annotations

import os

_ACTIVE_DIR: str | None = None


def enable_compile_cache(default_dir: str | None = None) -> str | None:
    """Idempotently point JAX's persistent compilation cache at
    ``LO_JIT_CACHE`` (or ``default_dir``, or ``<LO_DATA_DIR>/jit_cache``
    — the same data-dir root every service derives its paths from, so
    scripts and services share one cache). Returns the directory
    actually configured (the FIRST enabled dir — JAX's cache pointer is
    process-global), or None when disabled. Call before the first
    jitted execution — already-compiled programs are not retroactively
    cached."""
    global _ACTIVE_DIR
    if _ACTIVE_DIR is not None:
        return _ACTIVE_DIR
    cache_dir = os.environ.get("LO_JIT_CACHE")
    if cache_dir is None:
        cache_dir = default_dir
    if cache_dir is None:
        data_dir = os.environ.get(
            "LO_DATA_DIR", os.path.join(os.getcwd(), "lo_data")
        )
        cache_dir = os.path.join(data_dir, "jit_cache")
    if not cache_dir:
        return None
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # default min compile time (1 s) skips trivial programs; keep it
    _ACTIVE_DIR = cache_dir
    return cache_dir
