"""The padded-shape grid: quarter-octave size bucketing, shared.

XLA compiles one program per shape, so every padding path in this
codebase rounds a varying count up to a small geometric grid instead of
compiling one program per exact size:

- ``parallel/sharding.py`` buckets dataset ROW counts before aligning
  them to the mesh's data axis (without it, every distinct row count
  recompiled every estimator — SCALE_r04's 273 s NB "fit" whose kernel
  runs in 27 ms).
- ``serve/batcher.py`` pads micro-batched predict requests to a fixed
  ``LO_SERVE_MAX_BATCH`` floor so all small traffic shares ONE compiled
  forward per model.
- ``sched/coalesce.py`` pads the JOB axis of a fused vmap-across-jobs
  dispatch, so coalesced batch sizes share compiled programs instead of
  causing a compile storm.

This module is the one copy of that math (two private copies is how the
paths drift). The floor semantics double as a reproducibility guarantee
the coalescer leans on: two dispatches padded to the SAME grid value run
the SAME XLA program, and a vmap slice's result depends only on its own
inputs — so a job fused into a batch of N and the same job run alone
produce bit-identical results whenever both land on one grid value.

Stdlib + numpy only; safe to import from the scheduler, the store
server, and the serving lane without pulling in jax.
"""

from __future__ import annotations

import os

import numpy as np

# LO_SHAPE_BUCKETS=0 restores minimal padding everywhere the grid is
# consulted (rows, micro-batches above their floor, coalesced job
# axes). Read once: per-request reads could desynchronize padded shapes
# — and so dispatch counts — across the hosts of a multi-host mesh.
# lo: allow[LO305] module-level read-once by design (see above)
_BUCKETS_ENABLED = os.environ.get("LO_SHAPE_BUCKETS", "1") != "0"


def bucket_count(n: int) -> int:
    """Smallest quarter-octave grid value >= n: {4,5,6,7} x 2^k.

    Every value is a multiple of a power of two at least n/8, so grid
    values compose cleanly with mesh-size multiples of 2/4/8 devices.
    Values <= 8 pass through (the grid would be sub-integer there, and
    tiny shapes compile fast). Idempotent: grid values map to
    themselves, so bucketing an already-bucketed count never grows it.
    """
    if n <= 8:
        return n
    power = 1 << (n.bit_length() - 1)  # largest power of two <= n
    if n == power:
        return n
    for quarters in (5, 6, 7, 8):
        candidate = power * quarters // 4
        if candidate >= n:
            return candidate
    raise AssertionError("unreachable: 2*power >= n by construction")


def grid_size(n: int, floor: int = 0) -> int:
    """``n`` rounded up to the padded-shape grid, with a fixed floor.

    Counts at or under ``floor`` pad to exactly ``floor`` (the
    MicroBatcher's fixed-dispatch-shape trick: all small traffic shares
    ONE compiled program); larger counts ride the quarter-octave grid,
    which bounds the number of distinct compiled shapes logarithmically.
    ``LO_SHAPE_BUCKETS=0`` disables the above-floor bucketing (the
    debug knob for shape-dependent issues) — the floor itself stays,
    as it did before the grid was shared.
    """
    if n <= floor:
        return floor
    return bucket_count(n) if _BUCKETS_ENABLED else n


def pad_axis0(array: np.ndarray, target: int) -> np.ndarray:
    """Zero-pad ``array`` along axis 0 up to ``target`` rows (no copy
    when already there). Callers carry their own validity discipline —
    a mask, or slicing the pad back off after the dispatch."""
    n = array.shape[0]
    if n >= target:
        return array
    pad_width = [(0, target - n)] + [(0, 0)] * (array.ndim - 1)
    return np.pad(array, pad_width)


def padded_indices(n: int, target: int) -> list[int]:
    """Source indices for padding a stacked axis to ``target`` entries
    by REPLICATING entry 0 into the dummy slots: ``[0..n-1, 0, 0, ...]``.

    Replication (not zeros) keeps dummy vmap slices numerically inert —
    an all-zero dummy member would divide by a zero mask-sum and drag
    NaNs through the fused program's dummy lanes; a replica computes a
    discarded copy of real work instead.
    """
    if n < 1:
        raise ValueError("padded_indices needs at least one real entry")
    return list(range(n)) + [0] * (target - n)
