"""End-to-end feature dtype policy (``LO_DTYPE_POLICY``).

``f32`` (default) keeps the historical behavior: feature matrices ship
host→device and live in HBM as float32. ``bf16`` halves both — the
padded matrix is cast host-side before ``jax.device_put``
(parallel/sharding.py), so the H2D transfer AND the HBM-resident
working set drop 2×, which on a tunneled or PCIe-attached chip is most
of a cold build's boundary cost. Parameters, reductions, and metrics
stay float32 (jnp type promotion lifts ``bf16 @ f32`` matmuls to f32
accumulation), so fits remain numerically anchored; the policy trades
feature-matrix mantissa bits for bandwidth, the same trade serving
stacks make for activations.

The policy is part of every device-cache key (core/devcache.py): an
entry prepared under one policy never serves another, exactly like the
mesh signature.

Read ONCE per process (like ``LO_SHAPE_BUCKETS`` /
``LO_PROGRAM_ROW_STEPS``): a per-request read could desynchronize SPMD
dispatch shapes across a multi-host mesh, so the knob is
process-lifetime constant and must be set identically on every host.
Stdlib+numpy only — the store server imports this transitively and must
never pay a jax import.
"""

from __future__ import annotations

import os

POLICIES = ("f32", "bf16")

_POLICY: list = []  # one-element cache: read once per process


def validate_policy(raw: str) -> str:
    value = raw.strip() or "f32"
    if value not in POLICIES:
        raise ValueError(
            f"LO_DTYPE_POLICY must be one of {'|'.join(POLICIES)}, "
            f"got {raw!r}"
        )
    return value


def dtype_policy() -> str:
    """The process's feature dtype policy string — also the token that
    rides device-cache keys."""
    if not _POLICY:
        _POLICY.append(
            # lo: allow[LO305] read-once accessor, validated in place
            validate_policy(os.environ.get("LO_DTYPE_POLICY", "f32"))
        )
    return _POLICY[0]


def validate_env() -> None:
    """Fail fast on a malformed ``LO_DTYPE_POLICY`` — deploy/run.sh's
    preflight calls this (uncached, so it always re-reads the env)."""
    validate_policy(os.environ.get("LO_DTYPE_POLICY", "f32"))
