"""Tracing and per-phase timing.

The reference's only observability artifact is a wall-clock ``fit_time``
in the prediction metadata (reference: model_builder.py:198-203;
SURVEY.md §5 "Tracing / profiling: absent"). Here timings are
first-class: a :class:`PhaseTimer` accumulates named phase durations that
jobs attach to their result metadata, and :func:`trace` wraps the JAX
profiler so any block can emit a TensorBoard-loadable device trace.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

from learningorchestra_tpu.telemetry import tracing as _tracing


class PhaseTimer:
    """Accumulates ``{phase: seconds}``; reentrant per phase.

    Each phase ENTRY lands as its own timestamped span in the active
    trace (a no-op outside one) and as its own row in ``occurrences``:
    a phase entered twice is two events with distinct start/end
    boundaries on the timeline — summing them into one bucket would
    smear ``GET /jobs/<name>/profile``'s Chrome trace. The summed
    ``as_metadata()`` contract is unchanged: stored job metadata keeps
    one total per phase name. ``**attrs`` become typed span attributes
    (rows, bytes, dtype) on that occurrence's span."""

    def __init__(self):
        self.timings: dict[str, float] = {}
        # one row per phase ENTRY: (name, epoch start, seconds)
        self.occurrences: list[tuple[str, float, float]] = []

    @contextlib.contextmanager
    def phase(self, name: str, **attrs) -> Iterator[None]:
        start = time.perf_counter()
        started_at = time.time()
        try:
            with _tracing.span(f"phase:{name}", **attrs):
                yield
        finally:
            elapsed = time.perf_counter() - start
            self.timings[name] = self.timings.get(name, 0.0) + elapsed
            self.occurrences.append((name, started_at, elapsed))

    def as_metadata(self) -> dict[str, float]:
        """Rounded copy for inclusion in stored job metadata."""
        return {name: round(seconds, 6) for name, seconds in self.timings.items()}


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """JAX profiler trace into ``log_dir`` (no-op when None) — view with
    TensorBoard's profile plugin or Perfetto."""
    if log_dir is None:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield
