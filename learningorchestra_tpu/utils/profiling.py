"""Tracing and per-phase timing.

The reference's only observability artifact is a wall-clock ``fit_time``
in the prediction metadata (reference: model_builder.py:198-203;
SURVEY.md §5 "Tracing / profiling: absent"). Here timings are
first-class: a :class:`PhaseTimer` accumulates named phase durations that
jobs attach to their result metadata, and :func:`trace` wraps the JAX
profiler so any block can emit a TensorBoard-loadable device trace.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

from learningorchestra_tpu.telemetry import tracing as _tracing


class PhaseTimer:
    """Accumulates ``{phase: seconds}``; reentrant per phase.

    Each phase also lands as a span in the active trace context (a
    no-op outside one), so the same ``fit``/``write`` numbers that go to
    stored metadata appear in the request's correlated span tree
    (``GET /jobs/<name>/trace``) without double instrumentation."""

    def __init__(self):
        self.timings: dict[str, float] = {}

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        start = time.perf_counter()
        try:
            with _tracing.span(f"phase:{name}"):
                yield
        finally:
            elapsed = time.perf_counter() - start
            self.timings[name] = self.timings.get(name, 0.0) + elapsed

    def as_metadata(self) -> dict[str, float]:
        """Rounded copy for inclusion in stored job metadata."""
        return {name: round(seconds, 6) for name, seconds in self.timings.items()}


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """JAX profiler trace into ``log_dir`` (no-op when None) — view with
    TensorBoard's profile plugin or Perfetto."""
    if log_dir is None:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield
