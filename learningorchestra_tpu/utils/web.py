"""Minimal WSGI micro-framework over werkzeug.

The reference exposes its services as Flask apps (e.g. reference:
microservices/database_api_image/server.py:31). Flask is not available in
this environment, so this module provides the thin slice of that surface
our services need — routing with URL parameters, JSON request/response
helpers, file responses, a test client, and a threaded dev server — on
top of werkzeug, which is available.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Callable

from werkzeug.exceptions import HTTPException, NotFound
from werkzeug.routing import Map, Rule
from werkzeug.serving import make_server
from werkzeug.test import Client
from werkzeug.wrappers import Request, Response

from learningorchestra_tpu.telemetry import metrics as _metrics
from learningorchestra_tpu.telemetry import tracing as _tracing
from learningorchestra_tpu.utils import webloop as _webloop
from learningorchestra_tpu.utils.webloop import (  # noqa: F401 — re-export
    Upstream,
    Waiter,
)


def jsonify(payload: Any) -> Response:
    return Response(
        json.dumps(payload), mimetype="application/json", status=200
    )


def too_many_requests(error) -> Response:
    """HTTP 429 for a :class:`~learningorchestra_tpu.sched.scheduler.
    QueueFullError`: admission control's REST face. ``Retry-After``
    carries the scheduler's backlog-drain estimate so well-behaved
    clients pace themselves instead of hammering a full queue."""
    response = Response(
        json.dumps(
            {
                "result": "queue_full",
                "job_class": error.job_class,
                "retry_after_s": error.retry_after_s,
            }
        ),
        mimetype="application/json",
        status=429,
    )
    response.headers["Retry-After"] = str(error.retry_after_s)
    return response


def send_file(path: str, mimetype: str) -> Response:
    with open(path, "rb") as handle:
        data = handle.read()
    return Response(data, mimetype=mimetype, status=200)


class WebApp:
    """A WSGI application with Flask-like ``route`` registration.

    Handlers receive the ``werkzeug`` ``Request`` as their first argument
    (instead of Flask's implicit request global) plus any URL parameters,
    and may return a ``Response``, or a ``(payload, status)`` tuple where
    the payload is JSON-serialised.
    """

    def __init__(self, name: str, registry=None):
        self.name = name
        self.url_map = Map()
        self._handlers: dict[str, Callable] = {}
        # set by register_observability(): the store whose __lo_metrics__
        # ring backs /metrics/history, /debug/slo and /health's degraded
        self._obs_store = None
        # Telemetry: every app reports into the process registry (one
        # shared registry when services co-habit a process — families
        # are labelled by service) and serves it at GET /metrics.
        self.registry = registry or _metrics.global_registry()
        self._requests_total = self.registry.counter(
            "lo_http_requests_total",
            "HTTP requests handled",
            labels=("service", "route", "method", "status"),
        )
        self._request_seconds = self.registry.histogram(
            "lo_http_request_duration_seconds",
            "Wall-clock per request",
            labels=("service", "route", "method"),
        )
        self._in_flight = self.registry.gauge(
            "lo_http_requests_in_flight",
            "Requests currently being handled",
            labels=("service",),
        )
        # Flight-recorder byte-flow families declare eagerly so every
        # service's /metrics shows them from boot (dashboards see the
        # family before its first byte moves) — profile.py declares
        # lazily on its own to stay import-light for library embedders.
        from learningorchestra_tpu.telemetry import profile as _profile

        _profile._flow_metrics()

        @self.route("/metrics")
        def serve_metrics(request):
            return Response(
                self.registry.render(),
                content_type=_metrics.CONTENT_TYPE,
                status=200,
            )

        @self.route("/debug/profile")
        def debug_profile(request):
            """Sampling profiler (telemetry/profile.py): sample every
            thread's stack for ``?seconds=N`` (default 5, clamped to
            ``LO_PROF_WINDOW_S``) and answer folded flamegraph stacks —
            a live stall is diagnosable without a restart. Plain text
            by default (pipe to flamegraph.pl / speedscope);
            ``?format=json`` wraps the stacks with sample metadata.
            403 when disabled (``LO_PROF_HZ=0``)."""
            from learningorchestra_tpu.telemetry import profile as _profile

            try:
                seconds = float(request.args.get("seconds", "5"))
            except ValueError:
                return {"result": "bad_seconds"}, 400
            if not seconds > 0 or seconds != seconds:  # NaN included
                return {"result": "bad_seconds"}, 400
            try:
                stacks, samples = _profile.sample_stacks(seconds)
            except RuntimeError:
                return {"result": "profiler_disabled"}, 403
            except ValueError as error:
                # malformed LO_PROF_* in a process that skipped the
                # run.sh preflight (library embedder, hand-launched
                # service): clean JSON, never a traceback — this is the
                # endpoint for diagnosing an already-sick process
                return {
                    "result": "invalid_prof_config",
                    "error": str(error),
                }, 500
            if request.args.get("format") == "json":
                return {
                    "result": {
                        "stacks": stacks,
                        "samples": samples,
                        "hz": _profile.prof_hz(),
                    }
                }, 200
            return Response(
                _profile.folded_text(stacks),
                mimetype="text/plain",
                status=200,
            )

        @self.route("/debug/spans")
        def debug_spans(request):
            """This process's span export buffer (telemetry/tracing.py)
            — the per-member feed the fleet stitcher drains.
            ``?cid=`` filters to one correlation ID, ``?since=`` to
            entries updated after an epoch timestamp."""
            cid = request.args.get("cid")
            since = request.args.get("since")
            if since is not None:
                try:
                    since = float(since)
                except ValueError:
                    return {"result": "bad_since"}, 400
            return {"result": _tracing.exported_spans(cid, since)}, 200

        @self.route("/traces/<cid>")
        def read_stitched_trace(request, cid):
            """ONE Chrome trace for one correlation ID, stitched across
            every plane member in ``LO_PLANE_MEMBERS`` (telemetry/
            stitch.py): one process row per ``service@pid``, so a
            client-driven multi-service pipeline renders as a single
            timeline. 404 when no member holds spans for the cid."""
            from learningorchestra_tpu.telemetry import stitch as _stitch

            trace = _stitch.stitched_trace(cid)
            if not trace["otherData"]["processes"]:
                return {"result": "not_found"}, 404
            return trace, 200

    def register_observability(self, store) -> None:
        """The store-backed half of the fleet observability plane
        (docs/observability.md "Fleet plane"):

        - ``GET /metrics/history?family=…`` — the ``__lo_metrics__``
          ring's fold-forward series plus server-side windowed rollups
          (rate / p50 / p99 per instance — telemetry/tsdb.py);
        - ``POST /metrics/ingest`` — raw Prometheus exposition text in,
          one retention tick out (what deploy/cluster.py's collector
          posts per scraped member);
        - ``GET /debug/slo`` — ok/burning per SLO rule with the
          offending instance (telemetry/slo.py); also arms ``/health``'s
          ``degraded`` field.
        """
        from learningorchestra_tpu.telemetry import slo as _slo
        from learningorchestra_tpu.telemetry import tsdb as _tsdb

        self._obs_store = store
        ingest_tsdb = _tsdb.TSDB(store)

        @self.route("/metrics/history")
        def metrics_history(request):
            family = request.args.get("family")
            if not family:
                return {"result": "bad_family"}, 400
            try:
                since = (
                    float(request.args["since"])
                    if "since" in request.args
                    else None
                )
                window_s = float(
                    request.args.get("window", _slo.slo_window_s())
                )
            except ValueError:
                return {"result": "bad_window"}, 400
            instance = request.args.get("instance")
            series = _tsdb.history(store, family, instance=instance)
            return {
                "result": {
                    "family": family,
                    "series": {
                        inst: [
                            [ts, value]
                            for ts, value in points
                            if since is None or ts >= since
                        ]
                        for inst, points in series.items()
                    },
                    "rollup": _tsdb.window_rollups(
                        store, family, window_s=window_s, instance=instance
                    ),
                    "services": _tsdb.services_of(store),
                }
            }, 200

        @self.route("/metrics/ingest", methods=("POST",))
        def metrics_ingest(request):
            body = request.get_json()
            instance = body.get("instance")
            text = body.get("text")
            if not instance or not isinstance(text, str):
                return {"result": "bad_ingest"}, 400
            try:
                vals = _tsdb.parse_samples(text)
            except ValueError as error:
                # a member scraped mid-restart: ITS tick is dropped,
                # the collection stays consistent
                return {"result": "unparseable", "error": str(error)}, 400
            ingest_tsdb.append(
                instance,
                body.get("service") or "unknown",
                vals,
                ts=body.get("ts"),
            )
            return {"result": "ok", "families": len(vals)}, 200

        @self.route("/debug/slo")
        def debug_slo(request):
            try:
                return {"result": _slo.status(store)}, 200
            except Exception as error:  # noqa: BLE001 — a store mid-
                # failover must yield a diagnosable payload, not a 500
                # traceback from the diagnosis endpoint itself
                return {
                    "result": "slo_unavailable",
                    "error": f"{type(error).__name__}: {error}",
                }, 503

    def slo_degraded(self) -> bool:
        """``/health``'s SLO verdict: True when any rule burns. False
        without a registered store or on any evaluation error — health
        must keep answering while the plane itself is sick."""
        if self._obs_store is None:
            return False
        try:
            from learningorchestra_tpu.telemetry import slo as _slo

            return bool(_slo.status(self._obs_store)["degraded"])
        except Exception:  # noqa: BLE001
            return False

    def register_job_traces(self, jobs) -> None:
        """Serve ``GET /jobs/<name>/trace``: the span tree (with the
        request's correlation ID) of a tracked job — the per-request
        "where did the time go" answer (core/jobs.py grows the trace)."""

        @self.route("/jobs/<job_name>/trace")
        def read_job_trace(request, job_name):
            record = jobs.get(job_name)
            if record is None:
                return {"result": "not_found"}, 404
            return {"result": record.trace_dict()}, 200

        @self.route("/jobs/<job_name>/profile")
        def read_job_profile(request, job_name):
            """The job's merged timeline as Chrome trace-event JSON
            (load in Perfetto: one row per thread, byte counter
            tracks); ``?format=summary`` returns the per-phase
            seconds/bytes/rows-per-s rollup instead — the shape
            ``bench.py --compare`` diffs (docs/profiling.md)."""
            from learningorchestra_tpu.telemetry import profile as _profile

            record = jobs.get(job_name)
            if record is None:
                return {"result": "not_found"}, 404
            if record.trace is None:
                return {"result": "no_trace"}, 404
            if request.args.get("format") == "summary":
                summary = _profile.trace_summary(record.trace)
                summary["job"] = record.as_dict()
                return {"result": summary}, 200
            return _profile.chrome_trace(record.trace), 200

    def register_job_routes(self, jobs) -> None:
        """The full job surface for a service holding a JobManager:

        - ``GET /jobs`` — every tracked job's state, class, priority,
          attempt count, timings, error, and correlation ID;
        - ``GET /jobs/<name>`` — one tracked job's record (404 unknown);
        - ``GET /jobs/<name>/wait?timeout=S`` — push job completion:
          long-poll (or SSE with ``Accept: text/event-stream``) until
          the job goes terminal, released by the job's ``done`` event —
          no client-side 3-second polling. Immediate return for
          already-terminal jobs; a bare dataset filename resolves to
          the newest job materialising it (``titanic`` →
          ``ingest:titanic``); 404 parity with ``GET /jobs/<name>``;
          a timeout answers a clean ``{"result": "timeout"}`` re-poll
          hint (docs/web.md);
        - ``GET /jobs/<name>/trace`` — its correlated span tree;
        - ``GET /health`` — liveness + feature probe: ``job_wait: true``
          tells clients the push route exists (client.py prefers it
          over metadata polling);
        - ``DELETE /jobs/<name>`` — cooperative cancellation: a queued
          job terminates without running, a running one at its next
          cancel check (ml/builder.py's phase loop checks); 202 while
          the cancel propagates, 409 once the job is already terminal.
          A cancel also wakes the job's parked waiters.
        """
        self.register_job_traces(jobs)
        # terminal-state names live with the manager; imported here (not
        # at module top) to keep this transport module import-light
        from learningorchestra_tpu.core.jobs import TERMINAL_STATES

        @self.route("/jobs")
        def read_jobs(request):
            return {"result": jobs.all_jobs()}, 200

        @self.route("/jobs/<job_name>", methods=("DELETE",))
        def cancel_job(request, job_name):
            outcome = jobs.cancel(job_name)
            if outcome == "unknown":
                return {"result": "not_found"}, 404
            if outcome == "terminal":
                return {"result": "already_terminal"}, 409
            return {"result": "cancelling"}, 202

        @self.route("/jobs/<job_name>", methods=("GET",))
        def read_job(request, job_name):
            record = jobs.get(job_name)
            if record is None:
                return {"result": "not_found"}, 404
            return {"result": record.as_dict()}, 200

        @self.route("/jobs/<job_name>/wait", methods=("GET",))
        def wait_job(request, job_name):
            try:
                timeout_s = float(request.args.get("timeout", "25"))
            except ValueError:
                return {"result": "bad_timeout"}, 400
            if timeout_s != timeout_s or timeout_s < 0:  # NaN included
                return {"result": "bad_timeout"}, 400
            timeout_s = min(timeout_s, _webloop.wait_cap_s())
            record = jobs.resolve_wait(job_name)
            if record is None:
                # parity with GET /jobs/<name>: unknown job is a 404,
                # clients fall back to metadata polling
                return {"result": "not_found"}, 404
            sse = "text/event-stream" in (request.headers.get("Accept") or "")

            def poll(_record=record):
                if _record.state in TERMINAL_STATES:
                    return {"result": _record.as_dict()}, 200
                return None

            def on_timeout(_record=record):
                # a clean re-poll hint: the job is alive, ask again
                return {
                    "result": "timeout",
                    "job": _record.name,
                    "state": _record.state,
                }, 200

            waiter = Waiter(poll, timeout_s, on_timeout, sse=sse)
            jobs.add_done_callback(record.name, waiter.notify)
            return waiter

        if not any(
            rule.rule == "/health" for rule in self.url_map.iter_rules()
        ):

            @self.route("/health")
            def health(request):
                return {
                    "result": "ok",
                    "service": self.name,
                    # feature probe: client.py checks this once per
                    # cluster before preferring /wait over polling
                    "job_wait": True,
                    # SLO verdict (telemetry/slo.py): liveness is not
                    # healthiness — a serving replica can answer 200s
                    # while its p99 burns
                    "degraded": self.slo_degraded(),
                }, 200

    def route(self, rule: str, methods: tuple[str, ...] = ("GET",)):
        def decorator(handler: Callable) -> Callable:
            endpoint = f"{handler.__name__}|{rule}|{'|'.join(methods)}"
            self.url_map.add(Rule(rule, endpoint=endpoint, methods=list(methods)))
            self._handlers[endpoint] = handler
            return handler

        return decorator

    def _dispatch(self, request: Request) -> Response:
        adapter = self.url_map.bind_to_environ(request.environ)
        try:
            endpoint, args = adapter.match()
            # the RULE (not the concrete path) labels request metrics, so
            # /files/<filename> is one series, not one per dataset
            request.environ["lo.route"] = endpoint.split("|")[1]
        except NotFound:
            return Response(
                json.dumps({"result": "not_found"}),
                mimetype="application/json",
                status=404,
            )
        except HTTPException as error:
            return error.get_response(request.environ)

        try:
            result = self._handlers[endpoint](request, **args)
        except HTTPException as error:
            # e.g. BadRequest from request.get_json() on a malformed
            # body — keep its real status code, don't convert to a 500.
            return error.get_response(request.environ)
        if isinstance(result, (Waiter, Upstream)):
            # the answer isn't ready / lives on another server:
            # __call__ parks or proxies it (event loop) or resolves it
            # blocking (threaded server / test client)
            return result
        if isinstance(result, Response):
            return result
        if isinstance(result, tuple):
            payload, status = result
            if isinstance(payload, Response):
                payload.status_code = status
                return payload
            return Response(
                json.dumps(payload), mimetype="application/json", status=status
            )
        return Response(
            json.dumps(result), mimetype="application/json", status=200
        )

    def __call__(self, environ, start_response):
        request = Request(environ)
        # Correlation middleware: honour a caller-supplied ID (a client
        # stitching multi-service flows) or mint one; the request runs
        # under an active trace so spans anywhere below (job submit,
        # SPMD dispatch, PhaseTimer phases) correlate, and the ID echoes
        # back on the response.
        correlation_id = (
            request.headers.get(_tracing.CORRELATION_HEADER)
            or _tracing.mint_correlation_id()
        )
        trace = _tracing.Trace(
            correlation_id, name=f"{request.method} {request.path}"
        )
        self._in_flight.labels(self.name).inc()
        started = time.perf_counter()
        try:
            with _tracing.activate(trace), _tracing.span(
                f"http:{request.method} {request.path}"
            ):
                try:
                    response = self._dispatch(request)
                except Exception as error:  # mirror Flask's 500 text
                    response = Response(
                        f"{type(error).__name__}: {error}",
                        status=500,
                        mimetype="text/plain",
                    )
        finally:
            self._in_flight.labels(self.name).dec()
        # feed the cross-process stitcher: this request's spans land in
        # the cid-keyed export buffer GET /debug/spans drains
        _tracing.export_trace(trace, service=self.name)
        route = environ.get("lo.route", "<unmatched>")
        method = request.method
        if isinstance(response, Upstream):
            upstream = response
            upstream.correlation_id = correlation_id
            if environ.get("lo.async"):
                # Event-loop server: the loop proxies on its own thread
                # — this pooled thread is released immediately. Metrics
                # record at relay time, like a parked waiter's. A
                # route-set on_complete (the router's own families)
                # chains in front rather than being replaced.
                route_complete = upstream.on_complete

                def complete(status, _route=route, _method=method):
                    if route_complete is not None:
                        route_complete(status)
                    self._requests_total.labels(
                        self.name, _route, _method, status
                    ).inc()
                    self._request_seconds.labels(
                        self.name, _route, _method
                    ).observe(time.perf_counter() - started)

                upstream.on_complete = complete
                environ["lo.upstream"] = upstream
                start_response("204 No Content", [])
                return [b""]
            # Threaded server / test client: walk the targets blocking
            # on this request thread.
            status, headers, body = upstream.resolve_blocking()
            response = Response(body, status=status, headers=headers)
        if isinstance(response, Waiter):
            waiter = response
            waiter.correlation_id = correlation_id
            if environ.get("lo.async"):
                # Event-loop server: park the CONNECTION, not a thread.
                # Metrics record at resolution — a long-poll's latency
                # IS its parked time.
                def complete(status, _route=route, _method=method):
                    self._requests_total.labels(
                        self.name, _route, _method, status
                    ).inc()
                    self._request_seconds.labels(
                        self.name, _route, _method
                    ).observe(time.perf_counter() - started)

                waiter.on_complete = complete
                environ["lo.waiter"] = waiter
                start_response("204 No Content", [])
                return [b""]
            # Threaded server / test client: reference-parity blocking —
            # this request thread parks until ready or timeout.
            result, kind = waiter.resolve_blocking()
            body, status, content_type = _webloop.waiter_body(
                waiter, result, kind
            )
            response = Response(body, status=status, mimetype=content_type)
        self._requests_total.labels(
            self.name, route, method, response.status_code
        ).inc()
        self._request_seconds.labels(
            self.name, route, method
        ).observe(time.perf_counter() - started)
        response.headers[_tracing.CORRELATION_HEADER] = correlation_id
        return response(environ, start_response)

    def test_client(self) -> Client:
        return Client(self, Response)


class ServerThread:
    """Run a WSGI app on a background thread (integration tests, dev).

    ``LO_WEB_ASYNC=1`` (the default) serves through the event-loop core
    (utils/webloop.LoopServer): one selectors loop owns every socket and
    a bounded handler pool runs the route functions. ``LO_WEB_ASYNC=0``
    is the escape hatch back to werkzeug's thread-per-request server —
    byte-compatible routes, reference-parity blocking waits."""

    def __init__(self, app: WebApp, host: str, port: int):
        self.host = host
        if _webloop.web_async_enabled():
            self._server = None
            self._loop = _webloop.LoopServer(app, host, port)
            self.port = self._loop.port
            self._thread = self._loop._thread
        else:
            self._loop = None
            self._server = make_server(host, port, app, threaded=True)
            self.port = self._server.server_port
            self._thread = threading.Thread(
                target=self._server.serve_forever,
                daemon=True,
                name=f"{app.name}-server",
            )

    def start(self) -> "ServerThread":
        if self._loop is not None:
            self._loop.start()
        else:
            self._thread.start()
        return self

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.stop()
        else:
            self._server.shutdown()
        self._thread.join(timeout=5)


def run_app(app: WebApp, host: str, port: int) -> None:
    """Serve forever in the foreground (container entrypoint)."""
    if _webloop.web_async_enabled():
        _webloop.LoopServer(app, host, port).serve_forever()
        return
    make_server(host, port, app, threaded=True).serve_forever()
