"""Minimal WSGI micro-framework over werkzeug.

The reference exposes its services as Flask apps (e.g. reference:
microservices/database_api_image/server.py:31). Flask is not available in
this environment, so this module provides the thin slice of that surface
our services need — routing with URL parameters, JSON request/response
helpers, file responses, a test client, and a threaded dev server — on
top of werkzeug, which is available.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Callable

from werkzeug.exceptions import HTTPException, NotFound
from werkzeug.routing import Map, Rule
from werkzeug.serving import make_server
from werkzeug.test import Client
from werkzeug.wrappers import Request, Response

from learningorchestra_tpu.telemetry import metrics as _metrics
from learningorchestra_tpu.telemetry import tracing as _tracing


def jsonify(payload: Any) -> Response:
    return Response(
        json.dumps(payload), mimetype="application/json", status=200
    )


def too_many_requests(error) -> Response:
    """HTTP 429 for a :class:`~learningorchestra_tpu.sched.scheduler.
    QueueFullError`: admission control's REST face. ``Retry-After``
    carries the scheduler's backlog-drain estimate so well-behaved
    clients pace themselves instead of hammering a full queue."""
    response = Response(
        json.dumps(
            {
                "result": "queue_full",
                "job_class": error.job_class,
                "retry_after_s": error.retry_after_s,
            }
        ),
        mimetype="application/json",
        status=429,
    )
    response.headers["Retry-After"] = str(error.retry_after_s)
    return response


def send_file(path: str, mimetype: str) -> Response:
    with open(path, "rb") as handle:
        data = handle.read()
    return Response(data, mimetype=mimetype, status=200)


class WebApp:
    """A WSGI application with Flask-like ``route`` registration.

    Handlers receive the ``werkzeug`` ``Request`` as their first argument
    (instead of Flask's implicit request global) plus any URL parameters,
    and may return a ``Response``, or a ``(payload, status)`` tuple where
    the payload is JSON-serialised.
    """

    def __init__(self, name: str, registry=None):
        self.name = name
        self.url_map = Map()
        self._handlers: dict[str, Callable] = {}
        # Telemetry: every app reports into the process registry (one
        # shared registry when services co-habit a process — families
        # are labelled by service) and serves it at GET /metrics.
        self.registry = registry or _metrics.global_registry()
        self._requests_total = self.registry.counter(
            "lo_http_requests_total",
            "HTTP requests handled",
            labels=("service", "route", "method", "status"),
        )
        self._request_seconds = self.registry.histogram(
            "lo_http_request_duration_seconds",
            "Wall-clock per request",
            labels=("service", "route", "method"),
        )
        self._in_flight = self.registry.gauge(
            "lo_http_requests_in_flight",
            "Requests currently being handled",
            labels=("service",),
        )
        # Flight-recorder byte-flow families declare eagerly so every
        # service's /metrics shows them from boot (dashboards see the
        # family before its first byte moves) — profile.py declares
        # lazily on its own to stay import-light for library embedders.
        from learningorchestra_tpu.telemetry import profile as _profile

        _profile._flow_metrics()

        @self.route("/metrics")
        def serve_metrics(request):
            return Response(
                self.registry.render(),
                content_type=_metrics.CONTENT_TYPE,
                status=200,
            )

        @self.route("/debug/profile")
        def debug_profile(request):
            """Sampling profiler (telemetry/profile.py): sample every
            thread's stack for ``?seconds=N`` (default 5, clamped to
            ``LO_PROF_WINDOW_S``) and answer folded flamegraph stacks —
            a live stall is diagnosable without a restart. Plain text
            by default (pipe to flamegraph.pl / speedscope);
            ``?format=json`` wraps the stacks with sample metadata.
            403 when disabled (``LO_PROF_HZ=0``)."""
            from learningorchestra_tpu.telemetry import profile as _profile

            try:
                seconds = float(request.args.get("seconds", "5"))
            except ValueError:
                return {"result": "bad_seconds"}, 400
            if not seconds > 0 or seconds != seconds:  # NaN included
                return {"result": "bad_seconds"}, 400
            try:
                stacks, samples = _profile.sample_stacks(seconds)
            except RuntimeError:
                return {"result": "profiler_disabled"}, 403
            except ValueError as error:
                # malformed LO_PROF_* in a process that skipped the
                # run.sh preflight (library embedder, hand-launched
                # service): clean JSON, never a traceback — this is the
                # endpoint for diagnosing an already-sick process
                return {
                    "result": "invalid_prof_config",
                    "error": str(error),
                }, 500
            if request.args.get("format") == "json":
                return {
                    "result": {
                        "stacks": stacks,
                        "samples": samples,
                        "hz": _profile.prof_hz(),
                    }
                }, 200
            return Response(
                _profile.folded_text(stacks),
                mimetype="text/plain",
                status=200,
            )

    def register_job_traces(self, jobs) -> None:
        """Serve ``GET /jobs/<name>/trace``: the span tree (with the
        request's correlation ID) of a tracked job — the per-request
        "where did the time go" answer (core/jobs.py grows the trace)."""

        @self.route("/jobs/<job_name>/trace")
        def read_job_trace(request, job_name):
            record = jobs.get(job_name)
            if record is None:
                return {"result": "not_found"}, 404
            return {"result": record.trace_dict()}, 200

        @self.route("/jobs/<job_name>/profile")
        def read_job_profile(request, job_name):
            """The job's merged timeline as Chrome trace-event JSON
            (load in Perfetto: one row per thread, byte counter
            tracks); ``?format=summary`` returns the per-phase
            seconds/bytes/rows-per-s rollup instead — the shape
            ``bench.py --compare`` diffs (docs/profiling.md)."""
            from learningorchestra_tpu.telemetry import profile as _profile

            record = jobs.get(job_name)
            if record is None:
                return {"result": "not_found"}, 404
            if record.trace is None:
                return {"result": "no_trace"}, 404
            if request.args.get("format") == "summary":
                summary = _profile.trace_summary(record.trace)
                summary["job"] = record.as_dict()
                return {"result": summary}, 200
            return _profile.chrome_trace(record.trace), 200

    def register_job_routes(self, jobs) -> None:
        """The full job surface for a service holding a JobManager:

        - ``GET /jobs`` — every tracked job's state, class, priority,
          attempt count, timings, error, and correlation ID;
        - ``GET /jobs/<name>/trace`` — its correlated span tree;
        - ``DELETE /jobs/<name>`` — cooperative cancellation: a queued
          job terminates without running, a running one at its next
          cancel check (ml/builder.py's phase loop checks); 202 while
          the cancel propagates, 409 once the job is already terminal.
        """
        self.register_job_traces(jobs)

        @self.route("/jobs")
        def read_jobs(request):
            return {"result": jobs.all_jobs()}, 200

        @self.route("/jobs/<job_name>", methods=("DELETE",))
        def cancel_job(request, job_name):
            outcome = jobs.cancel(job_name)
            if outcome == "unknown":
                return {"result": "not_found"}, 404
            if outcome == "terminal":
                return {"result": "already_terminal"}, 409
            return {"result": "cancelling"}, 202

    def route(self, rule: str, methods: tuple[str, ...] = ("GET",)):
        def decorator(handler: Callable) -> Callable:
            endpoint = f"{handler.__name__}|{rule}|{'|'.join(methods)}"
            self.url_map.add(Rule(rule, endpoint=endpoint, methods=list(methods)))
            self._handlers[endpoint] = handler
            return handler

        return decorator

    def _dispatch(self, request: Request) -> Response:
        adapter = self.url_map.bind_to_environ(request.environ)
        try:
            endpoint, args = adapter.match()
            # the RULE (not the concrete path) labels request metrics, so
            # /files/<filename> is one series, not one per dataset
            request.environ["lo.route"] = endpoint.split("|")[1]
        except NotFound:
            return Response(
                json.dumps({"result": "not_found"}),
                mimetype="application/json",
                status=404,
            )
        except HTTPException as error:
            return error.get_response(request.environ)

        try:
            result = self._handlers[endpoint](request, **args)
        except HTTPException as error:
            # e.g. BadRequest from request.get_json() on a malformed
            # body — keep its real status code, don't convert to a 500.
            return error.get_response(request.environ)
        if isinstance(result, Response):
            return result
        if isinstance(result, tuple):
            payload, status = result
            if isinstance(payload, Response):
                payload.status_code = status
                return payload
            return Response(
                json.dumps(payload), mimetype="application/json", status=status
            )
        return Response(
            json.dumps(result), mimetype="application/json", status=200
        )

    def __call__(self, environ, start_response):
        request = Request(environ)
        # Correlation middleware: honour a caller-supplied ID (a client
        # stitching multi-service flows) or mint one; the request runs
        # under an active trace so spans anywhere below (job submit,
        # SPMD dispatch, PhaseTimer phases) correlate, and the ID echoes
        # back on the response.
        correlation_id = (
            request.headers.get(_tracing.CORRELATION_HEADER)
            or _tracing.mint_correlation_id()
        )
        trace = _tracing.Trace(
            correlation_id, name=f"{request.method} {request.path}"
        )
        self._in_flight.labels(self.name).inc()
        started = time.perf_counter()
        try:
            with _tracing.activate(trace), _tracing.span(
                f"http:{request.method} {request.path}"
            ):
                try:
                    response = self._dispatch(request)
                except Exception as error:  # mirror Flask's 500 text
                    response = Response(
                        f"{type(error).__name__}: {error}",
                        status=500,
                        mimetype="text/plain",
                    )
        finally:
            self._in_flight.labels(self.name).dec()
        route = environ.get("lo.route", "<unmatched>")
        self._requests_total.labels(
            self.name, route, request.method, response.status_code
        ).inc()
        self._request_seconds.labels(
            self.name, route, request.method
        ).observe(time.perf_counter() - started)
        response.headers[_tracing.CORRELATION_HEADER] = correlation_id
        return response(environ, start_response)

    def test_client(self) -> Client:
        return Client(self, Response)


class ServerThread:
    """Run a WSGI app on a background thread (integration tests, dev)."""

    def __init__(self, app: WebApp, host: str, port: int):
        self._server = make_server(host, port, app, threaded=True)
        self.host = host
        self.port = self._server.server_port
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name=f"{app.name}-server"
        )

    def start(self) -> "ServerThread":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._thread.join(timeout=5)


def run_app(app: WebApp, host: str, port: int) -> None:
    """Serve forever in the foreground (container entrypoint)."""
    make_server(host, port, app, threaded=True).serve_forever()
