"""Shared utilities: the micro web framework, env/config handling."""
