"""Event-driven serving core: one ``selectors`` loop, a bounded pool.

The reference serves every request on its own OS thread and tells
clients to poll ``finished`` every 3 seconds — at fleet scale that is
request amplification against a thread-per-request server, and every
idle waiter parks a whole thread. This module replaces the transport
layer with a reactor (stdlib only):

- one acceptor/reader loop owns every socket: it parses requests,
  holds idle keep-alive connections at near-zero marginal RSS, and
  streams responses back under write-readiness registration;
- a small bounded handler pool (``LO_WEB_HANDLERS``) runs the existing
  WSGI route functions unchanged — they block on store and device
  work, so they cannot run on the loop thread;
- a route that cannot answer yet returns a :class:`Waiter` instead of
  a response; the loop parks the CONNECTION (no thread) until the
  waiter is notified, times out, or its poll interval finds the
  answer. ``GET /jobs/<name>/wait`` and ``GET /wal?wait=`` both ride
  this;
- a route whose answer lives on ANOTHER server returns an
  :class:`Upstream`: the loop connects out non-blocking in the same
  selector, relays the request, and streams the reply back through the
  ordinary write-readiness machinery — fd + memcpy on the loop thread,
  failing over target-by-target on connection death or 5xx. The fleet
  router (serve/router.py) rides this.

The WSGI contract is untouched: ``utils/web.WebApp`` still serves
werkzeug's test client directly, and ``LO_WEB_ASYNC=0`` falls back to
the original threaded werkzeug server (docs/web.md).

Knob table (validated by deploy/run.sh's preflight):

====================  =======  ====================================
env var               default  meaning
====================  =======  ====================================
``LO_WEB_ASYNC``      1        1 = event-loop core, 0 = threaded
                               werkzeug server (escape hatch)
``LO_WEB_HANDLERS``   8        handler-pool width (blocking route
                               functions in flight at once)
``LO_WEB_MAX_CONNS``  10000    open-connection cap; past it new
                               connections get 503 + close
``LO_WEB_WAIT_CAP_S`` 60       ceiling on a ``/wait`` long-poll's
                               requested timeout
====================  =======  ====================================
"""

from __future__ import annotations

import collections
import io
import json
import os
import selectors
import socket
import sys
import threading
import time
import traceback
import urllib.parse
from concurrent.futures import ThreadPoolExecutor
from http.client import responses as _http_reasons
from typing import Any, Callable, Optional

from learningorchestra_tpu.sched.config import _float_env, _int_env
from learningorchestra_tpu.telemetry import metrics as _metrics

# ---------------------------------------------------------------------------
# Configuration


def web_async_enabled() -> bool:
    # lo: allow[LO305] this IS the validated accessor preflight calls
    raw = os.environ.get("LO_WEB_ASYNC", "").strip()
    if raw not in ("", "0", "1"):
        raise ValueError(f"LO_WEB_ASYNC must be 0 or 1, got {raw!r}")
    return raw != "0"


def handler_pool_size() -> int:
    return _int_env("LO_WEB_HANDLERS", 8)


def max_connections() -> int:
    return _int_env("LO_WEB_MAX_CONNS", 10_000)


def wait_cap_s() -> float:
    cap = _float_env("LO_WEB_WAIT_CAP_S", 60.0)
    if not cap > 0:
        raise ValueError(f"LO_WEB_WAIT_CAP_S must be > 0, got {cap}")
    return cap


def validate_env() -> dict:
    """Read every web knob (raising on malformed values) and return the
    resolved configuration — run.sh preflight and runner boot-print."""
    return {
        "LO_WEB_ASYNC": 1 if web_async_enabled() else 0,
        "LO_WEB_HANDLERS": handler_pool_size(),
        "LO_WEB_MAX_CONNS": max_connections(),
        "LO_WEB_WAIT_CAP_S": wait_cap_s(),
    }


# ---------------------------------------------------------------------------
# Waiter: a response that is not ready yet


class Waiter:
    """A parked response. A route handler returns one INSTEAD of a
    ``(payload, status)`` result when the answer is not ready:

    - ``poll()`` returns the handler-style ``(payload, status)`` once
      ready, else ``None``; it must be cheap — the event loop calls it
      on the loop thread;
    - ``notify()`` (thread-safe, idempotent — e.g. from a job's
      finalizer) marks the waiter possibly-ready and wakes whichever
      server holds it; a notify whose poll still answers ``None`` is
      spurious and the waiter stays parked;
    - after ``timeout_s`` with no result ``on_timeout()`` produces the
      response — a clean re-poll hint, never a hang;
    - ``interval_s`` re-polls sources with no push hook (the WAL feed)
      on that period;
    - ``sse=True`` frames the resolution as ``text/event-stream``.

    The threaded server resolves a waiter by blocking its request
    thread (reference-parity behaviour). The event loop parks the
    CONNECTION instead: no thread is held while the waiter pends.
    """

    __slots__ = (
        "poll", "timeout_s", "on_timeout", "interval_s", "sse",
        "notified_at", "on_complete", "correlation_id", "_event", "_wake",
    )

    def __init__(
        self,
        poll: Callable[[], Optional[tuple]],
        timeout_s: float,
        on_timeout: Callable[[], tuple],
        interval_s: Optional[float] = None,
        sse: bool = False,
    ):
        self.poll = poll
        self.timeout_s = max(float(timeout_s), 0.0)
        self.on_timeout = on_timeout
        self.interval_s = interval_s
        self.sse = bool(sse)
        # monotonic instant of the first (non-spurious) notify — the
        # start of the lo_web_notify_seconds measurement
        self.notified_at: Optional[float] = None
        # set by WebApp.__call__ on the async path: records the
        # request's metrics at resolution time
        self.on_complete: Optional[Callable[[int], None]] = None
        self.correlation_id: Optional[str] = None
        self._event = threading.Event()
        self._wake: Optional[Callable[[], None]] = None

    def notify(self) -> None:
        if self.notified_at is None:
            self.notified_at = time.monotonic()
        self._event.set()
        wake = self._wake
        if wake is not None:
            wake()

    def resolve_blocking(self) -> tuple[tuple, str]:
        """Threaded-server path: block THIS thread until ready or
        timeout. Returns ``(result, kind)``, kind in ``ready``/
        ``timeout``."""
        deadline = time.monotonic() + self.timeout_s
        while True:
            result = self.poll()
            if result is not None:
                return result, "ready"
            self.notified_at = None  # that notify (if any) was spurious
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return self.on_timeout(), "timeout"
            step = (
                remaining
                if self.interval_s is None
                else min(remaining, self.interval_s)
            )
            self._event.wait(step)
            self._event.clear()


# ---------------------------------------------------------------------------
# Upstream: a response that lives on another server

# end-to-end framing the proxy owns; everything else relays verbatim
_HOP_HEADERS = ("connection", "keep-alive", "content-length", "transfer-encoding")


def _relay_headers(headers: list) -> list:
    return [
        (key, value)
        for key, value in headers
        if key.lower() not in _HOP_HEADERS
    ]


def _parse_http_response(buf, eof: bool) -> Optional[tuple]:
    """One upstream HTTP/1.1 response out of ``buf``: ``None`` while
    incomplete, else ``(status, reason, headers, body)``. Raises
    ``ValueError`` on a reply the proxy cannot frame (bad status line,
    chunked body) — callers treat that as attempt failure. With no
    Content-Length the body is EOF-terminated (the proxy sends
    ``Connection: close``, so the peer's FIN frames it)."""
    head_end = buf.find(b"\r\n\r\n")
    if head_end < 0:
        if len(buf) > _MAX_HEADER_BYTES:
            raise ValueError("upstream response head too large")
        return None
    lines = bytes(buf[:head_end]).split(b"\r\n")
    parts = lines[0].split(None, 2)
    if len(parts) < 2 or not parts[0].startswith(b"HTTP/"):
        raise ValueError("bad upstream status line")
    status = int(parts[1])
    reason = parts[2].decode("latin-1") if len(parts) > 2 else ""
    headers = []
    length = None
    for line in lines[1:]:
        key, sep, value = line.partition(b":")
        if not sep:
            raise ValueError("bad upstream header")
        name = key.strip().decode("latin-1")
        text = value.strip().decode("latin-1")
        headers.append((name, text))
        lower = name.lower()
        if lower == "content-length":
            length = int(text)
        elif lower == "transfer-encoding" and "chunked" in text.lower():
            raise ValueError("chunked upstream body unsupported")
    body_start = head_end + 4
    if length is None:
        if not eof:
            return None
        body = bytes(buf[body_start:])
    else:
        if len(buf) - body_start < length:
            return None
        body = bytes(buf[body_start:body_start + length])
    return status, reason, headers, body


class Upstream:
    """A proxied response. A route handler returns one INSTEAD of a
    ``(payload, status)`` result when the answer lives on another
    server (the fleet router's predict path, serve/router.py):

    - ``targets`` is the ordered ``(host, port)`` failover list;
      ``raw_request`` the pre-serialized HTTP request to relay (built
      with ``Connection: close`` so the peer's FIN frames a
      length-less body);
    - a connection failure, torn/unparseable reply, per-attempt
      ``timeout_s``, or 5xx answer advances to the next target; the
      first non-5xx reply relays verbatim minus hop-by-hop headers;
    - with every target down the last 5xx seen relays (the real error
      beats a synthetic one), else ``on_exhausted()`` supplies the
      ``(payload, status)`` for a clean JSON 502;
    - ``on_attempt(index, target)`` observes every attempt start (the
      router counts ``index > 0`` as retries — it runs on the loop
      thread, keep it cheap); ``on_complete(status)`` is set by
      ``WebApp.__call__`` and records request metrics at relay time,
      exactly like :class:`Waiter`.

    The event loop drives the whole exchange on the loop thread — no
    proxy thread per request. The threaded server (and the test
    client) resolves with :meth:`resolve_blocking` instead.
    """

    __slots__ = (
        "targets", "raw_request", "timeout_s", "on_attempt",
        "on_exhausted", "on_complete", "correlation_id",
    )

    def __init__(
        self,
        targets,
        raw_request: bytes,
        timeout_s: float = 30.0,
        on_attempt: Optional[Callable[[int, tuple], None]] = None,
        on_exhausted: Optional[Callable[[], tuple]] = None,
    ):
        if not targets:
            raise ValueError("Upstream needs at least one target")
        self.targets = [(host, int(port)) for host, port in targets]
        self.raw_request = bytes(raw_request)
        self.timeout_s = float(timeout_s)
        self.on_attempt = on_attempt
        self.on_exhausted = on_exhausted or (
            lambda: ({"result": "bad_gateway"}, 502)
        )
        self.on_complete: Optional[Callable[[int], None]] = None
        self.correlation_id: Optional[str] = None

    def resolve_blocking(self) -> tuple[int, list, bytes]:
        """Threaded-server path: walk the targets with blocking sockets
        on THIS thread. Returns ``(status, headers, body)`` with
        hop-by-hop headers already stripped."""
        last_5xx = None
        for index, target in enumerate(self.targets):
            if self.on_attempt is not None:
                try:
                    self.on_attempt(index, target)
                except Exception:  # noqa: BLE001 — observer must not kill
                    traceback.print_exc()
            try:
                parsed = self._attempt_blocking(target)
            except (OSError, ValueError):
                continue
            status = parsed[0]
            if status >= 500:
                last_5xx = parsed
                continue
            break
        else:
            if last_5xx is None:
                payload, status = self.on_exhausted()
                body = json.dumps(payload).encode("utf-8")
                self._completed(status)
                return status, [("Content-Type", "application/json")], body
            parsed = last_5xx
        status, _reason, headers, body = parsed
        self._completed(status)
        return status, _relay_headers(headers), body

    def _completed(self, status: int) -> None:
        # parity with the loop path's relay-time callback (_proxy_relay)
        if self.on_complete is not None:
            try:
                self.on_complete(status)
            except Exception:  # noqa: BLE001
                traceback.print_exc()

    def _attempt_blocking(self, target) -> tuple:
        deadline = time.monotonic() + self.timeout_s
        with socket.create_connection(target, timeout=self.timeout_s) as sock:
            sock.sendall(self.raw_request)
            buf = bytearray()
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError("upstream attempt timed out")
                sock.settimeout(remaining)
                chunk = sock.recv(_READ_CHUNK)
                if not chunk:
                    parsed = _parse_http_response(buf, eof=True)
                    if parsed is None:
                        raise ConnectionError("upstream closed mid-response")
                    return parsed
                buf += chunk
                parsed = _parse_http_response(buf, eof=False)
                if parsed is not None:
                    return parsed


SSE_RETRY_MS = 3000
SSE_PREAMBLE = f"retry: {SSE_RETRY_MS}\n\n".encode("ascii")


def sse_frame(event: str, payload: Any) -> bytes:
    """One ``text/event-stream`` frame. Golden-tested: both servers must
    emit byte-identical framing."""
    return f"event: {event}\ndata: {json.dumps(payload)}\n\n".encode("utf-8")


def waiter_body(waiter: Waiter, result: tuple, kind: str) -> tuple[bytes, int, str]:
    """``(body, status, content_type)`` for a resolved waiter — shared
    by both servers so long-poll JSON and SSE framing match exactly."""
    payload, status = result
    if waiter.sse:
        event = "done" if kind == "ready" else "timeout"
        return SSE_PREAMBLE + sse_frame(event, payload), 200, "text/event-stream"
    return (
        json.dumps(payload).encode("utf-8"),
        status,
        "application/json",
    )


# ---------------------------------------------------------------------------
# The event loop server

_MAX_HEADER_BYTES = 65536
# pipelined bytes a client may buffer while its previous request is
# still being handled; past this the connection is abusive
_MAX_BUFFERED_BYTES = 64 * 1024 * 1024
_READ_CHUNK = 262144

_BUSY_BODY = json.dumps({"result": "server_busy"}).encode("utf-8")
_BUSY_RESPONSE = (
    b"HTTP/1.1 503 Service Unavailable\r\n"
    b"Content-Type: application/json\r\n"
    b"Content-Length: " + str(len(_BUSY_BODY)).encode("ascii") + b"\r\n"
    b"Retry-After: 1\r\nConnection: close\r\n\r\n" + _BUSY_BODY
)

# notify latency lives in the millisecond range DEFAULT_BUCKETS cannot
# resolve (same rationale as serve/batcher.LATENCY_BUCKETS)
_NOTIFY_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0,
)

# connection states. IDLE/PARKED cost no thread and count as "idle" in
# lo_web_connections; READING/HANDLING/WRITING are "active".
_IDLE = "idle"
_READING = "reading"
_HANDLING = "handling"
_WRITING = "writing"
_PARKED = "parked"
_IDLE_STATES = (_IDLE, _PARKED)


class _Conn:
    __slots__ = (
        "sock", "fd", "addr", "rbuf", "wbuf", "state", "keep_alive",
        "last_activity", "waiter", "deadline", "next_poll",
        "sse_streaming", "notify_pending_at", "mask", "close_after_write",
        "upstream",
    )

    def __init__(self, sock: socket.socket, addr):
        self.sock = sock
        self.fd = sock.fileno()
        self.addr = addr
        self.rbuf = bytearray()
        self.wbuf = bytearray()
        self.state = _IDLE
        self.keep_alive = True
        self.last_activity = time.monotonic()
        self.waiter: Optional[Waiter] = None
        self.deadline: Optional[float] = None
        self.next_poll: Optional[float] = None
        self.sse_streaming = False
        self.notify_pending_at: Optional[float] = None
        self.mask = 0
        self.close_after_write = False
        self.upstream: Optional["_UpstreamConn"] = None


class _UpstreamConn:
    """Loop-side state of one in-flight proxied request: the upstream
    socket currently being tried plus the client connection awaiting
    the relay. One instance survives failover — ``sock`` is replaced
    per attempt, ``index`` walks ``upstream.targets``."""

    __slots__ = (
        "client", "upstream", "index", "sock", "fd", "rbuf", "wbuf",
        "connected", "deadline", "mask", "last_5xx",
    )

    def __init__(self, client: _Conn, upstream: Upstream):
        self.client = client
        self.upstream = upstream
        self.index = 0
        self.sock: Optional[socket.socket] = None
        self.fd = -1
        self.rbuf = bytearray()
        self.wbuf = bytearray()
        self.connected = False
        self.deadline: Optional[float] = None
        self.mask = 0
        self.last_5xx: Optional[tuple] = None


def _raw_response(status_line: str, headers, body: bytes, keep_alive: bool) -> bytes:
    """Serialize a WSGI (status, headers, body) triple to HTTP/1.1."""
    out = [f"HTTP/1.1 {status_line}\r\n".encode("latin-1")]
    saw_length = False
    for key, value in headers:
        lower = key.lower()
        if lower == "connection":
            continue  # the loop owns connection lifecycle
        if lower == "content-length":
            saw_length = True
        out.append(f"{key}: {value}\r\n".encode("latin-1"))
    if not saw_length:
        out.append(f"Content-Length: {len(body)}\r\n".encode("latin-1"))
    out.append(
        b"Connection: keep-alive\r\n" if keep_alive else b"Connection: close\r\n"
    )
    out.append(b"\r\n")
    out.append(body)
    return b"".join(out)


def _status_line(status: int) -> str:
    return f"{status} {_http_reasons.get(status, 'Unknown')}"


class LoopServer:
    """Serve a WSGI app from one ``selectors`` loop plus a bounded
    handler pool. Constructor binds immediately (``port=0`` picks a
    free port, exposed as ``.port`` — ServerThread parity)."""

    def __init__(
        self,
        app,
        host: str,
        port: int,
        handlers: Optional[int] = None,
        max_conns: Optional[int] = None,
        header_timeout_s: float = 15.0,
        idle_timeout_s: Optional[float] = None,
    ):
        self._app = app
        self.host = host
        self._name = getattr(app, "name", "web")
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(1024)
        self._listener.setblocking(False)
        self.port = self._listener.getsockname()[1]
        self._max_conns = max_conns if max_conns is not None else max_connections()
        self._header_timeout_s = header_timeout_s
        self._idle_timeout_s = idle_timeout_s
        width = handlers if handlers is not None else handler_pool_size()
        self._pool = ThreadPoolExecutor(
            max_workers=width, thread_name_prefix=f"{self._name}-web-handler"
        )
        self._sel = selectors.DefaultSelector()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        # cross-thread command inbox; deque append/popleft are atomic
        # under the GIL, so no lock guards it by design
        self._commands: collections.deque = collections.deque()
        self._conns: dict[int, _Conn] = {}
        self._parked: set[_Conn] = set()
        self._upstreams: set[_UpstreamConn] = set()
        self._stopping = False
        self._stop_deadline = 0.0
        self._last_sweep = time.monotonic()
        self._stopped = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"{self._name}-webloop"
        )
        registry = getattr(app, "registry", None) or _metrics.global_registry()
        self._g_conns = registry.gauge(
            "lo_web_connections",
            "Open HTTP connections (idle = keep-alive or parked waiter)",
            labels=("service", "state"),
        )
        self._g_waiters = registry.gauge(
            "lo_web_waiters",
            "Long-poll/SSE waiters parked on the event loop",
            labels=("service",),
        )
        self._h_notify = registry.histogram(
            "lo_web_notify_seconds",
            "Waiter wake latency: done-event set to response bytes on wire",
            labels=("service",),
            buckets=_NOTIFY_BUCKETS,
        )
        self._refresh_gauges()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "LoopServer":
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self.start()
        self._thread.join()

    def stop(self) -> None:
        self._post(("stop", None))
        self._stopped.wait(timeout=5)
        self._pool.shutdown(wait=False)

    @property
    def waiter_count(self) -> int:
        return len(self._parked)

    # -- cross-thread commands --------------------------------------------

    def _post(self, command) -> None:
        self._commands.append(command)
        try:
            self._wake_w.send(b"\x00")
        except OSError:
            pass  # buffer full (loop already waking) or shut down

    # -- the loop ----------------------------------------------------------

    def _run(self) -> None:
        self._sel.register(self._listener, selectors.EVENT_READ, "listener")
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        try:
            while True:
                for key, mask in self._sel.select(self._next_timeout()):
                    if key.data == "listener":
                        self._accept()
                    elif key.data == "wake":
                        self._drain_wake()
                    elif isinstance(key.data, _UpstreamConn):
                        ups = key.data
                        if mask & selectors.EVENT_WRITE:
                            self._upstream_writable(ups)
                        if (
                            ups in self._upstreams
                            and mask & selectors.EVENT_READ
                        ):
                            self._upstream_readable(ups)
                    else:
                        conn = key.data
                        if mask & selectors.EVENT_READ:
                            self._on_readable(conn)
                        if (
                            self._conns.get(conn.fd) is conn
                            and mask & selectors.EVENT_WRITE
                        ):
                            self._on_writable(conn)
                self._drain_commands()
                self._service_timers()
                if self._stopping and self._drained():
                    break
        except Exception:  # noqa: BLE001 — the loop must not die silently
            traceback.print_exc()
        finally:
            for conn in list(self._conns.values()):
                self._close(conn)
            for sock in (self._listener, self._wake_r, self._wake_w):
                try:
                    sock.close()
                except OSError:
                    pass
            self._stopped.set()

    def _next_timeout(self) -> float:
        timeout = 0.05 if self._stopping else 1.0
        now = time.monotonic()
        for conn in self._parked:
            if conn.deadline is not None:
                timeout = min(timeout, max(conn.deadline - now, 0.0))
            if conn.next_poll is not None:
                timeout = min(timeout, max(conn.next_poll - now, 0.0))
        for ups in self._upstreams:
            if ups.deadline is not None:
                timeout = min(timeout, max(ups.deadline - now, 0.0))
        return timeout

    def _drain_wake(self) -> None:
        try:
            while self._wake_r.recv(4096):
                pass
        except OSError:
            pass  # BlockingIOError: drained

    def _drain_commands(self) -> None:
        while True:
            try:
                kind, payload = self._commands.popleft()
            except IndexError:
                return
            if kind == "stop":
                self._begin_stop()
            elif kind == "respond":
                conn, raw = payload
                if self._alive(conn):
                    conn.state = _WRITING
                    self._queue_write(conn, raw, close=not conn.keep_alive)
            elif kind == "park":
                conn, waiter = payload
                if self._alive(conn):
                    self._park(conn, waiter)
                else:
                    waiter._wake = None
            elif kind == "proxy":
                conn, upstream = payload
                if self._alive(conn):
                    self._proxy_start(conn, upstream)
            elif kind == "wake":
                conn = payload
                if self._alive(conn) and conn.state == _PARKED:
                    self._try_resolve(conn)

    def _alive(self, conn: _Conn) -> bool:
        return self._conns.get(conn.fd) is conn

    # -- accept / read / write --------------------------------------------

    def _accept(self) -> None:
        while True:
            try:
                sock, addr = self._listener.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            sock.setblocking(False)
            if len(self._conns) >= self._max_conns or self._stopping:
                try:
                    sock.send(_BUSY_RESPONSE)  # best-effort: tiny, fresh buffer
                except OSError:
                    pass
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            conn = _Conn(sock, addr)
            self._conns[conn.fd] = conn
            conn.mask = selectors.EVENT_READ
            self._sel.register(sock, conn.mask, conn)
            self._refresh_gauges()

    def _on_readable(self, conn: _Conn) -> None:
        try:
            data = conn.sock.recv(_READ_CHUNK)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._close(conn)
            return
        if not data:
            # peer hung up — a parked waiter dies with its connection
            self._close(conn)
            return
        conn.last_activity = time.monotonic()
        conn.rbuf += data
        if conn.state == _IDLE:
            conn.state = _READING
            self._refresh_gauges()
        if conn.state == _READING:
            self._advance_read(conn)
        elif len(conn.rbuf) > _MAX_BUFFERED_BYTES:
            self._close(conn)  # pipelining abuse while a request runs

    def _advance_read(self, conn: _Conn) -> None:
        head_end = conn.rbuf.find(b"\r\n\r\n")
        if head_end < 0:
            if len(conn.rbuf) > _MAX_HEADER_BYTES:
                self._respond_error(conn, 431, "header_too_large")
            return
        head = bytes(conn.rbuf[:head_end])
        lines = head.split(b"\r\n")
        parts = lines[0].split()
        if len(parts) != 3:
            self._respond_error(conn, 400, "bad_request_line")
            return
        method, target, version = parts
        headers: dict[bytes, bytes] = {}
        for line in lines[1:]:
            key, sep, value = line.partition(b":")
            if not sep:
                self._respond_error(conn, 400, "bad_header")
                return
            headers[key.strip().lower()] = value.strip()
        if b"chunked" in headers.get(b"transfer-encoding", b"").lower():
            self._respond_error(conn, 501, "chunked_request_unsupported")
            return
        try:
            length = int(headers.get(b"content-length", b"0") or b"0")
        except ValueError:
            self._respond_error(conn, 400, "bad_content_length")
            return
        body_start = head_end + 4
        if len(conn.rbuf) - body_start < length:
            if len(conn.rbuf) > _MAX_BUFFERED_BYTES:
                self._close(conn)
            return  # body still arriving
        body = bytes(conn.rbuf[body_start:body_start + length])
        del conn.rbuf[:body_start + length]
        connection = headers.get(b"connection", b"").lower()
        conn.keep_alive = (
            connection == b"keep-alive"
            if version == b"HTTP/1.0"
            else connection != b"close"
        )
        environ = self._build_environ(method, target, headers, body, conn)
        conn.state = _HANDLING
        self._refresh_gauges()
        self._pool.submit(self._handle, conn, environ)

    def _build_environ(
        self,
        method: bytes,
        target: bytes,
        headers: dict[bytes, bytes],
        body: bytes,
        conn: _Conn,
    ) -> dict:
        path, _, query = target.partition(b"?")
        environ = {
            "REQUEST_METHOD": method.decode("latin-1"),
            "SCRIPT_NAME": "",
            "PATH_INFO": urllib.parse.unquote_to_bytes(bytes(path)).decode(
                "latin-1"
            ),
            "QUERY_STRING": query.decode("latin-1"),
            "SERVER_NAME": self.host,
            "SERVER_PORT": str(self.port),
            "SERVER_PROTOCOL": "HTTP/1.1",
            "REMOTE_ADDR": conn.addr[0] if conn.addr else "",
            "CONTENT_LENGTH": str(len(body)),
            "wsgi.version": (1, 0),
            "wsgi.url_scheme": "http",
            "wsgi.input": io.BytesIO(body),
            "wsgi.errors": sys.stderr,
            "wsgi.multithread": True,
            "wsgi.multiprocess": False,
            "wsgi.run_once": False,
            # tells WebApp.__call__ a returned Waiter may park instead
            # of blocking this (pooled) thread
            "lo.async": True,
        }
        for key, value in headers.items():
            name = key.decode("latin-1").replace("-", "_").upper()
            text = value.decode("latin-1")
            if name == "CONTENT_TYPE":
                environ["CONTENT_TYPE"] = text
            elif name != "CONTENT_LENGTH":
                environ["HTTP_" + name] = text
        return environ

    def _handle(self, conn: _Conn, environ: dict) -> None:
        """Pool thread: run the WSGI app, then hand the outcome back to
        the loop — a serialized response or a waiter to park."""
        captured: dict[str, Any] = {}

        def start_response(status, headers, exc_info=None):
            captured["status"] = status
            captured["headers"] = headers
            return lambda chunk: None

        try:
            iterable = self._app(environ, start_response)
            waiter = environ.get("lo.waiter")
            if waiter is not None:
                if hasattr(iterable, "close"):
                    iterable.close()
                self._post(("park", (conn, waiter)))
                return
            upstream = environ.get("lo.upstream")
            if upstream is not None:
                if hasattr(iterable, "close"):
                    iterable.close()
                self._post(("proxy", (conn, upstream)))
                return
            try:
                body = b"".join(iterable)
            finally:
                if hasattr(iterable, "close"):
                    iterable.close()
            raw = _raw_response(
                captured["status"], captured["headers"], body, conn.keep_alive
            )
        except Exception:  # noqa: BLE001 — WSGI layer itself failed
            traceback.print_exc()
            body = json.dumps({"result": "internal_error"}).encode("utf-8")
            raw = _raw_response(
                "500 Internal Server Error",
                [("Content-Type", "application/json")],
                body,
                False,
            )
            conn.keep_alive = False
        self._post(("respond", (conn, raw)))

    def _queue_write(self, conn: _Conn, raw: bytes, close: bool) -> None:
        conn.wbuf += raw
        conn.close_after_write = conn.close_after_write or close
        self._refresh_gauges()
        self._on_writable(conn)  # opportunistic synchronous flush

    def _on_writable(self, conn: _Conn) -> None:
        sent_total = 0
        error = False
        if conn.wbuf:
            view = memoryview(conn.wbuf)
            try:
                while sent_total < len(view):
                    try:
                        sent = conn.sock.send(view[sent_total:])
                    except (BlockingIOError, InterruptedError):
                        break
                    except OSError:
                        error = True
                        break
                    if sent <= 0:
                        break
                    sent_total += sent
            finally:
                view.release()
            del conn.wbuf[:sent_total]
        if error:
            self._close(conn)
            return
        self._update_mask(conn)
        if conn.wbuf:
            return
        if conn.notify_pending_at is not None:
            self._h_notify.labels(self._name).observe(
                time.monotonic() - conn.notify_pending_at
            )
            conn.notify_pending_at = None
        if conn.close_after_write:
            self._close(conn)
            return
        if conn.state == _WRITING:
            conn.state = _IDLE
            conn.last_activity = time.monotonic()
            self._refresh_gauges()
            if conn.rbuf:
                # pipelined request already buffered: parse it now
                conn.state = _READING
                self._advance_read(conn)

    def _update_mask(self, conn: _Conn) -> None:
        mask = selectors.EVENT_READ
        if conn.wbuf:
            mask |= selectors.EVENT_WRITE
        if mask != conn.mask and self._alive(conn):
            conn.mask = mask
            try:
                self._sel.modify(conn.sock, mask, conn)
            except (KeyError, ValueError, OSError):
                pass

    def _respond_error(self, conn: _Conn, status: int, slug: str) -> None:
        body = json.dumps({"result": slug}).encode("utf-8")
        raw = _raw_response(
            _status_line(status),
            [("Content-Type", "application/json")],
            body,
            False,
        )
        conn.rbuf.clear()
        conn.state = _WRITING
        self._queue_write(conn, raw, close=True)

    # -- upstream proxying -------------------------------------------------

    def _proxy_start(self, conn: _Conn, upstream: Upstream) -> None:
        if conn.upstream is not None:  # defensive: one proxy per request
            self._abort_upstream(conn.upstream)
        ups = _UpstreamConn(conn, upstream)
        conn.upstream = ups
        self._upstreams.add(ups)
        self._proxy_attempt(ups)

    def _proxy_attempt(self, ups: _UpstreamConn) -> None:
        """Open a non-blocking connection to the current target and
        register it in the loop's selector; immediate failures advance
        the index without recursing."""
        while True:
            if ups.index >= len(ups.upstream.targets):
                self._proxy_exhausted(ups)
                return
            target = ups.upstream.targets[ups.index]
            if ups.upstream.on_attempt is not None:
                try:
                    ups.upstream.on_attempt(ups.index, target)
                except Exception:  # noqa: BLE001 — observer must not kill
                    traceback.print_exc()
            try:
                sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                sock.setblocking(False)
                sock.connect_ex(target)  # EINPROGRESS reports via SO_ERROR
            except OSError:
                ups.index += 1
                continue
            ups.sock = sock
            ups.fd = sock.fileno()
            ups.rbuf = bytearray()
            ups.wbuf = bytearray(ups.upstream.raw_request)
            ups.connected = False
            ups.deadline = time.monotonic() + ups.upstream.timeout_s
            ups.mask = selectors.EVENT_WRITE
            try:
                self._sel.register(sock, ups.mask, ups)
            except (KeyError, ValueError, OSError):
                try:
                    sock.close()
                except OSError:
                    pass
                ups.index += 1
                continue
            return

    def _upstream_writable(self, ups: _UpstreamConn) -> None:
        if ups.sock is None or ups not in self._upstreams:
            return
        if not ups.connected:
            try:
                error = ups.sock.getsockopt(
                    socket.SOL_SOCKET, socket.SO_ERROR
                )
            except OSError:
                error = 1
            if error:
                self._proxy_retry(ups)
                return
            ups.connected = True
        sent_total = 0
        failed = False
        if ups.wbuf:
            view = memoryview(ups.wbuf)
            try:
                while sent_total < len(view):
                    try:
                        sent = ups.sock.send(view[sent_total:])
                    except (BlockingIOError, InterruptedError):
                        break
                    except OSError:
                        failed = True
                        break
                    if sent <= 0:
                        break
                    sent_total += sent
            finally:
                view.release()
            del ups.wbuf[:sent_total]
        if failed:
            self._proxy_retry(ups)
            return
        mask = selectors.EVENT_READ
        if ups.wbuf:
            mask |= selectors.EVENT_WRITE
        if mask != ups.mask:
            ups.mask = mask
            try:
                self._sel.modify(ups.sock, mask, ups)
            except (KeyError, ValueError, OSError):
                pass

    def _upstream_readable(self, ups: _UpstreamConn) -> None:
        if ups.sock is None or not ups.connected:
            # stale event for a socket a failover just replaced
            return
        try:
            data = ups.sock.recv(_READ_CHUNK)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._proxy_retry(ups)
            return
        eof = not data
        if data:
            ups.rbuf += data
        try:
            parsed = _parse_http_response(ups.rbuf, eof=eof)
        except ValueError:
            self._proxy_retry(ups)
            return
        if parsed is None:
            if eof or len(ups.rbuf) > _MAX_BUFFERED_BYTES:
                self._proxy_retry(ups)  # torn or abusive reply
            return
        if parsed[0] >= 500:
            ups.last_5xx = parsed
            self._proxy_retry(ups)
            return
        self._proxy_relay(ups, parsed)

    def _proxy_retry(self, ups: _UpstreamConn) -> None:
        self._drop_upstream_socket(ups)
        ups.index += 1
        if not self._alive(ups.client):
            self._abort_upstream(ups)  # client left: nothing to answer
            return
        self._proxy_attempt(ups)

    def _proxy_exhausted(self, ups: _UpstreamConn) -> None:
        if ups.last_5xx is not None:
            # the real upstream error beats a synthetic 502
            self._proxy_relay(ups, ups.last_5xx)
            return
        payload, status = ups.upstream.on_exhausted()
        body = json.dumps(payload).encode("utf-8")
        self._proxy_relay(
            ups,
            (
                status,
                _http_reasons.get(status, "Unknown"),
                [("Content-Type", "application/json")],
                body,
            ),
        )

    def _proxy_relay(self, ups: _UpstreamConn, parsed: tuple) -> None:
        self._drop_upstream_socket(ups)
        self._upstreams.discard(ups)
        conn = ups.client
        if conn.upstream is ups:
            conn.upstream = None
        if not self._alive(conn):
            return
        status, reason, headers, body = parsed
        upstream = ups.upstream
        if upstream.on_complete is not None:
            try:
                upstream.on_complete(status)
            except Exception:  # noqa: BLE001
                traceback.print_exc()
        header_list = _relay_headers(headers)
        if upstream.correlation_id and not any(
            key.lower() == "x-correlation-id" for key, _ in header_list
        ):
            header_list.append(
                ("X-Correlation-ID", upstream.correlation_id)
            )
        raw = _raw_response(
            f"{status} {reason or _http_reasons.get(status, 'Unknown')}",
            header_list,
            body,
            conn.keep_alive,
        )
        conn.state = _WRITING
        self._queue_write(conn, raw, close=not conn.keep_alive)

    def _drop_upstream_socket(self, ups: _UpstreamConn) -> None:
        if ups.sock is None:
            return
        try:
            self._sel.unregister(ups.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            ups.sock.close()
        except OSError:
            pass
        ups.sock = None
        ups.connected = False
        ups.deadline = None

    def _abort_upstream(self, ups: _UpstreamConn) -> None:
        self._drop_upstream_socket(ups)
        self._upstreams.discard(ups)
        if ups.client is not None and ups.client.upstream is ups:
            ups.client.upstream = None

    # -- waiters -----------------------------------------------------------

    def _park(self, conn: _Conn, waiter: Waiter) -> None:
        # already-ready (e.g. already-terminal job): answer immediately,
        # never park
        result = waiter.poll()
        if result is not None:
            self._finish_waiter(conn, waiter, result, "ready")
            return
        now = time.monotonic()
        conn.waiter = waiter
        conn.deadline = now + waiter.timeout_s
        conn.next_poll = (
            now + waiter.interval_s if waiter.interval_s else None
        )
        waiter._wake = lambda: self._post(("wake", conn))
        conn.state = _PARKED
        self._parked.add(conn)
        self._refresh_gauges()
        if waiter.sse:
            self._queue_sse_head(conn, waiter)
        if waiter._event.is_set():
            # notify() fired between the handler's poll and this park
            self._try_resolve(conn)

    def _queue_sse_head(self, conn: _Conn, waiter: Waiter) -> None:
        """SSE parks with its headers + retry preamble already on the
        wire, so the client knows the stream is live."""
        headers = [
            b"HTTP/1.1 200 OK\r\n",
            b"Content-Type: text/event-stream\r\n",
            b"Cache-Control: no-cache\r\n",
            b"Connection: close\r\n",
        ]
        if waiter.correlation_id:
            headers.append(
                f"X-Correlation-ID: {waiter.correlation_id}\r\n".encode("latin-1")
            )
        headers.append(b"\r\n")
        conn.sse_streaming = True
        self._queue_write(conn, b"".join(headers) + SSE_PREAMBLE, close=False)

    def _try_resolve(self, conn: _Conn) -> None:
        waiter = conn.waiter
        if waiter is None:
            return
        waiter._event.clear()
        result = waiter.poll()
        if result is None:
            waiter.notified_at = None  # spurious notify: stay parked
            return
        self._finish_waiter(conn, waiter, result, "ready")

    def _finish_waiter(
        self, conn: _Conn, waiter: Waiter, result: tuple, kind: str
    ) -> None:
        waiter._wake = None
        if waiter.notified_at is not None:
            conn.notify_pending_at = waiter.notified_at
        self._parked.discard(conn)
        conn.waiter = None
        conn.deadline = None
        conn.next_poll = None
        if waiter.sse:
            status = 200
        else:
            status = result[1]
        if waiter.on_complete is not None:
            try:
                waiter.on_complete(status)
            except Exception:  # noqa: BLE001
                traceback.print_exc()
        if conn.sse_streaming:
            # headers + preamble already sent at park: final frame only
            conn.sse_streaming = False
            event = "done" if kind == "ready" else "timeout"
            conn.state = _WRITING
            self._queue_write(conn, sse_frame(event, result[0]), close=True)
            return
        body, status, content_type = waiter_body(waiter, result, kind)
        header_list = [("Content-Type", content_type)]
        if waiter.correlation_id:
            header_list.append(("X-Correlation-ID", waiter.correlation_id))
        close = waiter.sse or not conn.keep_alive
        raw = _raw_response(_status_line(status), header_list, body, not close)
        conn.state = _WRITING
        self._queue_write(conn, raw, close=close)

    # -- timers ------------------------------------------------------------

    def _service_timers(self) -> None:
        now = time.monotonic()
        if now - self._last_sweep >= 1.0:
            self._last_sweep = now
            for conn in list(self._conns.values()):
                stalled = now - conn.last_activity
                if (
                    conn.state == _READING
                    and stalled > self._header_timeout_s
                ):
                    # slow-loris: a partial request may not hold its
                    # buffer open indefinitely
                    self._respond_error(conn, 408, "request_timeout")
                elif (
                    conn.state == _IDLE
                    and self._idle_timeout_s is not None
                    and stalled > self._idle_timeout_s
                ):
                    self._close(conn)
        for conn in list(self._parked):
            waiter = conn.waiter
            if waiter is None:
                continue
            if conn.deadline is not None and now >= conn.deadline:
                self._finish_waiter(conn, waiter, waiter.on_timeout(), "timeout")
            elif conn.next_poll is not None and now >= conn.next_poll:
                conn.next_poll = now + (waiter.interval_s or 1.0)
                self._try_resolve(conn)
        for ups in list(self._upstreams):
            if ups.deadline is not None and now >= ups.deadline:
                self._proxy_retry(ups)  # stalled attempt: next target

    # -- shutdown ----------------------------------------------------------

    def _begin_stop(self) -> None:
        if self._stopping:
            return
        self._stopping = True
        self._stop_deadline = time.monotonic() + 2.0
        try:
            self._sel.unregister(self._listener)
        except (KeyError, ValueError):
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        # graceful drain: every parked waiter resolves with its timeout
        # response — no client is left hanging on a dead socket
        for conn in list(self._parked):
            waiter = conn.waiter
            if waiter is not None:
                self._finish_waiter(
                    conn, waiter, waiter.on_timeout(), "timeout"
                )

    def _drained(self) -> bool:
        if time.monotonic() >= self._stop_deadline:
            return True
        return not any(
            conn.wbuf or conn.state == _HANDLING
            for conn in self._conns.values()
        )

    # -- bookkeeping -------------------------------------------------------

    def _close(self, conn: _Conn) -> None:
        if self._conns.get(conn.fd) is not conn:
            return
        del self._conns[conn.fd]
        self._parked.discard(conn)
        if conn.waiter is not None:
            conn.waiter._wake = None
            conn.waiter = None
        if conn.upstream is not None:
            self._abort_upstream(conn.upstream)
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        self._refresh_gauges()

    def _refresh_gauges(self) -> None:
        idle = active = 0
        for conn in self._conns.values():
            if conn.state in _IDLE_STATES:
                idle += 1
            else:
                active += 1
        self._g_conns.labels(self._name, "idle").set(idle)
        self._g_conns.labels(self._name, "active").set(active)
        self._g_waiters.labels(self._name).set(len(self._parked))
