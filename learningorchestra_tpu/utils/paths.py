"""Filesystem-name safety shared by the ops and REST layers."""

from __future__ import annotations

import os


def safe_filename(name: str) -> bool:
    """A bare filename only — no separators or traversal components — so
    request-supplied names can never escape their volume."""
    return bool(name) and os.path.basename(name) == name and name not in (".", "..")
