"""The concurrency-hazard rule family (LO201–LO206).

Seventeen modules in this codebase hold ``threading.Lock`` / ``RLock`` /
``Condition`` state — scheduler queues, the device cache, the serving
registry and micro-batcher, replication/arbiter role state, telemetry
rings — and the review-hardening log of PRs 3–8 is a catalog of one bug
class found by eyeball: a checkpoint load blocking the registry lock, a
record/event/task publish torn across lock releases, a candidate's term
and self-vote computed under two lock acquisitions, a ``wait()``
snapshot read racing registration. These rules machine-check the same
invariants, RacerD-style (lockset reasoning, one module at a time):

- **LO201 lock-order** — a nested ``with`` acquisition graph per
  module: A-then-B somewhere and B-then-A elsewhere is a deadlock the
  moment both paths run concurrently; acquisitions of locks named in
  the declared :data:`LOCK_REGISTRY` must also respect its global
  ranks (the cross-module ordering a per-module analysis cannot see).
- **LO202 blocking-call-under-lock** — network I/O, ``time.sleep``,
  subprocess spawns, thread joins / executor shutdowns, unbounded
  waits, device syncs (``block_until_ready``), checkpoint loads, and
  store wire calls inside a held-lock scope stall every other thread
  parked on that lock (the "GET /models hangs behind a checkpoint
  load" shape fixed by hand in PR 7).
- **LO203 unguarded shared state** — lockset-lite inference: an
  attribute accessed under a class's lock somewhere but read/written
  bare elsewhere, with at least one write in the mix. The golden
  cases are the ``JobManager.wait()`` snapshot race and the
  ``store_token`` minting race, both found by hand in PRs 3–4.
  Methods named ``*_locked`` are treated as lock-held by convention
  (the codebase's existing ``_drop_locked`` / ``_evict_locked``
  idiom); ``__init__`` is exempt (construction precedes sharing).
- **LO204 condvar discipline** — ``Condition.wait`` must sit inside a
  predicate loop (a bare wait misses a notify that fired early and a
  spurious wakeup breaks it) and carry a timeout (a lost notify must
  degrade to a re-check, not a hang); ``notify``/``notify_all`` must
  run under the same lock's ``with``.
- **LO205 torn-publish** — the same guarded attribute mutated in two
  separate ``with``-blocks of one method: an observer acquiring the
  lock between them sees the half-published state (the
  ``_finalize``/DELETE race shape from PR 3).
- **LO206 unbounded/silent service I/O** — scoped to the HTTP edges
  (``client.py``, ``services/``, ``serve/``): a ``requests.*`` /
  ``urlopen`` call without ``timeout=`` parks a thread forever on a
  half-open connection (the exact hang the crash-resume drill
  produces by killing a server mid-request), and an
  ``except Exception: pass`` handler swallows the resulting failure
  so nobody ever learns the wait hung. Both defeat the robustness
  contract (docs/robustness.md), so both are flagged at the edge.

Like the LO1xx family the detectors are syntactic — one module at a
time, no cross-function dataflow — so every finding is explainable by
pointing at the flagged line. ``# lo: allow[LO2xx]`` suppresses a
deliberate occurrence in place (with a justifying comment); the
baseline workflow grandfathers the rest. docs/analysis.md has the
per-rule tables and the lock-registry contract.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional

from learningorchestra_tpu.analysis.core import Finding

# --------------------------------------------------------------------
# lock recognition
# --------------------------------------------------------------------

# A with-context expression is a lock scope when its final name part is
# lock-like: `self._lock`, `cls.cond`, `_GLOBAL_LOCK`, `role["lock"]`,
# `repl_cv`. Matching the TAIL only keeps `unlock()`/`blocked` out.
_LOCKISH_TAIL = re.compile(
    r"(?i)(?:^|_)(?:lock|mutex|cond|cv|condition)$"
)


def _dotted(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def _last_part(name: str) -> str:
    return name.rsplit(".", 1)[-1]


def lock_name(node: ast.AST) -> Optional[str]:
    """The normalized identity of a lock-like expression, or None.

    ``self._lock`` → ``"self._lock"``; ``role["lock"]`` →
    ``"role['lock']"``. Identity is textual: two methods writing
    ``with self._lock:`` mean the same lock within one class, which is
    exactly the per-module granularity these rules work at.
    """
    name = _dotted(node)
    if name is not None:
        return name if _LOCKISH_TAIL.search(_last_part(name)) else None
    if (
        isinstance(node, ast.Subscript)
        and isinstance(node.slice, ast.Constant)
        and isinstance(node.slice.value, str)
    ):
        key = node.slice.value
        base = _dotted(node.value)
        if base is not None and _LOCKISH_TAIL.search(key):
            return f"{base}[{key!r}]"
    return None


def _with_locks(stmt: ast.AST) -> list[str]:
    """Lock names acquired by a With statement (empty for non-With)."""
    if not isinstance(stmt, (ast.With, ast.AsyncWith)):
        return []
    names = []
    for item in stmt.items:
        name = lock_name(item.context_expr)
        if name is not None:
            names.append(name)
    return names


def _function_defs(tree: ast.Module) -> Iterator[ast.AST]:
    """Every def (and the module top level) as an independent walk
    root. Nested defs are visited as their own roots with an EMPTY
    lock context: a closure defined under a lock runs on its own
    schedule, not with the lock held."""
    yield tree
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _iter_scoped(
    body: list[ast.stmt], held: tuple[str, ...]
) -> Iterator[tuple[ast.stmt, tuple[str, ...]]]:
    """Yield ``(statement, locks_held)`` for every statement lexically
    inside ``body``, tracking ``with <lock>:`` scopes and pruning
    nested function/lambda bodies (deferred code)."""
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield stmt, held
        inner = held
        acquired = _with_locks(stmt)
        if acquired:
            inner = held + tuple(acquired)
        for block in (
            getattr(stmt, "body", None),
            getattr(stmt, "orelse", None),
            getattr(stmt, "finalbody", None),
        ):
            if isinstance(block, list) and block and isinstance(
                block[0], ast.stmt
            ):
                yield from _iter_scoped(block, inner)
        for handler in getattr(stmt, "handlers", []) or []:
            yield from _iter_scoped(handler.body, inner)
        for case in getattr(stmt, "cases", []) or []:
            yield from _iter_scoped(case.body, inner)


def _own_exprs(stmt: ast.stmt) -> Iterator[ast.AST]:
    """The statement's own expressions, without nested statements or
    def/lambda bodies. Statement nodes are pruned at EVERY level, not
    just the first: an ``except`` handler is not itself a statement,
    and descending through it would re-visit its body's statements
    with the wrong lock context."""
    stack = [
        child
        for child in ast.iter_child_nodes(stmt)
        if not isinstance(child, ast.stmt)
    ]
    while stack:
        node = stack.pop()
        if isinstance(
            node,
            (
                ast.stmt,
                ast.FunctionDef,
                ast.AsyncFunctionDef,
                ast.Lambda,
            ),
        ):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


# --------------------------------------------------------------------
# the declared cross-module lock registry (LO201)
# --------------------------------------------------------------------

# Global ranks for the process-wide module-level locks: LOWER rank
# locks are acquired FIRST (outermost). A module nesting two ranked
# locks against their ranks is flagged even when the module's own
# acquisition graph is (locally) acyclic — this is the only ordering
# evidence a per-module analysis can carry across module boundaries,
# so every call chain below is ordered outer→inner by construction:
#
#   builder trace capture (10) → chaos fault check (20) →
#   singleton construction (30–50) → telemetry rings/decls (60–70) →
#   metrics registry declaration (80, innermost: every subsystem's
#   get-or-create metric declaration lands here).
#
# Keys are (module path suffix, lock name as written); the suffix is
# matched against the analyzed file's posix path. Adding a module-level
# lock? Register it at the rank matching what it may call into —
# docs/analysis.md ("The lock registry") walks the tiers.
LOCK_REGISTRY: dict[tuple[str, str], int] = {
    ("ml/builder.py", "_TRACE_LOCK"): 10,
    ("testing/faults.py", "_LOCK"): 20,
    ("core/jobs.py", "_MANAGER_LOCK"): 30,
    ("core/store.py", "_GLOBAL_LOCK"): 30,
    ("serve/__init__.py", "_GLOBAL_LOCK"): 30,
    ("core/devcache.py", "_GLOBAL_LOCK"): 40,
    ("core/devcache.py", "_TOKEN_LOCK"): 50,
    ("native/loader.py", "_lock"): 50,
    ("telemetry/tracing.py", "_RECENT_LOCK"): 60,
    ("serve/batcher.py", "_METRICS_LOCK"): 70,
    ("serve/registry.py", "_METRICS_LOCK"): 70,
    ("telemetry/profile.py", "_METRICS_LOCK"): 70,
    ("telemetry/metrics.py", "_GLOBAL_LOCK"): 80,
}


def _registry_rank(path: str, lock: str) -> Optional[int]:
    normalized = path.replace("\\", "/")
    for (suffix, name), rank in LOCK_REGISTRY.items():
        if name == lock and normalized.endswith(suffix):
            return rank
    return None


def check_lo201(tree: ast.Module, path: str) -> Iterator[Finding]:
    """Lock-order: nested acquisitions build a per-module graph; a
    cycle (A→B and B→A) deadlocks the first time both paths run
    concurrently. Self-nesting of one name is flagged too (fatal
    unless the lock is an RLock — suppress in place if so), and
    ranked registry locks must nest outer→inner."""
    # edge (outer, inner) → first line it was seen at
    edges: dict[tuple[str, str], int] = {}
    for func in _function_defs(tree):
        for stmt, held in _iter_scoped(getattr(func, "body", []), ()):
            acquired = _with_locks(stmt)
            if not acquired:
                continue
            chain = list(held)
            for inner in acquired:
                for outer in chain:
                    if outer == inner:
                        yield Finding(
                            "",
                            stmt.lineno,
                            "LO201",
                            f"`{inner}` is acquired while already "
                            "held — self-deadlock unless it is an "
                            "RLock (if so, suppress in place with a "
                            "comment saying which)",
                        )
                        continue
                    edges.setdefault((outer, inner), stmt.lineno)
                    outer_rank = _registry_rank(path, outer)
                    inner_rank = _registry_rank(path, inner)
                    if (
                        outer_rank is not None
                        and inner_rank is not None
                        and outer_rank > inner_rank
                    ):
                        yield Finding(
                            "",
                            stmt.lineno,
                            "LO201",
                            f"`{inner}` (registry rank {inner_rank}) "
                            f"acquired under `{outer}` (rank "
                            f"{outer_rank}) — violates the declared "
                            "lock-registry order "
                            "(analysis/concurrency.py LOCK_REGISTRY)",
                        )
                chain.append(inner)
    for (outer, inner), line in sorted(
        edges.items(), key=lambda item: item[1]
    ):
        if (inner, outer) in edges and outer < inner:
            other = edges[(inner, outer)]
            yield Finding(
                "",
                max(line, other),
                "LO201",
                f"inconsistent lock order: `{outer}` → `{inner}` and "
                f"`{inner}` → `{outer}` both occur in this module — "
                "two threads taking opposite paths deadlock",
            )


# --------------------------------------------------------------------
# LO202 — blocking calls under a held lock
# --------------------------------------------------------------------

# Dotted call names that block the calling thread for unbounded or
# wall-clock time. Everything parked on the held lock stalls with it.
BLOCKING_CALLS: dict[str, str] = {
    "time.sleep": "sleeps on the wall clock",
    "urllib.request.urlopen": "performs network I/O",
    "urlopen": "performs network I/O",
    "requests.get": "performs network I/O",
    "requests.post": "performs network I/O",
    "requests.put": "performs network I/O",
    "requests.delete": "performs network I/O",
    "requests.head": "performs network I/O",
    "requests.request": "performs network I/O",
    "socket.create_connection": "performs network I/O",
    "subprocess.run": "waits on a subprocess",
    "subprocess.call": "waits on a subprocess",
    "subprocess.check_call": "waits on a subprocess",
    "subprocess.check_output": "waits on a subprocess",
    "subprocess.Popen": "spawns a subprocess",
    "os.system": "waits on a subprocess",
    "os.popen": "waits on a subprocess",
    "jax.block_until_ready": "synchronizes the device queue",
    "block_until_ready": "synchronizes the device queue",
    "jax.device_get": "synchronizes the device queue",
    "pickle.load": "loads an artifact from disk",
    "np.load": "loads an artifact from disk",
    "numpy.load": "loads an artifact from disk",
    "load_model": "loads a checkpoint (disk + H2D transfer)",
    "load_checkpoint": "loads a checkpoint (disk + H2D transfer)",
}

# Method tails that block regardless of receiver: thread/pool joins and
# future results are waits on OTHER threads' progress — under a lock
# those threads may need, that is the textbook lock-held deadlock.
# ``join`` is handled separately (a thread join only when the receiver
# looks like a thread/pool — ``", ".join`` and ``os.path.join`` are
# string/path operations).
BLOCKING_METHOD_TAILS: dict[str, str] = {
    "shutdown": "waits for an executor's threads",
    "stop": "stops (typically joins) a worker",
    "result": "blocks on a future",
    "block_until_ready": "synchronizes the device queue",
}

_THREADY_RECEIVER = re.compile(r"(?i)thread|worker|pool|proc")

# Store wire methods: on a RemoteStore each is an HTTP round trip (and
# mid-failover, a retry loop bounded only by LO_FAILOVER_TIMEOUT_S).
# Receiver `self`/`cls` is exempt — the in-memory store's internal
# re-entrant calls under its own RLock are its design.
STORE_METHOD_TAILS = {
    "insert_one",
    "insert_many",
    "insert_columns",
    "insert_column_arrays",
    "update_one",
    "set_column",
    "set_field_values",
    "read_columns",
    "read_column_arrays",
    "read_column_arrays_rev",
    "wal_feed",
    "resync_apply",
    "apply_replicated",
    "create_collection",
    "aggregate",
}


def _call_blocks(call: ast.Call, held: tuple[str, ...]) -> Optional[str]:
    name = _dotted(call.func)
    if name is not None:
        if name in BLOCKING_CALLS:
            return f"{name}() {BLOCKING_CALLS[name]}"
        last = _last_part(name)
        if last in BLOCKING_CALLS and last == name:
            return f"{name}() {BLOCKING_CALLS[last]}"
    if isinstance(call.func, ast.Attribute):
        tail = call.func.attr
        receiver = _dotted(call.func.value) or ""
        receiver_root = receiver.split(".", 1)[0]
        if tail in BLOCKING_METHOD_TAILS:
            return f".{tail}() {BLOCKING_METHOD_TAILS[tail]}"
        if tail == "join" and _THREADY_RECEIVER.search(
            _last_part(receiver)
        ):
            return (
                f"{receiver}.join() joins a thread (unbounded without "
                "a timeout argument)"
            )
        if tail in STORE_METHOD_TAILS and receiver_root not in (
            "self",
            "cls",
            "",
        ):
            return (
                f"{receiver}.{tail}() is a store call — an HTTP round "
                "trip on a RemoteStore backend"
            )
        if (
            tail == "get"
            and "queue" in _last_part(receiver).lower()
            or tail == "get"
            and "inbox" in _last_part(receiver).lower()
        ):
            if not call.args and not any(
                kw.arg == "timeout" for kw in call.keywords
            ):
                return (
                    f"{receiver}.get() without a timeout parks this "
                    "thread until a producer shows up"
                )
        if tail == "wait":
            # waiting on the HELD lock's own condition is the condvar
            # idiom (wait releases it — LO204's domain); waiting on
            # anything ELSE while holding a lock is a stall, flagged
            # only when unbounded (no timeout argument).
            if receiver not in held and not call.args and not call.keywords:
                return (
                    f"{receiver}.wait() with no timeout parks this "
                    "thread indefinitely"
                )
    return None


def check_lo202(tree: ast.Module, path: str) -> Iterator[Finding]:
    del path
    seen: set[tuple[int, str]] = set()
    for func in _function_defs(tree):
        for stmt, held in _iter_scoped(getattr(func, "body", []), ()):
            locks = held + tuple(_with_locks(stmt))
            if not locks:
                continue
            for node in _own_exprs(stmt):
                if not isinstance(node, ast.Call):
                    continue
                reason = _call_blocks(node, locks)
                if reason is None:
                    continue
                key = (node.lineno, reason)
                if key in seen:
                    continue
                seen.add(key)
                yield Finding(
                    "",
                    node.lineno,
                    "LO202",
                    f"{reason} while holding `{locks[-1]}` — every "
                    "thread parked on that lock stalls with it "
                    "(move the slow work outside the lock scope)",
                )


# --------------------------------------------------------------------
# LO203 — unguarded shared state (lockset-lite)
# --------------------------------------------------------------------

# Method-call tails that mutate their receiver in place.
MUTATING_TAILS = {
    "pop",
    "popitem",
    "popleft",
    "append",
    "appendleft",
    "extend",
    "insert",
    "remove",
    "discard",
    "add",
    "clear",
    "update",
    "setdefault",
}


class _Access:
    __slots__ = ("attr", "line", "method", "locked", "write")

    def __init__(self, attr, line, method, locked, write):
        self.attr = attr
        self.line = line
        self.method = method
        self.locked = locked
        self.write = write


def _self_attr(node: ast.AST) -> Optional[str]:
    """``X`` for a direct ``self.X`` attribute node."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _collect_accesses(method: ast.FunctionDef) -> Iterator[_Access]:
    convention_locked = method.name.endswith("_locked")
    for stmt, held in _iter_scoped(method.body, ()):
        locked = convention_locked or bool(held) or bool(_with_locks(stmt))
        writes: dict[int, str] = {}  # id(attr node) → attr, for targets
        targets: list[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            targets = [stmt.target]
        elif isinstance(stmt, ast.Delete):
            targets = list(stmt.targets)
        for target in targets:
            # the written attribute: `self.X = ...`, `self.X += ...`,
            # `self.X[k] = ...` (container mutation), `del self.X`,
            # and tuple-unpacked combinations thereof
            for node in ast.walk(target):
                attr = _self_attr(node)
                if attr is not None:
                    writes[id(node)] = attr
        for node in _own_exprs(stmt):
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ):
                if node.func.attr in MUTATING_TAILS:
                    attr = _self_attr(node.func.value)
                    if attr is not None:
                        writes[id(node.func.value)] = attr
        emitted: set[tuple[str, bool]] = set()
        for node in _own_exprs(stmt):
            attr = _self_attr(node)
            if attr is None:
                continue
            write = id(node) in writes
            key = (attr, write)
            if key in emitted:
                continue
            emitted.add(key)
            yield _Access(attr, node.lineno, method.name, locked, write)


def check_lo203(tree: ast.Module, path: str) -> Iterator[Finding]:
    del path
    for klass in ast.walk(tree):
        if not isinstance(klass, ast.ClassDef):
            continue
        accesses: list[_Access] = []
        for item in klass.body:
            if not isinstance(
                item, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            if item.name == "__init__":
                continue  # construction precedes sharing
            accesses.extend(_collect_accesses(item))
        by_attr: dict[str, list[_Access]] = {}
        for access in accesses:
            # the lock attributes themselves are synchronization, not
            # shared data; queues/events carry their own locking
            if _LOCKISH_TAIL.search(access.attr):
                continue
            by_attr.setdefault(access.attr, []).append(access)
        for attr, attr_accesses in sorted(by_attr.items()):
            locked = [a for a in attr_accesses if a.locked]
            bare = [a for a in attr_accesses if not a.locked]
            if not locked or not bare:
                continue
            if not any(a.write for a in attr_accesses):
                continue  # read-only everywhere: immutable config
            reported: set[str] = set()
            for access in sorted(bare, key=lambda a: a.line):
                if access.method in reported:
                    continue
                reported.add(access.method)
                guarded_in = sorted(
                    {a.method for a in locked if a.write}
                ) or sorted({a.method for a in locked})
                kind = "written" if access.write else "read"
                yield Finding(
                    "",
                    access.line,
                    "LO203",
                    f"`self.{attr}` is {kind} without the lock that "
                    f"guards it in {', '.join(guarded_in)}() — a "
                    "concurrent holder sees (or produces) a torn "
                    "value; snapshot/mutate it under the lock",
                )


# --------------------------------------------------------------------
# LO204 — condition-variable discipline
# --------------------------------------------------------------------


def check_lo204(tree: ast.Module, path: str) -> Iterator[Finding]:
    del path
    for func in _function_defs(tree):
        body = getattr(func, "body", [])
        yield from _lo204_walk(body, held=(), loops=0)


def _lo204_walk(
    body: list[ast.stmt], held: tuple[str, ...], loops: int
) -> Iterator[Finding]:
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        inner_held = held + tuple(_with_locks(stmt))
        inner_loops = loops + (1 if isinstance(stmt, (ast.While, ast.For)) else 0)
        for node in _own_exprs(stmt):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
            ):
                continue
            receiver = _dotted(node.func.value)
            if receiver is None:
                continue
            tail = node.func.attr
            if tail == "wait" and receiver in held:
                # a wait on the condition whose `with` we are inside
                if loops == 0:
                    yield Finding(
                        "",
                        node.lineno,
                        "LO204",
                        f"`{receiver}.wait()` outside a predicate "
                        "loop — a notify that fired early is missed "
                        "forever and a spurious wakeup proceeds on a "
                        "false predicate; use `while not <pred>: "
                        f"{receiver}.wait(timeout)`",
                    )
                elif not node.args and not node.keywords:
                    yield Finding(
                        "",
                        node.lineno,
                        "LO204",
                        f"`{receiver}.wait()` without a timeout — a "
                        "lost notify (worker died mid-critical-"
                        "section, shutdown raced the wait) parks "
                        "this thread forever; pass a timeout and let "
                        "the predicate loop re-check",
                    )
            elif tail in ("notify", "notify_all"):
                if (
                    lock_name(node.func.value) is not None
                    and receiver not in inner_held
                ):
                    yield Finding(
                        "",
                        node.lineno,
                        "LO204",
                        f"`{receiver}.{tail}()` outside `with "
                        f"{receiver}:` — notify without the lock "
                        "races the waiter's predicate check "
                        "(RuntimeError at best, a lost wakeup at "
                        "worst)",
                    )
        for block in (
            getattr(stmt, "body", None),
            getattr(stmt, "orelse", None),
            getattr(stmt, "finalbody", None),
        ):
            if isinstance(block, list) and block and isinstance(
                block[0], ast.stmt
            ):
                yield from _lo204_walk(block, inner_held, inner_loops)
        for handler in getattr(stmt, "handlers", []) or []:
            yield from _lo204_walk(handler.body, inner_held, inner_loops)
        for case in getattr(stmt, "cases", []) or []:
            yield from _lo204_walk(case.body, inner_held, inner_loops)


# --------------------------------------------------------------------
# LO205 — torn publish across separate lock scopes
# --------------------------------------------------------------------


def _mutated_attrs_under(
    with_stmt: ast.With, lock: str
) -> set[str]:
    """Self-attributes mutated lexically inside ``with_stmt``'s body
    (not inside nested withs of OTHER locks — those publish under a
    different guard — and not inside nested defs)."""
    mutated: set[str] = set()
    for stmt, held in _iter_scoped(with_stmt.body, (lock,)):
        if held != (lock,):
            continue
        targets: list[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            targets = [stmt.target]
        elif isinstance(stmt, ast.Delete):
            targets = list(stmt.targets)
        for target in targets:
            for node in ast.walk(target):
                attr = _self_attr(node)
                if attr is not None:
                    mutated.add(attr)
        for node in _own_exprs(stmt):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in MUTATING_TAILS
            ):
                attr = _self_attr(node.func.value)
                if attr is not None:
                    mutated.add(attr)
    return mutated


def check_lo205(tree: ast.Module, path: str) -> Iterator[Finding]:
    del path
    for func in _function_defs(tree):
        if isinstance(func, ast.Module):
            continue
        # every with-block of each lock, in source order
        blocks: dict[str, list[tuple[ast.With, set[str]]]] = {}
        for stmt, _held in _iter_scoped(getattr(func, "body", []), ()):
            if not isinstance(stmt, ast.With):
                continue
            for lock in _with_locks(stmt):
                blocks.setdefault(lock, []).append(
                    (stmt, _mutated_attrs_under(stmt, lock))
                )
        for lock, lock_blocks in blocks.items():
            if len(lock_blocks) < 2:
                continue
            published: set[str] = set()
            reported: set[str] = set()
            for stmt, mutated in lock_blocks:
                torn = sorted(
                    attr
                    for attr in mutated
                    if attr in published and attr not in reported
                )
                reported.update(torn)
                if torn:
                    names = ", ".join(f"self.{attr}" for attr in torn)
                    # no line numbers in the message: baseline and
                    # --changed keys are line-number-free by contract,
                    # and an embedded lineno would resurrect
                    # grandfathered findings on unrelated line shifts
                    yield Finding(
                        "",
                        stmt.lineno,
                        "LO205",
                        f"{names} mutated under `{lock}` here AND in "
                        "an earlier lock scope of the same method — a "
                        "thread acquiring the lock between the two "
                        "blocks observes the half-published state; "
                        "publish related mutations in ONE scope",
                    )
                published.update(mutated)


# --------------------------------------------------------------------
# LO206 — unbounded or silently-swallowed service I/O
# --------------------------------------------------------------------

# PATH-gated to the HTTP edges of the system: the client library, the
# Flask services, and the serving plane. Everything else (tests, the
# analyzer itself) talks to in-process objects.
_LO206_HTTP_TAILS = {
    "get",
    "post",
    "put",
    "patch",
    "delete",
    "head",
    "options",
    "request",
}


def _lo206_in_scope(path: str) -> bool:
    normalized = "/" + path.replace("\\", "/")
    return (
        "/services/" in normalized
        or "/serve/" in normalized
        or normalized.endswith("/client.py")
    )


def _lo206_swallows(handler: ast.ExceptHandler) -> Optional[str]:
    """The caught-type name when ``handler`` is a broad catch whose
    body does nothing (``pass`` / ``...``), else None."""
    if handler.type is None:
        caught = "bare except"
    elif isinstance(handler.type, ast.Name) and handler.type.id in (
        "Exception",
        "BaseException",
    ):
        caught = f"except {handler.type.id}"
    else:
        return None
    body = handler.body
    if len(body) == 1 and (
        isinstance(body[0], ast.Pass)
        or (
            isinstance(body[0], ast.Expr)
            and isinstance(body[0].value, ast.Constant)
            and body[0].value.value is Ellipsis
        )
    ):
        return caught
    return None


def check_lo206(tree: ast.Module, path: str) -> Iterator[Finding]:
    """Unbounded HTTP waits and silent broad catches on the service
    edges. A ``requests.*``/``urlopen`` call with no ``timeout=``
    blocks until the kernel gives up on a half-open peer (hours); a
    ``pass``-bodied broad except then hides that it ever happened."""
    if not _lo206_in_scope(path):
        return
    seen: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = _dotted(node.func) or ""
            root = name.split(".", 1)[0]
            tail = _last_part(name)
            is_http = (
                root == "requests" and tail in _LO206_HTTP_TAILS
            ) or tail == "urlopen"
            if (
                is_http
                and not any(kw.arg == "timeout" for kw in node.keywords)
                and node.lineno not in seen
            ):
                seen.add(node.lineno)
                yield Finding(
                    "",
                    node.lineno,
                    "LO206",
                    f"`{name}()` without `timeout=` — a half-open "
                    "connection (peer killed mid-request) parks this "
                    "thread forever; every service/client HTTP call "
                    "must bound its wait",
                )
        elif isinstance(node, ast.ExceptHandler):
            caught = _lo206_swallows(node)
            if caught is not None and node.lineno not in seen:
                seen.add(node.lineno)
                yield Finding(
                    "",
                    node.lineno,
                    "LO206",
                    f"`{caught}: pass` on a service edge swallows "
                    "every failure silently — log it "
                    "(traceback.print_exc()) or narrow the catch; an "
                    "edge that eats errors cannot be operated",
                )


# --------------------------------------------------------------------
# registry
# --------------------------------------------------------------------

CONCURRENCY_RULES = {
    "LO201": (
        check_lo201,
        "inconsistent or registry-violating lock acquisition order",
    ),
    "LO202": (check_lo202, "blocking call inside a held-lock scope"),
    "LO203": (
        check_lo203,
        "shared attribute accessed both with and without its lock",
    ),
    "LO204": (
        check_lo204,
        "Condition.wait/notify outside the predicate-loop discipline",
    ),
    "LO205": (
        check_lo205,
        "guarded attribute mutation torn across separate lock scopes",
    ),
    "LO206": (
        check_lo206,
        "untimed HTTP call or silent broad except on a service edge",
    ),
}
