"""Project-wide extraction pass: the cross-artifact contract registry.

The LO1xx/LO2xx rules see one module at a time; the deployment
contract (ISSUE: LO301-LO306, contracts.py) is a property of the whole
tree plus its non-Python artifacts — the bash preflight, the cluster
manifest plumbing, the docs tables. This module walks everything ONCE
and builds plain-data registries the contract rules then compare:

- every ``LO_*`` env name read in Python (``learningorchestra_tpu/``
  and ``deploy/*.py``), with its reading module, line, enclosing
  function, and whether the read flows through a config helper
  (``_int_env``-style call, or a ``validate_*``/``*_env`` function);
- every knob validated by ``deploy/run.sh``'s preflight, parsed from
  the bash: the embedded ``python - <<'EOF'`` heredoc is valid Python,
  so explicit ``LO_*`` string constants are read off its AST, and
  validator calls (``config.host_width()``, ``webloop.validate_env()``)
  resolve to knob sets through a per-module, per-function transitive
  env-read map built from the same walk;
- every manifest key -> env pair plumbed by ``deploy/cluster.py``'s
  ``_*_KNOBS`` maps;
- every ``lo_*`` metric family declared against the telemetry registry
  (attribute calls, local ``_counter``-style wrappers, and f-string
  names expanded through literal comprehension tuples);
- every ``lo_*`` metric row in ``docs/observability.md`` (with the
  catalog's ``\\`lo_x_hits\\` / \\`_misses\\``` suffix shorthand
  expanded), every ``LO_*`` knob-table row across ``docs/*.md``, and
  every fault-table row (point + ``LO_FAULT_*`` env pair);
- every ``FAULT_POINTS`` entry in ``testing/faults.py``.

Stdlib only, like the rest of the analysis package: the registry READS
the tree, it never imports it.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

# Directories (under the project root) whose Python participates in the
# deployment contract. tests/ and learning_orchestra_client/ are
# deliberately out: a knob only a test reads is a test fixture, not a
# deployment surface.
PY_SCOPE = ("learningorchestra_tpu", "deploy")

_ENV_NAME_RE = re.compile(r"^LO_[A-Z0-9_]+$")
_DOC_KNOB_ROW_RE = re.compile(r"\s*\|\s*`(LO_[A-Z0-9_*]+)")
_DOC_FAULT_ROW_RE = re.compile(
    r"\|\s*`([a-z][a-z0-9_.]*)`\s*\|\s*`(LO_FAULT_[A-Z0-9_]+)`"
)
_DOC_METRIC_CELL_RE = re.compile(
    r"\s*\|\s*((?:`[a-z0-9_]+`)(?:\s*/\s*`[a-z0-9_]+`)*)\s*\|"
)
_METRIC_FACTORIES = {"counter", "gauge", "histogram"}


@dataclass(frozen=True)
class EnvRead:
    """One ``LO_*`` env read site in Python."""

    name: str  # the env var
    path: str  # project-root-relative, '/'-separated
    line: int
    function: str  # innermost enclosing def name; "" at module level
    direct: bool  # True = os.environ/getenv; False = *_env helper call

    @property
    def via_helper(self) -> bool:
        """Does the read flow through a config helper — either a
        ``_int_env``-style call, or code inside a ``validate_*`` /
        ``*_env`` function (the validated-accessor pattern)?"""
        if not self.direct:
            return True
        return self.function.startswith("validate_") or self.function.endswith(
            "_env"
        )


@dataclass(frozen=True)
class ManifestKnob:
    """One env var plumbed by a ``deploy/cluster.py`` ``_*_KNOBS`` map."""

    env: str
    manifest_key: str  # "" for tuple-style (env-name-keyed) knob lists
    path: str
    line: int


@dataclass(frozen=True)
class MetricDecl:
    """One ``lo_*`` metric family declaration site."""

    name: str
    kind: str  # counter | gauge | histogram
    path: str
    line: int


@dataclass(frozen=True)
class DocRow:
    """One table row in docs/ naming a metric, knob, or fault point."""

    name: str
    path: str
    line: int


@dataclass
class ProjectRegistry:
    """Everything the LO30x parity rules compare, from one tree walk."""

    root: str
    env_reads: dict[str, list[EnvRead]] = field(default_factory=dict)
    # knob -> run.sh line; explicit = LO_* string constants in the
    # heredoc, resolved = knobs reached through validator calls
    validated_explicit: dict[str, int] = field(default_factory=dict)
    validated_resolved: dict[str, int] = field(default_factory=dict)
    run_sh: str = ""  # root-relative path, "" when absent
    manifest_knobs: list[ManifestKnob] = field(default_factory=list)
    metrics: dict[str, MetricDecl] = field(default_factory=dict)
    doc_metrics: dict[str, DocRow] = field(default_factory=dict)
    doc_knobs: dict[str, list[DocRow]] = field(default_factory=dict)
    doc_faults: dict[str, DocRow] = field(default_factory=dict)  # by env
    fault_points: dict[str, int] = field(default_factory=dict)
    fault_points_path: str = ""
    problems: list[str] = field(default_factory=list)

    @property
    def validated(self) -> dict[str, int]:
        merged = dict(self.validated_resolved)
        merged.update(self.validated_explicit)
        return merged


def is_project_root(path: str) -> bool:
    """A directory with the three artifacts the contract rules need."""
    return (
        os.path.isfile(os.path.join(path, "deploy", "run.sh"))
        and os.path.isdir(os.path.join(path, "learningorchestra_tpu"))
        and os.path.isdir(os.path.join(path, "docs"))
    )


def find_project_root(path: str) -> str | None:
    """Walk ``path`` and its ancestors for the project root; None when
    the analyzed tree is not a deployment-contract project (a lone
    module, a fixture dir) — the LO30x pass then just doesn't run."""
    probe = os.path.abspath(path)
    if os.path.isfile(probe):
        probe = os.path.dirname(probe)
    while True:
        if is_project_root(probe):
            return probe
        parent = os.path.dirname(probe)
        if parent == probe:
            return None
        probe = parent


# --------------------------------------------------------------------
# Python walk: env reads + per-module function knob maps + metrics
# --------------------------------------------------------------------


def _iter_scope_files(root: str):
    from learningorchestra_tpu.analysis.core import iter_python_files

    scope = [
        os.path.join(root, part)
        for part in PY_SCOPE
        if os.path.exists(os.path.join(root, part))
    ]
    yield from iter_python_files(scope)


def _dotted(node: ast.AST) -> str | None:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


class _ModuleScan(ast.NodeVisitor):
    """One module's env reads, call graph, and metric declarations."""

    def __init__(self, rel_path: str):
        self.rel_path = rel_path
        self.stack: list[str] = []
        self.reads: list[EnvRead] = []
        # function name -> {knobs read directly inside it}
        self.func_knobs: dict[str, set[str]] = {}
        # function name -> {same-module function names it calls}
        self.calls: dict[str, set[str]] = {}
        self.defined: set[str] = set()
        self.metrics: list[MetricDecl] = []
        self._tree: ast.Module | None = None

    # -- structure ----------------------------------------------------

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if not self.stack:
            self.defined.add(node.name)
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def _func(self) -> str:
        return self.stack[-1] if self.stack else ""

    def _record(self, name: str, line: int, direct: bool) -> None:
        read = EnvRead(name, self.rel_path, line, self._func(), direct)
        self.reads.append(read)
        self.func_knobs.setdefault(self._func(), set()).add(name)

    # -- env reads ----------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        func_name = _dotted(node.func) or ""
        last = func_name.rsplit(".", 1)[-1]
        arg0 = node.args[0] if node.args else None
        arg0_env = (
            arg0.value
            if isinstance(arg0, ast.Constant)
            and isinstance(arg0.value, str)
            and _ENV_NAME_RE.match(arg0.value)
            else None
        )
        if arg0_env is not None:
            base = ""
            if isinstance(node.func, ast.Attribute):
                base = _dotted(node.func.value) or ""
            if last == "getenv" or (
                base.endswith("environ")
                and last in ("get", "pop", "setdefault")
            ):
                self._record(arg0_env, node.lineno, direct=True)
            elif last != "getenv" and last.endswith("_env"):
                # _int_env("LO_X", ...) — the config-helper pattern
                self._record(arg0_env, node.lineno, direct=False)
        # call graph (same-module Name calls only — enough to resolve
        # validate_all()-style validators to their accessors)
        if isinstance(node.func, ast.Name):
            self.calls.setdefault(self._func(), set()).add(node.func.id)
        # metric declarations: registry.counter("lo_..."), a local
        # _counter("lo_...") wrapper, global_registry().counter(...),
        # or an f-string name expanded through a literal comprehension
        # tuple (core/devcache.py). The attr is read off the node, not
        # the dotted chain — a chain rooted at a call has no dotted name
        attr = ""
        if isinstance(node.func, ast.Attribute):
            attr = node.func.attr
        elif isinstance(node.func, ast.Name):
            attr = node.func.id
        if attr.lstrip("_") in _METRIC_FACTORIES and arg0 is not None:
            kind = attr.lstrip("_")
            if (
                isinstance(arg0, ast.Constant)
                and isinstance(arg0.value, str)
                and arg0.value.startswith("lo_")
            ):
                self.metrics.append(
                    MetricDecl(arg0.value, kind, self.rel_path, node.lineno)
                )
            elif isinstance(arg0, ast.JoinedStr):
                for name in self._expand_fstring(arg0):
                    if name.startswith("lo_"):
                        self.metrics.append(
                            MetricDecl(name, kind, self.rel_path, node.lineno)
                        )
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        base = _dotted(node.value) or ""
        key = node.slice
        if (
            base.endswith("environ")
            and isinstance(key, ast.Constant)
            and isinstance(key.value, str)
            and _ENV_NAME_RE.match(key.value)
        ):
            self._record(key.value, node.lineno, direct=True)
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare) -> None:
        # "LO_X" in os.environ — a presence check is a read
        if (
            len(node.ops) == 1
            and isinstance(node.ops[0], (ast.In, ast.NotIn))
            and isinstance(node.left, ast.Constant)
            and isinstance(node.left.value, str)
            and _ENV_NAME_RE.match(node.left.value)
            and (_dotted(node.comparators[0]) or "").endswith("environ")
        ):
            self._record(node.left.value, node.lineno, direct=True)
        self.generic_visit(node)

    # -- f-string metric names ----------------------------------------

    def _expand_fstring(self, joined: ast.JoinedStr) -> list[str]:
        """``f"lo_devcache_{name}"`` -> one name per value ``name``
        takes in a literal comprehension iterable in this module. Only
        all-Name placeholders with literal-tuple generators expand;
        anything dynamic yields nothing (and the declared-vs-documented
        rule surfaces the gap instead of guessing)."""
        parts: list[list[str]] = []
        for value in joined.values:
            if isinstance(value, ast.Constant) and isinstance(
                value.value, str
            ):
                parts.append([value.value])
            elif isinstance(value, ast.FormattedValue) and isinstance(
                value.value, ast.Name
            ):
                candidates = self._comprehension_values(value.value.id)
                if not candidates:
                    return []
                parts.append(sorted(candidates))
            else:
                return []
        names = [""]
        for options in parts:
            names = [prefix + opt for prefix in names for opt in options]
        return names

    def _comprehension_values(self, var: str) -> set[str]:
        values: set[str] = set()
        assert self._tree is not None
        for node in ast.walk(self._tree):
            if not isinstance(
                node, (ast.DictComp, ast.ListComp, ast.SetComp, ast.GeneratorExp)
            ):
                continue
            for gen in node.generators:
                position = None
                if isinstance(gen.target, ast.Name) and gen.target.id == var:
                    position = -1  # bare element
                elif isinstance(gen.target, ast.Tuple):
                    for index, elt in enumerate(gen.target.elts):
                        if isinstance(elt, ast.Name) and elt.id == var:
                            position = index
                if position is None or not isinstance(
                    gen.iter, (ast.Tuple, ast.List)
                ):
                    continue
                for elt in gen.iter.elts:
                    if position == -1 and isinstance(elt, ast.Constant):
                        if isinstance(elt.value, str):
                            values.add(elt.value)
                    elif (
                        position >= 0
                        and isinstance(elt, (ast.Tuple, ast.List))
                        and len(elt.elts) > position
                        and isinstance(elt.elts[position], ast.Constant)
                        and isinstance(elt.elts[position].value, str)
                    ):
                        values.add(elt.elts[position].value)
        return values

    # -- closure ------------------------------------------------------

    def knob_closure(self) -> dict[str, set[str]]:
        """function -> every knob its (same-module-transitive) body
        reads; how ``serve_config.validate_all()`` in the run.sh
        heredoc resolves to the full serving knob set."""
        closed = {name: set(knobs) for name, knobs in self.func_knobs.items()}
        changed = True
        while changed:
            changed = False
            for caller, callees in self.calls.items():
                bucket = closed.setdefault(caller, set())
                before = len(bucket)
                for callee in callees:
                    bucket |= closed.get(callee, set())
                if len(bucket) != before:
                    changed = True
        return closed


def _scan_module(abs_path: str, rel_path: str) -> _ModuleScan | None:
    try:
        with open(abs_path, encoding="utf-8") as handle:
            source = handle.read()
        tree = ast.parse(source, filename=rel_path)
    except (OSError, UnicodeDecodeError, SyntaxError):
        return None  # per-file rules already report these as LO000
    scan = _ModuleScan(rel_path)
    scan._tree = tree
    scan.visit(tree)
    return scan


# --------------------------------------------------------------------
# deploy/run.sh preflight
# --------------------------------------------------------------------


def _parse_run_sh(
    root: str, module_knobs: dict[str, dict[str, set[str]]]
) -> tuple[dict[str, int], dict[str, int], list[str]]:
    """(explicit, resolved, problems) — knobs the preflight validates,
    each with its run.sh line. ``module_knobs`` maps dotted module
    names to that module's function->knobs closure."""
    path = os.path.join(root, "deploy", "run.sh")
    with open(path, encoding="utf-8") as handle:
        lines = handle.read().splitlines()
    heredoc_start = heredoc_end = None
    for index, line in enumerate(lines):
        if heredoc_start is None and re.match(r"python\d?\s+-\s+<<", line):
            heredoc_start = index + 1
        elif heredoc_start is not None and line.strip() == "EOF":
            heredoc_end = index
            break
    if heredoc_start is None or heredoc_end is None:
        return {}, {}, ["deploy/run.sh: no python heredoc preflight found"]
    source = "\n".join(lines[heredoc_start:heredoc_end])
    try:
        tree = ast.parse(source)
    except SyntaxError as error:
        return {}, {}, [f"deploy/run.sh: preflight heredoc: {error.msg}"]

    def sh_line(node: ast.AST) -> int:
        return heredoc_start + getattr(node, "lineno", 1)

    aliases: dict[str, str] = {}
    explicit: dict[str, int] = {}
    resolved: dict[str, int] = {}
    problems: list[str] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module:
            for name in node.names:
                aliases[name.asname or name.name] = (
                    f"{node.module}.{name.name}"
                )
        elif isinstance(node, ast.Import):
            for name in node.names:
                aliases[name.asname or name.name] = name.name
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            if _ENV_NAME_RE.match(node.value):
                explicit.setdefault(node.value, sh_line(node))
        elif isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ):
            base = _dotted(node.func.value)
            module = aliases.get(base or "")
            if module is None:
                continue
            knobs = module_knobs.get(module, {}).get(node.func.attr)
            if knobs is None:
                if module.startswith("learningorchestra_tpu"):
                    problems.append(
                        f"deploy/run.sh: preflight calls {base}."
                        f"{node.func.attr}() but {module} defines no such "
                        "validator"
                    )
                continue
            for knob in knobs:
                resolved.setdefault(knob, sh_line(node))
    return explicit, resolved, problems


# --------------------------------------------------------------------
# deploy/cluster.py manifest plumbing
# --------------------------------------------------------------------


def _parse_manifest_knobs(root: str) -> list[ManifestKnob]:
    rel = "deploy/cluster.py"
    path = os.path.join(root, rel)
    if not os.path.isfile(path):
        return []
    try:
        tree = ast.parse(open(path, encoding="utf-8").read(), filename=rel)
    except (OSError, SyntaxError):
        return []
    knobs: list[ManifestKnob] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        targets = [
            t.id
            for t in node.targets
            if isinstance(t, ast.Name) and t.id.endswith("_KNOBS")
        ]
        if not targets:
            continue
        value = node.value
        if isinstance(value, ast.Dict):
            for key, val in zip(value.keys, value.values):
                if (
                    isinstance(key, ast.Constant)
                    and isinstance(val, ast.Constant)
                    and isinstance(val.value, str)
                    and _ENV_NAME_RE.match(val.value)
                ):
                    knobs.append(
                        ManifestKnob(
                            val.value, str(key.value), rel, val.lineno
                        )
                    )
        elif isinstance(value, (ast.Tuple, ast.List)):
            for elt in value.elts:
                if (
                    isinstance(elt, ast.Constant)
                    and isinstance(elt.value, str)
                    and _ENV_NAME_RE.match(elt.value)
                ):
                    knobs.append(ManifestKnob(elt.value, "", rel, elt.lineno))
    return knobs


# --------------------------------------------------------------------
# docs tables
# --------------------------------------------------------------------


def _expand_metric_shorthand(names: list[str]) -> list[str]:
    """``["lo_serve_registry_hits_total", "_misses_total"]`` — the
    observability catalog's row shorthand — expands each ``_suffix`` by
    replacing the same number of trailing segments of the first full
    name."""
    if not names:
        return []
    expanded = [names[0]]
    head_segments = names[0].split("_")
    for name in names[1:]:
        if name.startswith("_"):
            suffix_segments = name.lstrip("_").split("_")
            expanded.append(
                "_".join(
                    head_segments[: -len(suffix_segments)] + suffix_segments
                )
            )
        else:
            expanded.append(name)
    return expanded


def _parse_docs(
    root: str,
) -> tuple[
    dict[str, DocRow], dict[str, list[DocRow]], dict[str, DocRow]
]:
    doc_metrics: dict[str, DocRow] = {}
    doc_knobs: dict[str, list[DocRow]] = {}
    doc_faults: dict[str, DocRow] = {}
    docs_dir = os.path.join(root, "docs")
    for entry in sorted(os.listdir(docs_dir)):
        if not entry.endswith(".md"):
            continue
        rel = f"docs/{entry}"
        try:
            lines = open(
                os.path.join(docs_dir, entry), encoding="utf-8"
            ).read().splitlines()
        except (OSError, UnicodeDecodeError):
            continue
        for lineno, line in enumerate(lines, 1):
            knob_match = _DOC_KNOB_ROW_RE.match(line)
            if knob_match:
                doc_knobs.setdefault(knob_match.group(1), []).append(
                    DocRow(knob_match.group(1), rel, lineno)
                )
            fault_match = _DOC_FAULT_ROW_RE.search(line)
            if fault_match:
                doc_faults.setdefault(
                    fault_match.group(2),
                    DocRow(fault_match.group(2), rel, lineno),
                )
            if entry == "observability.md":
                cell_match = _DOC_METRIC_CELL_RE.match(line)
                if cell_match:
                    raw = re.findall(r"`([a-z0-9_]+)`", cell_match.group(1))
                    if raw and raw[0].startswith("lo_"):
                        for name in _expand_metric_shorthand(raw):
                            doc_metrics.setdefault(
                                name, DocRow(name, rel, lineno)
                            )
    return doc_metrics, doc_knobs, doc_faults


# --------------------------------------------------------------------
# testing/faults.py
# --------------------------------------------------------------------


def _parse_fault_points(root: str) -> tuple[dict[str, int], str]:
    rel = "learningorchestra_tpu/testing/faults.py"
    path = os.path.join(root, rel)
    if not os.path.isfile(path):
        return {}, ""
    try:
        tree = ast.parse(open(path, encoding="utf-8").read(), filename=rel)
    except (OSError, SyntaxError):
        return {}, rel
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == "FAULT_POINTS"
                for t in node.targets
            )
            and isinstance(node.value, ast.Dict)
        ):
            return {
                key.value: key.lineno
                for key in node.value.keys
                if isinstance(key, ast.Constant)
                and isinstance(key.value, str)
            }, rel
    return {}, rel


def fault_env_name(point: str) -> str:
    """``store.wire.mutate`` -> ``LO_FAULT_STORE_WIRE_MUTATE`` — the
    same mapping ``testing/faults.py`` applies."""
    return "LO_FAULT_" + point.upper().replace(".", "_")


# --------------------------------------------------------------------
# the one entry point
# --------------------------------------------------------------------


def build_registry(root: str) -> ProjectRegistry:
    """Walk the project once; every extraction failure lands in
    ``registry.problems`` (surfaced as LO000 by the driver) instead of
    raising — a half-parsed tree must degrade to fewer checks, not an
    analyzer crash."""
    root = os.path.abspath(root)
    registry = ProjectRegistry(root=root)

    module_knobs: dict[str, dict[str, set[str]]] = {}
    for abs_path in _iter_scope_files(root):
        rel = os.path.relpath(os.path.abspath(abs_path), root).replace(
            os.sep, "/"
        )
        scan = _scan_module(abs_path, rel)
        if scan is None:
            continue
        for read in scan.reads:
            registry.env_reads.setdefault(read.name, []).append(read)
        module = rel[:-3].replace("/", ".")
        module_knobs[module] = scan.knob_closure()
        if rel.startswith("learningorchestra_tpu/"):
            for decl in scan.metrics:
                registry.metrics.setdefault(decl.name, decl)
    for reads in registry.env_reads.values():
        reads.sort(key=lambda r: (r.path, r.line))

    run_sh = os.path.join(root, "deploy", "run.sh")
    if os.path.isfile(run_sh):
        registry.run_sh = "deploy/run.sh"
        try:
            explicit, resolved, problems = _parse_run_sh(root, module_knobs)
            registry.validated_explicit = explicit
            registry.validated_resolved = resolved
            registry.problems.extend(problems)
        except (OSError, UnicodeDecodeError) as error:
            registry.problems.append(f"deploy/run.sh: {error}")

    registry.manifest_knobs = _parse_manifest_knobs(root)
    (
        registry.doc_metrics,
        registry.doc_knobs,
        registry.doc_faults,
    ) = _parse_docs(root)
    registry.fault_points, registry.fault_points_path = _parse_fault_points(
        root
    )
    return registry
