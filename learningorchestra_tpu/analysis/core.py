"""Analyzer core: findings, suppressions, and file orchestration.

A finding is one violated SPMD-safety invariant at one source location.
The rule implementations (rules.py) yield findings; this module owns
everything around them — walking trees of files, attaching the
``# lo: allow[LOxxx]`` inline-suppression escape hatch, and rendering
``file:line: LOxxx message`` output lines.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field, replace
from typing import Iterable, Iterator

# `# lo: allow[LO101]`, `# lo: allow[LO101,LO103]`, `# lo: allow[*]` —
# on the flagged line (or the line above it, for long expressions).
_ALLOW_RE = re.compile(r"#\s*lo:\s*allow\[([A-Z0-9*,\s]+)\]")

SYNTAX_RULE = "LO000"


@dataclass(frozen=True)
class Finding:
    """One rule violation: ``path:line: rule message``."""

    path: str
    line: int
    rule: str
    message: str
    baselined: bool = field(default=False, compare=False)

    def render(self) -> str:
        suffix = "  (baselined)" if self.baselined else ""
        return f"{self.path}:{self.line}: {self.rule} {self.message}{suffix}"

    def baseline_key(self, root: str | None = None) -> str:
        """Line-number-free identity used by the baseline file, so
        unrelated edits that shift a grandfathered finding do not make
        it look new. ``root`` (the baseline file's directory) anchors
        the path so the key is identical no matter what CWD or path
        spelling the analyzer ran with."""
        path = self.path
        if root and path != "<string>":
            path = os.path.relpath(os.path.abspath(path), root)
            path = path.replace(os.sep, "/")
        return f"{path}: {self.rule} {self.message}"


def _allowed_rules(source_line: str) -> set[str]:
    match = _ALLOW_RE.search(source_line)
    if not match:
        return set()
    return {token.strip() for token in match.group(1).split(",")}


def suppressed(finding: Finding, source_lines: list[str]) -> bool:
    """True when the finding's line (or the one above) carries an
    ``# lo: allow[...]`` comment naming the rule (or ``*``)."""
    for lineno in (finding.line, finding.line - 1):
        if 1 <= lineno <= len(source_lines):
            allowed = _allowed_rules(source_lines[lineno - 1])
            if finding.rule in allowed or "*" in allowed:
                return True
    return False


def analyze_source(
    source: str, path: str = "<string>", select: set[str] | None = None
) -> list[Finding]:
    """Run every rule over one module's source. ``select`` restricts to
    a subset of rule ids (prefix match, so "LO101" and "LO1" both
    work)."""
    from learningorchestra_tpu.analysis import rules

    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as error:
        return [
            Finding(
                path,
                error.lineno or 1,
                SYNTAX_RULE,
                f"syntax error: {error.msg}",
            )
        ]
    source_lines = source.splitlines()
    findings = [
        replace(finding, path=path)
        for finding in rules.run_rules(tree, path)
    ]
    if select is not None:
        findings = [
            finding
            for finding in findings
            if any(finding.rule.startswith(rule) for rule in select)
            or finding.rule == SYNTAX_RULE
        ]
    return [
        finding
        for finding in findings
        if not suppressed(finding, source_lines)
    ]


_SKIP_DIRS = {"__pycache__", "build", "dist", "node_modules", "venv"}


def _skip_dir(name: str) -> bool:
    # hidden dirs cover .git/.venv/.tox/...; the rest are vendored or
    # generated code a directory walk must not lint (a site-packages
    # false positive would fail the deploy preflight on third-party
    # code). Name such a directory explicitly to analyze it anyway.
    return (
        name.startswith(".")
        or name.endswith(".egg-info")
        or name in _SKIP_DIRS
    )


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    """Expand files/directories into a sorted, deterministic module
    list (sorted so baseline diffs and CLI output are stable). Each
    file is yielded once even when the given paths overlap — a
    duplicate would double-report its findings, and the second copy
    of a baselined finding would surface as spuriously NEW."""
    seen: set[str] = set()
    for path in paths:
        if os.path.isdir(path):
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(d for d in dirs if not _skip_dir(d))
                for name in sorted(files):
                    if name.endswith(".py"):
                        file_path = os.path.join(root, name)
                        if os.path.realpath(file_path) not in seen:
                            seen.add(os.path.realpath(file_path))
                            yield file_path
        elif os.path.isfile(path):
            # explicitly named files are analyzed regardless of suffix
            # (extensionless scripts, generated files) — silently
            # skipping them would print "clean" for a run that checked
            # nothing
            if os.path.realpath(path) not in seen:
                seen.add(os.path.realpath(path))
                yield path


def analyze_paths(
    paths: Iterable[str], select: set[str] | None = None
) -> list[Finding]:
    findings: list[Finding] = []
    for file_path in iter_python_files(paths):
        try:
            with open(file_path, encoding="utf-8") as handle:
                source = handle.read()
        except UnicodeDecodeError as error:
            # a finding, not a crash — like the SyntaxError path, so
            # the gate names the file at fault instead of dying
            findings.append(
                Finding(
                    os.path.relpath(file_path),
                    1,
                    SYNTAX_RULE,
                    f"not valid UTF-8: {error.reason}",
                )
            )
            continue
        except OSError as error:
            # dangling symlink, permission-restricted file — same
            # treatment, so warn-only mode can still downgrade it
            findings.append(
                Finding(
                    os.path.relpath(file_path),
                    1,
                    SYNTAX_RULE,
                    f"unreadable: {error.strerror or error}",
                )
            )
            continue
        findings.extend(
            analyze_source(source, os.path.relpath(file_path), select)
        )
    return findings
