"""SPMD-safety static analyzer.

The runtime's correctness invariants — coordinator-only host effects,
byte-identical broadcast payloads, sync-free jitted code — are
conventions the type system cannot see and the runtime only reports as
a poisoned mesh (``SpmdTimeoutError`` → supervisor restart). This
package machine-checks them at the AST level, before anything runs:

- ``LO101`` collective/device dispatch under a process-divergent guard
- ``LO102`` nondeterministic value flowing into a broadcast payload
- ``LO103`` host sync hidden inside jit-compiled code
- ``LO104`` float64 dtype in device code

plus the concurrency-hazard family over the threaded serving stack
(``analysis/concurrency.py``; RacerD-style lockset reasoning, one
module at a time):

- ``LO201`` inconsistent / registry-violating lock acquisition order
- ``LO202`` blocking call (network, sleep, join, device sync, store
  wire) inside a held-lock scope
- ``LO203`` attribute accessed both with and without its lock
- ``LO204`` Condition.wait/notify outside the predicate-loop discipline
- ``LO205`` guarded mutation torn across separate lock scopes

CLI: ``python -m learningorchestra_tpu.analysis [paths...]`` (see
``--help``; docs/analysis.md walks through each rule and the baseline
workflow). Library: :func:`analyze_source` / :func:`analyze_paths`.

Pure stdlib — importing this package never imports jax, so the gate
runs in constrained CI images and inside deploy/run.sh preflight.
"""

from learningorchestra_tpu.analysis.core import (
    Finding,
    analyze_paths,
    analyze_source,
)
from learningorchestra_tpu.analysis.rules import RULES

__all__ = ["Finding", "analyze_paths", "analyze_source", "RULES"]
