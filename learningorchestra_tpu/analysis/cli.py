"""CLI: ``python -m learningorchestra_tpu.analysis [paths...]``.

Exit codes: 0 = clean (or every finding baselined / warn-only mode),
1 = new findings, 2 = usage error. ``LO_ANALYSIS_WARN=1`` (or
``--warn-only``) downgrades failures to warnings — the emergency
escape hatch deploy/run.sh honours so a hotfix can ship while the
finding is triaged.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from dataclasses import replace

from learningorchestra_tpu.analysis.baseline import (
    apply_baseline,
    baseline_root,
    load_baseline,
    write_baseline,
)
from learningorchestra_tpu.analysis.core import analyze_paths
from learningorchestra_tpu.analysis.rules import RULES

DEFAULT_BASELINE = "analysis-baseline.txt"


def _build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m learningorchestra_tpu.analysis",
        description=(
            "SPMD-safety analyzer: collective deadlocks (LO101), "
            "broadcast nondeterminism (LO102), trace-unsafe host syncs "
            "(LO103), float64 in device code (LO104) — plus the "
            "concurrency-hazard family: lock order (LO201), blocking "
            "calls under locks (LO202), unguarded shared state "
            "(LO203), condvar discipline (LO204), torn publishes "
            "(LO205) — plus the deployment-contract family "
            "(LO301-LO306): knob/preflight/manifest/metric/fault-table "
            "parity across deploy/run.sh, deploy/cluster.py, the "
            "telemetry registry, and the docs tables."
        ),
    )
    parser.add_argument(
        "paths",
        nargs="*",
        default=["."],
        help="files or directories to analyze (default: .)",
    )
    parser.add_argument(
        "--baseline",
        default=None,
        metavar="FILE",
        help=(
            "baseline file of grandfathered findings (default: "
            f"./{DEFAULT_BASELINE} when it exists)"
        ),
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="write current findings to the baseline file and exit 0",
    )
    parser.add_argument(
        "--select",
        default=None,
        metavar="RULES",
        help="comma-separated rule ids to run (e.g. LO101,LO103)",
    )
    parser.add_argument(
        "--changed",
        action="store_true",
        help=(
            "only fail on findings NEW since the git merge-base with "
            "--base (the merge-base's findings print as (baselined))"
        ),
    )
    parser.add_argument(
        "--base",
        default="",
        metavar="REF",
        help=(
            "ref --changed diffs against via `git merge-base HEAD REF` "
            "(default: origin/main, then main)"
        ),
    )
    parser.add_argument(
        "--warn-only",
        action="store_true",
        help="report findings but always exit 0 (also: LO_ANALYSIS_WARN=1)",
    )
    parser.add_argument(
        "--format",
        choices=("text", "json"),
        default="text",
        help=(
            "output format: text (default) or json — a stable array of "
            "{rule, path, line, message, suppressed} objects on stdout "
            "(summaries move to stderr)"
        ),
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rule ids and exit"
    )
    return parser


def main(argv: list[str] | None = None) -> int:
    args = _build_parser().parse_args(argv)
    if args.list_rules:
        for rule_id, (_check, description) in sorted(RULES.items()):
            print(f"{rule_id}  {description}")
        return 0

    select = None
    if args.select:
        # strip BEFORE dropping empties: a whitespace-only token would
        # otherwise strip to "" and prefix-match every rule
        select = {
            token
            for token in (t.strip() for t in args.select.split(","))
            if token
        }
        if not select:
            print("--select given but names no rules", file=sys.stderr)
            return 2
        unknown = {
            token
            for token in select
            if not any(rule.startswith(token) for rule in RULES)
        }
        if unknown:
            print(
                f"unknown rule(s): {', '.join(sorted(unknown))}",
                file=sys.stderr,
            )
            return 2

    missing = [path for path in args.paths if not os.path.exists(path)]
    if missing:
        print(f"no such path: {', '.join(missing)}", file=sys.stderr)
        return 2

    # every usage error fires BEFORE the (potentially long) tree scan
    baseline_path = args.baseline
    if (
        baseline_path
        and not args.write_baseline
        and not os.path.isfile(baseline_path)
    ):
        # silently analyzing without the named baseline would report
        # every grandfathered finding as new with no hint why
        print(f"no such baseline file: {baseline_path}", file=sys.stderr)
        return 2
    if baseline_path is None and os.path.isfile(DEFAULT_BASELINE):
        baseline_path = DEFAULT_BASELINE
    if args.write_baseline and select is not None:
        # a filtered run sees a subset of findings; writing it would
        # silently drop every other rule's grandfathered entries and
        # break the next full preflight
        print(
            "--write-baseline with --select would discard other "
            "rules' baseline entries; run without --select",
            file=sys.stderr,
        )
        return 2
    if args.changed and (args.write_baseline or args.baseline):
        # two competing definitions of "old" (a checked-in file vs the
        # merge-base) would silently double-grandfather; pick one
        print(
            "--changed is mutually exclusive with --baseline/"
            "--write-baseline",
            file=sys.stderr,
        )
        return 2
    if args.base and not args.changed:
        print("--base only makes sense with --changed", file=sys.stderr)
        return 2
    changed_root = None
    changed_base = None
    if args.changed:
        baseline_path = None  # merge-base supersedes the auto-default
        from learningorchestra_tpu.analysis.changed import (
            ChangedModeError,
            resolve_merge_base,
        )

        try:
            changed_root, changed_base = resolve_merge_base(args.base)
        except ChangedModeError as error:
            print(f"--changed: {error}", file=sys.stderr)
            return 2

    findings = analyze_paths(args.paths, select)

    # LO30x deployment-contract pass: runs once per project root the
    # analyzed paths belong to (none found — a lone module, a fixture
    # dir — means the contract rules simply have nothing to check)
    from learningorchestra_tpu.analysis.contracts import (
        find_project_root,
        project_findings,
    )

    project_roots = sorted(
        {
            root
            for root in (find_project_root(path) for path in args.paths)
            if root is not None
        }
    )
    for project_root in project_roots:
        findings.extend(project_findings(project_root, select))

    if changed_root is not None:
        from learningorchestra_tpu.analysis.changed import (
            base_findings,
            base_project_keys,
        )

        base_keys = base_findings(
            args.paths, select, changed_root, changed_base
        )
        if os.path.realpath(changed_root) in {
            os.path.realpath(root) for root in project_roots
        }:
            base_keys += base_project_keys(
                select, changed_root, changed_base
            )
        findings = apply_baseline(findings, base_keys, changed_root)

    if args.write_baseline:
        write_baseline(baseline_path or DEFAULT_BASELINE, findings)
        print(
            f"wrote {len(findings)} finding(s) to "
            f"{baseline_path or DEFAULT_BASELINE}"
        )
        return 0
    if baseline_path and os.path.isfile(baseline_path):
        findings = apply_baseline(
            findings,
            load_baseline(baseline_path),
            baseline_root(baseline_path),
        )

    def _display(finding):
        # contract findings carry absolute paths (they are anchored at
        # the project root, not at an argv path); show them relative to
        # the CWD like every per-file finding the user asked about
        if os.path.isabs(finding.path):
            rel = os.path.relpath(finding.path)
            if not rel.startswith(".."):
                return replace(finding, path=rel)
        return finding

    findings = [
        _display(finding)
        for finding in sorted(
            findings, key=lambda f: (f.path, f.line, f.rule)
        )
    ]
    new = [finding for finding in findings if not finding.baselined]
    summary_out = sys.stdout
    if args.format == "json":
        # stable machine-readable schema; the human summary moves to
        # stderr so stdout parses as one JSON document
        summary_out = sys.stderr
        print(
            json.dumps(
                [
                    {
                        "rule": finding.rule,
                        "path": finding.path,
                        "line": finding.line,
                        "message": finding.message,
                        "suppressed": finding.baselined,
                    }
                    for finding in findings
                ],
                indent=2,
            )
        )
    else:
        for finding in findings:
            print(finding.render())
    if not findings:
        print("analysis: clean", file=summary_out)
    elif not new:
        print(
            f"analysis: {len(findings)} baselined finding(s), 0 new",
            file=summary_out,
        )
    else:
        print(
            f"analysis: {len(new)} new finding(s) "
            f"({len(findings) - len(new)} baselined)",
            file=summary_out,
        )
    # the analyzer's own escape hatch, read at CLI invocation time
    # lo: allow[LO301,LO305] — no preflight runs before the analyzer
    warn_env = os.environ.get("LO_ANALYSIS_WARN", "").strip().lower()
    # "=1 downgrades": an explicit 0/false/off must keep enforcement ON
    warn = args.warn_only or warn_env not in ("", "0", "false", "no", "off")
    if new and not warn:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
