"""The four SPMD-safety rule families.

Every rule checks a *convention the runtime cannot enforce* — the
invariants documented in ``parallel/spmd.py`` whose violation surfaces
only as a poisoned runtime (``SpmdTimeoutError`` /
``SpmdRuntimePoisonedError``) and a supervisor restart:

- **LO101 collective-divergence** — code reachable only on SOME
  processes (a ``coordinator`` / ``write_outputs`` /
  ``jax.process_index()`` guard) must not enter device computation or
  SPMD dispatch. A collective entered by one process and not its peers
  deadlocks the whole mesh (parallel/spmd.py:3-7).
- **LO102 broadcast-determinism** — values flowing into
  ``_broadcast_json`` / dispatcher job payloads must serialize to the
  same bytes on every process, so wall clocks, unseeded RNGs, and set
  iteration order are banned at the source. Motivating bug:
  ``ml/builder.py`` once derived a trace directory name from
  ``int(time.time() * 1000)`` — a different name on every host.
- **LO103 trace-safety** — ``@jax.jit`` bodies must not force a traced
  value to host (``float()``/``int()``/``.item()``/``np.*``/``print``):
  each one is a hidden device sync that devalues the persistent compile
  cache (utils/jitcache.py) or a trace-time error.
- **LO104 dtype hygiene** — no ``float64`` dtypes inside jitted code:
  TPUs emulate f64 in software, and one stray widening poisons the
  whole program's layout.

The detectors are intentionally syntactic (one module at a time, no
cross-function dataflow) — a finding must be explainable by pointing at
the flagged line. ``# lo: allow[LOxxx]`` suppresses an intentional
occurrence in place; the baseline file grandfathers the rest.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from learningorchestra_tpu.analysis.core import Finding

# --------------------------------------------------------------------
# shared AST helpers
# --------------------------------------------------------------------


def dotted(node: ast.AST) -> Optional[str]:
    """``jax.process_index`` for a Name/Attribute chain, else None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted(call.func)


def _last_part(name: str) -> str:
    return name.rsplit(".", 1)[-1]


def _walk_expr(root: ast.AST) -> Iterator[ast.AST]:
    """Walk an expression subtree, pruning lambda bodies (deferred
    code runs on the closure's schedule)."""
    stack = [root]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Lambda):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def iter_own_exprs(stmt: ast.stmt) -> Iterator[ast.AST]:
    """The statement's own expressions — header tests, call arguments,
    assignment values — WITHOUT descending into nested statement blocks
    (callers visit those separately, with the bindings the block's own
    statements establish) or into def/lambda bodies."""
    stack = [
        child
        for child in ast.iter_child_nodes(stmt)
        if not isinstance(child, ast.stmt)
    ]
    while stack:
        node = stack.pop()
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


# --------------------------------------------------------------------
# jit-compiled function discovery (LO103 / LO104 scope)
# --------------------------------------------------------------------

_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}
_PARTIAL_NAMES = {"partial", "functools.partial"}


def _is_jit_expr(node: ast.AST) -> bool:
    """True for ``jax.jit``, ``jit``, or ``partial(jax.jit, ...)`` —
    the decorator shapes that make a def's body traced code."""
    if dotted(node) in _JIT_NAMES:
        return True
    if isinstance(node, ast.Call):
        func = call_name(node)
        if func in _JIT_NAMES:
            return True
        if func in _PARTIAL_NAMES and node.args:
            return dotted(node.args[0]) in _JIT_NAMES
    return False


def jit_function_defs(tree: ast.Module) -> set[ast.AST]:
    """Every FunctionDef whose body is traced: decorated with a jit
    shape, wrapped via ``f = jax.jit(g)`` / ``jax.jit(g)(...)``, or
    nested inside such a function (inner defs trace with the outer)."""
    jitted: set[ast.AST] = set()
    wrapped_names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_jit_expr(dec) for dec in node.decorator_list):
                jitted.add(node)
        elif isinstance(node, ast.Call) and _is_jit_expr(node.func):
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    wrapped_names.add(arg.id)
                elif isinstance(arg, ast.Lambda):
                    jitted.add(arg)
    for node in ast.walk(tree):
        if (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node.name in wrapped_names
        ):
            jitted.add(node)
    # propagate into nested defs
    changed = True
    while changed:
        changed = False
        for outer in list(jitted):
            for node in ast.walk(outer):
                if (
                    isinstance(
                        node,
                        (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda),
                    )
                    and node not in jitted
                ):
                    jitted.add(node)
                    changed = True
    return jitted


# --------------------------------------------------------------------
# LO101 — collective divergence under coordinator-only guards
# --------------------------------------------------------------------

# Boolean names whose truth differs between processes of one mesh (the
# coordinator-only conventions from parallel/spmd.py:19-21).
DIVERGENT_NAMES = {"coordinator", "is_coordinator", "write_outputs", "render"}
# Calls whose value differs per process; comparing one is a guard.
DIVERGENT_CALLS = {"jax.process_index", "process_index"}

# Calls that enter device computation or SPMD dispatch — the things a
# single process must never do alone. Generic JAX collectives plus this
# codebase's compute entry points.
COLLECTIVE_CALLS = {
    "_broadcast_json",
    "broadcast_one_to_all",
    "sync_global_devices",
    "process_allgather",
    "gather_model",
    "build_model",
    "predict_with_model",
    "create_embedding_image",
    "tsne_embedding",
    "pca_embedding",
}
# Method-call tails that enter device programs (classifier fits and the
# frame's device transfers), keyed on the attribute name alone.
COLLECTIVE_METHODS = {
    "fit",
    "evaluate_predict",
    "predict_both",
    "device_matrix",
    "device_labels",
}
# jax.* / jnp.* is device work unless it is one of these host-side
# query/config prefixes.
_JAX_HOST_SAFE_PREFIXES = (
    "jax.process_index",
    "jax.process_count",
    "jax.device_count",
    "jax.local_device_count",
    "jax.devices",
    "jax.local_devices",
    "jax.default_backend",
    "jax.config",
    "jax.monitoring",
    "jax.distributed",
    "jax.tree_util",
    "jax.tree",
)


def _mentions_divergent_value(test: ast.AST) -> bool:
    for node in ast.walk(test):
        if isinstance(node, ast.Name) and node.id in DIVERGENT_NAMES:
            return True
        if (
            isinstance(node, ast.Attribute)
            and node.attr in DIVERGENT_NAMES
        ):
            return True
        if isinstance(node, ast.Call) and call_name(node) in DIVERGENT_CALLS:
            return True
    return False


def _collective_reason(call: ast.Call) -> Optional[str]:
    name = call_name(call)
    if name:
        last = _last_part(name)
        if last in COLLECTIVE_CALLS:
            return f"{last}() enters a cross-process collective"
        if name.startswith("jnp.") or name.startswith("jaxlib."):
            return f"{name}() dispatches device computation"
        if name.startswith("jax.") and not name.startswith(
            _JAX_HOST_SAFE_PREFIXES
        ):
            return f"{name}() dispatches device computation"
    if isinstance(call.func, ast.Attribute):
        if call.func.attr in COLLECTIVE_METHODS:
            return (
                f".{call.func.attr}() enters a device program "
                "(cross-process collectives on a multi-host mesh)"
            )
        if call.func.attr == "submit":
            receiver = dotted(call.func.value) or ""
            if _last_part(receiver) == "dispatcher":
                return "dispatcher.submit() broadcasts an SPMD job"
    return None


def _terminates(block: list[ast.stmt]) -> bool:
    return bool(block) and isinstance(
        block[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break)
    )


class _DivergenceChecker:
    """Walks statement blocks carrying a "this code only runs on some
    processes" context and flags collective entries inside it."""

    def __init__(self) -> None:
        self.findings: list[Finding] = []
        self._reported: set[int] = set()

    def check_function(self, func: ast.AST) -> None:
        body = getattr(func, "body", [])
        self._visit_block(body, guard=None)

    @staticmethod
    def _describe_guard(test: ast.AST) -> str:
        # the guard's source text, NOT its line number: baseline keys
        # are built from the message and must survive unrelated edits
        # that shift the file around
        text = ast.unparse(test)
        if len(text) > 48:
            text = text[:45] + "..."
        return text

    def _visit_block(
        self, block: list[ast.stmt], guard: Optional[str]
    ) -> None:
        for index, stmt in enumerate(block):
            if isinstance(stmt, ast.If) and _mentions_divergent_value(
                stmt.test
            ):
                desc = self._describe_guard(stmt.test)
                self._visit_block(stmt.body, guard=desc)
                self._visit_block(stmt.orelse, guard=desc)
                # `if not coordinator: return` makes everything AFTER
                # the if coordinator-only — same divergence, no indent.
                if _terminates(stmt.body) and not stmt.orelse:
                    self._visit_block(block[index + 1 :], guard=desc)
                    return
                continue
            if isinstance(stmt, ast.While) and _mentions_divergent_value(
                stmt.test
            ):
                # `while coordinator:` — the body runs on a subset of
                # processes, same divergence as an if. The else clause
                # runs on every process (loop exit), so it keeps the
                # OUTER guard.
                desc = self._describe_guard(stmt.test)
                self._visit_block(stmt.body, guard=desc)
                self._visit_block(stmt.orelse, guard)
                continue
            self._visit_stmt(stmt, guard)

    def _flag(self, node: ast.Call, reason: str, guard: str) -> None:
        if id(node) in self._reported:
            return  # one finding per call, even under nested guards
        self._reported.add(id(node))
        self.findings.append(
            Finding(
                "",
                node.lineno,
                "LO101",
                f"{reason}, but this code is reachable only under the "
                f"process-divergent guard `{guard}` — the other "
                "processes never enter it and the mesh deadlocks "
                "(parallel/spmd.py)",
            )
        )

    def _flag_collectives_in(self, root: ast.AST, desc: str) -> None:
        for sub in _walk_expr(root):
            if isinstance(sub, ast.Call):
                reason = _collective_reason(sub)
                if reason:
                    self._flag(sub, reason, desc)

    def _check_ifexp(self, node: ast.AST) -> None:
        """``gather(x) if coordinator else None`` — divergence without
        any statement-level guard."""
        if not (
            isinstance(node, ast.IfExp)
            and _mentions_divergent_value(node.test)
        ):
            return
        desc = self._describe_guard(node.test)
        for branch in (node.body, node.orelse):
            self._flag_collectives_in(branch, desc)

    def _check_boolop(self, node: ast.AST) -> None:
        """``coordinator and gather_model(x)`` — short-circuiting makes
        every operand after a divergent one conditionally evaluated
        (for ``or``, on the complement subset — equally divergent)."""
        if not isinstance(node, ast.BoolOp):
            return
        desc = None
        for operand in node.values:
            if desc is not None:
                self._flag_collectives_in(operand, desc)
            elif _mentions_divergent_value(operand):
                desc = self._describe_guard(operand)

    def _visit_stmt(
        self, stmt: ast.stmt, guard: Optional[str]
    ) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # A def under a guard is not *called* under the guard —
            # closures (worker loops, heartbeats) run on their own
            # schedule. Reset the context; check_function covers them.
            return
        for node in iter_own_exprs(stmt):
            self._check_ifexp(node)
            self._check_boolop(node)
        if guard is not None:
            # own expressions only: calls inside child blocks are
            # flagged when the recursion below reaches them — walking
            # the whole subtree here would report a call twice (with
            # two guard descriptions) when guards nest through a
            # non-If compound statement
            for node in iter_own_exprs(stmt):
                if isinstance(node, ast.Call):
                    reason = _collective_reason(node)
                    if reason:
                        self._flag(node, reason, guard)
        # recurse into compound statements, preserving the guard
        for child_block in (
            getattr(stmt, "body", None),
            getattr(stmt, "orelse", None),
            getattr(stmt, "finalbody", None),
        ):
            if isinstance(child_block, list) and child_block:
                if isinstance(child_block[0], ast.stmt):
                    self._visit_block(child_block, guard)
        for handler in getattr(stmt, "handlers", []) or []:
            self._visit_block(handler.body, guard)
        for case in getattr(stmt, "cases", []) or []:
            self._visit_block(case.body, guard)


def check_lo101(tree: ast.Module) -> Iterator[Finding]:
    checker = _DivergenceChecker()
    checker.check_function(tree)  # module level counts too
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            checker.check_function(node)
    seen: set[tuple[int, str]] = set()
    for finding in checker.findings:
        key = (finding.line, finding.message)
        if key not in seen:
            seen.add(key)
            yield finding


# --------------------------------------------------------------------
# LO102 — nondeterministic values flowing into broadcast payloads
# --------------------------------------------------------------------

NONDET_CALLS = {
    "time.time": "wall-clock",
    "time.time_ns": "wall-clock",
    "time.monotonic": "per-process clock",
    "time.monotonic_ns": "per-process clock",
    "time.perf_counter": "per-process clock",
    "time.perf_counter_ns": "per-process clock",
    "os.urandom": "os entropy",
    "os.getpid": "per-process id",
    "uuid.uuid1": "uuid entropy",
    "uuid.uuid4": "uuid entropy",
    "secrets.token_hex": "os entropy",
    "secrets.token_bytes": "os entropy",
}
_RANDOM_MODULE_PREFIXES = ("random.", "np.random.", "numpy.random.")
_SEEDED_RNG_CONSTRUCTORS = {"default_rng", "RandomState", "Generator"}
# Deterministic reductions over an unordered collection — they cleanse
# set-iteration-order taint (but never clock/entropy taint).
_ORDER_CLEANSERS = {"sorted", "len", "sum", "min", "max", "any", "all"}

BROADCAST_SINKS = {"_broadcast_json", "broadcast_one_to_all"}


class _TaintScanner:
    """Per-function, single-pass taint tracking: simple assignments
    propagate a source description from nondeterministic expressions to
    names, and broadcast sinks check their arguments."""

    def __init__(self) -> None:
        self.findings: list[Finding] = []

    # -- taint classification ---------------------------------------
    def _call_taint(self, call: ast.Call, env: dict) -> Optional[str]:
        name = call_name(call)
        if name:
            if name in NONDET_CALLS:
                return f"{name}() ({NONDET_CALLS[name]})"
            if name.startswith(_RANDOM_MODULE_PREFIXES):
                tail = _last_part(name)
                if tail in _SEEDED_RNG_CONSTRUCTORS and call.args:
                    return None  # explicitly seeded constructor
                if tail == "seed":
                    return None
                return f"{name}() (unseeded RNG)"
            if name in {"set", "frozenset"}:
                return "set() (iteration order is per-process)"
        sources = list(call.args) + [kw.value for kw in call.keywords]
        if isinstance(call.func, ast.Attribute):
            # method call: the receiver's taint rides through — both
            # `default_rng().random()` and the assigned spelling
            # `rng = default_rng(); rng.random()`
            sources.append(call.func.value)
        arg_taints = [
            taint
            for arg in sources
            for taint in [self.taint_of(arg, env)]
            if taint
        ]
        if not arg_taints:
            return None
        if (
            name
            and _last_part(name) in _ORDER_CLEANSERS
            and all("iteration order" in taint for taint in arg_taints)
        ):
            return None  # sorted(set(...)) is deterministic
        return arg_taints[0]

    def taint_of(self, node: ast.AST, env: dict) -> Optional[str]:
        if isinstance(node, ast.Name):
            return env.get(node.id)
        if isinstance(node, ast.Call):
            return self._call_taint(node, env)
        if isinstance(node, (ast.Set, ast.SetComp)):
            return "set literal (iteration order is per-process)"
        if isinstance(node, ast.JoinedStr):
            for value in node.values:
                if isinstance(value, ast.FormattedValue):
                    taint = self.taint_of(value.value, env)
                    if taint:
                        return taint
            return None
        if isinstance(node, (ast.BinOp,)):
            return self.taint_of(node.left, env) or self.taint_of(
                node.right, env
            )
        if isinstance(node, ast.UnaryOp):
            return self.taint_of(node.operand, env)
        if isinstance(node, ast.BoolOp):
            for value in node.values:
                taint = self.taint_of(value, env)
                if taint:
                    return taint
            return None
        if isinstance(node, ast.IfExp):
            return self.taint_of(node.body, env) or self.taint_of(
                node.orelse, env
            )
        if isinstance(node, ast.Dict):
            for value in list(node.keys) + list(node.values):
                if value is not None:
                    taint = self.taint_of(value, env)
                    if taint:
                        return taint
            return None
        if isinstance(node, (ast.List, ast.Tuple)):
            for element in node.elts:
                taint = self.taint_of(element, env)
                if taint:
                    return taint
            return None
        if isinstance(node, ast.Subscript):
            return self.taint_of(node.value, env)
        if isinstance(node, ast.Attribute):
            return self.taint_of(node.value, env)
        if isinstance(node, ast.Starred):
            return self.taint_of(node.value, env)
        return None

    # -- sinks -------------------------------------------------------
    def _check_sink(self, call: ast.Call, env: dict) -> None:
        name = call_name(call)
        sink = None
        if name and _last_part(name) in BROADCAST_SINKS:
            sink = f"{_last_part(name)}()"
        elif (
            isinstance(call.func, ast.Attribute)
            and call.func.attr == "submit"
            and _last_part(dotted(call.func.value) or "")
            == "dispatcher"
        ):
            sink = "dispatcher.submit() payload"
        if not sink:
            return
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            taint = self.taint_of(arg, env)
            if taint:
                self.findings.append(
                    Finding(
                        "",
                        call.lineno,
                        "LO102",
                        f"value from {taint} flows into {sink} — every "
                        "process must serialize an identical payload, "
                        "or the broadcast desynchronizes the job stream "
                        "(parallel/spmd.py)",
                    )
                )
                return

    # -- statement walk ----------------------------------------------
    def scan_function(self, func: ast.AST) -> None:
        env: dict[str, str] = {}
        self._scan_block(getattr(func, "body", []), env)

    def _bind_target(self, target: ast.AST, taint, env: dict) -> None:
        """Assign ``taint`` to every name the target binds — through
        tuple/list unpacking and ``*rest`` — clearing stale taint on
        untainted rebinds."""
        if isinstance(target, ast.Name):
            if taint:
                env[target.id] = taint
            else:
                env.pop(target.id, None)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for element in target.elts:
                self._bind_target(element, taint, env)
        elif isinstance(target, ast.Starred):
            self._bind_target(target.value, taint, env)
        elif isinstance(target, ast.Subscript):
            # payload["key"] = tainted → payload is tainted
            base = dotted(target.value)
            if taint and base:
                env[base] = taint

    def _bind_assign(self, target: ast.AST, value: ast.AST, env) -> None:
        # `a, b = time.time(), 1` — pair targets with values so only
        # the wall-clock element taints its name
        if (
            isinstance(target, (ast.Tuple, ast.List))
            and isinstance(value, (ast.Tuple, ast.List))
            and len(target.elts) == len(value.elts)
            and not any(
                isinstance(n, ast.Starred)
                for n in list(target.elts) + list(value.elts)
            )
        ):
            for element, element_value in zip(target.elts, value.elts):
                self._bind_assign(element, element_value, env)
            return
        self._bind_target(target, self.taint_of(value, env), env)

    def _scan_block(self, block: list[ast.stmt], env: dict) -> None:
        for stmt in block:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # fresh scope; scanned separately
            if isinstance(stmt, ast.Assign):
                for target in stmt.targets:
                    self._bind_assign(target, stmt.value, env)
            elif isinstance(stmt, ast.AugAssign):
                taint = self.taint_of(stmt.value, env)
                if taint and isinstance(stmt.target, ast.Name):
                    env[stmt.target.id] = taint
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                taint = self.taint_of(stmt.value, env)
                if isinstance(stmt.target, ast.Name):
                    if taint:
                        env[stmt.target.id] = taint
                    else:
                        env.pop(stmt.target.id, None)
            if isinstance(stmt, ast.For):
                # `for item in set(...)` / `for k, v in d.items()` —
                # every loop-bound name carries the iteration-order
                # taint
                iter_taint = self.taint_of(stmt.iter, env)
                if iter_taint:
                    self._bind_target(stmt.target, iter_taint, env)
            # sinks in THIS statement's own expressions only: sinks
            # inside child blocks are checked when the recursion below
            # reaches them, with the env their block's rebinds produce
            # — checking them here with the pre-block env would report
            # taint the block has already cleared
            for node in iter_own_exprs(stmt):
                if isinstance(node, ast.Call):
                    self._check_sink(node, env)
            child_blocks = [
                child_block
                for child_block in (
                    getattr(stmt, "body", None),
                    getattr(stmt, "orelse", None),
                    getattr(stmt, "finalbody", None),
                )
                if isinstance(child_block, list)
                and child_block
                and isinstance(child_block[0], ast.stmt)
            ]
            child_blocks += [
                handler.body
                for handler in getattr(stmt, "handlers", []) or []
            ]
            child_blocks += [
                case.body for case in getattr(stmt, "cases", []) or []
            ]
            if child_blocks:
                # each branch scans a COPY of env; the join keeps a
                # name tainted when ANY path taints it — sharing one
                # mutable env would let `else: x = 1` erase the if
                # branch's wall-clock taint before the sink after the
                # join sees it
                branch_envs = [
                    self._scan_branch(child_block, env)
                    for child_block in child_blocks
                ]
                # unless an if has an else, falling past the statement
                # unchanged is itself a possible path
                if not (isinstance(stmt, ast.If) and stmt.orelse):
                    branch_envs.append(dict(env))
                env.clear()
                for branch_env in branch_envs:
                    for name, taint in branch_env.items():
                        env.setdefault(name, taint)

    def _scan_branch(self, block: list[ast.stmt], env: dict) -> dict:
        branch_env = dict(env)
        self._scan_block(block, branch_env)
        return branch_env


def check_lo102(tree: ast.Module) -> Iterator[Finding]:
    scanner = _TaintScanner()
    scanner.scan_function(tree)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scanner.scan_function(node)
    seen: set[tuple[int, str]] = set()
    for finding in scanner.findings:
        key = (finding.line, finding.message)
        if key not in seen:
            seen.add(key)
            yield finding


# --------------------------------------------------------------------
# LO103 — host syncs inside jitted code
# --------------------------------------------------------------------

_HOST_FORCE_BUILTINS = {"float", "int", "bool", "complex"}
_HOST_FORCE_METHODS = {"item", "tolist", "numpy", "__array__"}
# numpy helpers that are shape/dtype bookkeeping, not array math — fine
# at trace time because they never touch a tracer's *values*.
_NP_TRACE_SAFE = {
    "np.dtype",
    "np.shape",
    "np.ndim",
    "np.result_type",
    "np.promote_types",
    "np.issubdtype",
    "np.iinfo",
    "np.finfo",
}


def _is_static_expr(node: ast.AST) -> bool:
    """Expressions that are Python values (not tracers) inside a jit
    body: literals, len(), and shape/dtype metadata chains."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Call) and call_name(node) == "len":
        return True
    if isinstance(node, ast.Attribute) and node.attr in {
        "ndim",
        "size",
        "dtype",
    }:
        return True
    if isinstance(node, ast.Subscript):
        return (
            isinstance(node.value, ast.Attribute)
            and node.value.attr == "shape"
        )
    if isinstance(node, ast.BinOp):
        return _is_static_expr(node.left) and _is_static_expr(node.right)
    if isinstance(node, ast.UnaryOp):
        return _is_static_expr(node.operand)
    return False


def _host_sync_reason(call: ast.Call) -> Optional[str]:
    name = call_name(call)
    if name in _HOST_FORCE_BUILTINS:
        if call.args and all(_is_static_expr(arg) for arg in call.args):
            return None
        return (
            f"{name}() on a traced value forces a device sync (or a "
            "ConcretizationTypeError) at every call"
        )
    if name == "print":
        return (
            "print() inside jitted code runs at trace time only (or "
            "forces a sync) — use jax.debug.print"
        )
    if name and (name.startswith("np.") or name.startswith("numpy.")):
        if name in _NP_TRACE_SAFE:
            return None
        return (
            f"{name}() materializes traced values on host — use the "
            "jnp equivalent"
        )
    if (
        isinstance(call.func, ast.Attribute)
        and call.func.attr in _HOST_FORCE_METHODS
    ):
        return (
            f".{call.func.attr}() pulls the value to host — a hidden "
            "device sync inside the compiled program"
        )
    return None


def check_lo103(tree: ast.Module) -> Iterator[Finding]:
    jitted = jit_function_defs(tree)
    seen: set[int] = set()
    for func in jitted:
        for node in ast.walk(func):
            if isinstance(node, ast.Call) and node.lineno not in seen:
                reason = _host_sync_reason(node)
                if reason:
                    seen.add(node.lineno)
                    yield Finding(
                        "",
                        node.lineno,
                        "LO103",
                        f"{reason} — inside a jit-compiled function, "
                        "this devalues the persistent compile cache "
                        "(utils/jitcache.py)",
                    )


# --------------------------------------------------------------------
# LO104 — float64 dtypes in device code
# --------------------------------------------------------------------

_F64_ATTRS = {"np.float64", "numpy.float64", "jnp.float64", "np.double"}


def _is_float64_dtype(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant) and node.value == "float64":
        return True
    return dotted(node) in _F64_ATTRS


def check_lo104(tree: ast.Module) -> Iterator[Finding]:
    jitted = jit_function_defs(tree)
    seen: set[int] = set()

    def flag(node: ast.AST, context: str) -> Iterator[Finding]:
        if node.lineno in seen:
            return
        seen.add(node.lineno)
        yield Finding(
            "",
            node.lineno,
            "LO104",
            f"float64 dtype in {context} — TPUs emulate f64 in "
            "software and one widening poisons the whole program; use "
            "float32 (or rely on default dtypes)",
        )

    for func in jitted:
        for node in ast.walk(func):
            if _is_float64_dtype(node):
                yield from flag(node, "a jit-compiled function")
    # jnp calls anywhere with an explicit float64 dtype are device code
    # even outside a jit body (op-by-op dispatch)
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = call_name(node) or ""
            if not name.startswith("jnp."):
                continue
            for keyword in node.keywords:
                if keyword.arg == "dtype" and _is_float64_dtype(
                    keyword.value
                ):
                    yield from flag(node, f"{name}()")


# --------------------------------------------------------------------
# LO106 — host copies on core/ encode/decode hot paths
# --------------------------------------------------------------------

# The rule is PATH-gated: only modules under core/ (the store's cell
# engine, wire framing, and service — every dataset byte funnels
# through them) are hot enough that one stray copy re-taxes the whole
# data plane. The zero-copy wire rework (core/wire.py v2) removed these
# copies; this rule keeps them from silently returning.


def _is_frombuffer_chain(node: ast.AST) -> bool:
    """True when ``node`` is an ``np.frombuffer(...)`` call, possibly
    chained through view-shaping methods (``.reshape``/``.view``/
    ``.astype`` receivers) — ``np.frombuffer(b).reshape(-1, w).copy()``
    is the same double pass as the direct spelling."""
    while isinstance(node, ast.Call):
        name = call_name(node)
        if name and _last_part(name) == "frombuffer":
            return True
        if not isinstance(node.func, ast.Attribute):
            return False
        node = node.func.value
    return False


def _lo106_in_scope(path: str) -> bool:
    normalized = "/" + path.replace("\\", "/")
    return "/core/" in normalized


def check_lo106(tree: ast.Module, path: str) -> Iterator[Finding]:
    if not _lo106_in_scope(path):
        return
    seen: set[int] = set()
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
        ):
            continue
        reason = None
        if node.func.attr == "copy" and _is_frombuffer_chain(
            node.func.value
        ):
            reason = (
                "np.frombuffer(...).copy() copies the freshly-wrapped "
                "wire buffer — decode into a view (the v2 zero-copy "
                "path, core/wire.py) or justify the ownership copy "
                "with `# lo: allow[LO106]`"
            )
        elif node.func.attr == "tobytes":
            reason = (
                ".tobytes() copies a live buffer on a core/ "
                "encode/decode path — hand the numpy view over "
                "(memoryview/buffer protocol) instead, or justify "
                "with `# lo: allow[LO106]`"
            )
        if reason and node.lineno not in seen:
            seen.add(node.lineno)
            yield Finding("", node.lineno, "LO106", reason)


# --------------------------------------------------------------------
# registry
# --------------------------------------------------------------------

from learningorchestra_tpu.analysis.concurrency import (  # noqa: E402
    CONCURRENCY_RULES,
)
from learningorchestra_tpu.analysis.contracts import (  # noqa: E402
    CONTRACT_RULES,
    PROJECT_RULE_IDS,
)

RULES = {
    "LO101": (
        check_lo101,
        "collective or device dispatch under a process-divergent guard",
    ),
    "LO102": (
        check_lo102,
        "nondeterministic value flowing into a broadcast payload",
    ),
    "LO103": (check_lo103, "host sync inside jit-compiled code"),
    "LO104": (check_lo104, "float64 dtype in device code"),
    "LO106": (
        check_lo106,
        "host copy (frombuffer().copy() / .tobytes()) on a core/ "
        "encode/decode hot path",
    ),
    **CONCURRENCY_RULES,
    **CONTRACT_RULES,
}

# rules whose check takes (tree, path): the LO2xx family (lock registry
# ranks are keyed by module path) and LO106 (scope-gated to core/)
_PATH_RULES = set(CONCURRENCY_RULES) | {"LO106"}


def run_rules(tree: ast.Module, path: str = "<string>") -> Iterator[Finding]:
    """Every per-FILE rule over one module. ``path`` feeds the LO2xx
    rules' declared lock registry (cross-module lock ranks are keyed by
    module path) and LO106's core/ scope gate; the LO1xx checks ignore
    it. The LO30x contract rules are registered in RULES (for
    --list-rules / --select / doc parity) but run once per project via
    contracts.project_findings, not here."""
    for rule_id, (check, _description) in RULES.items():
        if rule_id in PROJECT_RULE_IDS:
            continue
        if rule_id in _PATH_RULES:
            yield from check(tree, path)
        else:
            yield from check(tree)
