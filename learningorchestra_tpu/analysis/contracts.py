"""LO301-LO306: the deployment-contract parity rules.

The reference system's deployment contract was a pile of hand-wired
env vars in docker-compose; this reproduction grew the same surface at
10x the scale — every subsystem PR adds ``LO_*`` knobs that must be
validated in ``deploy/run.sh``, plumbed by ``deploy/cluster.py``,
documented in a ``docs/*.md`` knob table, and (for metrics and fault
points) kept in lockstep with ``docs/observability.md`` and the docs
fault tables. Until this family, that parity was reviewer discipline.

These rules ride the same Finding/suppression/baseline machinery as
LO1xx/LO2xx but run over the :mod:`registry` module's project-wide
extraction pass instead of one module's AST:

- **LO301** — a knob read in code with no ``run.sh`` preflight
  validation, or validated there but read nowhere (dead validation).
- **LO302** — a ``deploy/cluster.py`` manifest map plumbs an env name
  no code reads (the spelling drifted from the code's).
- **LO303** — a metric family declared but missing from
  ``docs/observability.md``'s catalog, or a catalog row naming a
  metric no code declares.
- **LO304** — a ``testing/faults.py`` fault point without a docs
  fault-table row, or a docs row naming an unregistered point.
- **LO305** — an inline ``os.environ`` read outside config/boot
  helpers (the read-once discipline: reads belong in
  ``_int_env``-style helpers or ``validate_*`` accessors).
- **LO306** — a knob read in code with no knob-table row in any
  ``docs/*.md``.

Suppression: knob-level findings (LO301/LO302/LO306) accept a
``# lo: allow[LO30x]`` on ANY of the knob's read sites (or the line
above), not just the anchor — the justification lives wherever the
read is most at home. Site-level findings (LO305, doc rows, run.sh
lines) suppress in place like every other rule; markdown rows take the
comment as ``<!-- # lo: allow[LO303] -->``.
"""

from __future__ import annotations

import os
from typing import Iterator

from learningorchestra_tpu.analysis.core import (
    Finding,
    SYNTAX_RULE,
    _allowed_rules,
)
from learningorchestra_tpu.analysis.registry import (
    ProjectRegistry,
    build_registry,
    fault_env_name,
    find_project_root,
)

# Modules whose direct environ reads are boot wiring by definition:
# deploy/*.py are launchers (they SET the env for everything else),
# and config.py modules are the helpers themselves.
_CONFIG_BASENAMES = ("config.py",)


def _reads_for_contract(registry: ProjectRegistry):
    """Knob -> read sites, minus the fault-injection grammar (LO304's
    domain — ``LO_FAULT_*`` names are validated dynamically by
    ``faults.validate_env`` and documented per point, not per knob)."""
    return {
        name: reads
        for name, reads in registry.env_reads.items()
        if not name.startswith("LO_FAULT_")
    }


# Each check yields (path, line, message, extra_sites): path/line
# anchor the finding, extra_sites are additional (path, line) pairs an
# allow comment may sit on (the knob's other read sites).


def check_lo301(registry: ProjectRegistry) -> Iterator[tuple]:
    if not registry.run_sh:
        return
    reads = _reads_for_contract(registry)
    validated = registry.validated
    for name in sorted(set(reads) - set(validated)):
        sites = [(read.path, read.line) for read in reads[name]]
        yield (
            sites[0][0],
            sites[0][1],
            f"deployment knob {name} is read here but never validated by "
            "the deploy/run.sh preflight (add a preflight check, or a "
            "justified allow for boot-internal wiring)",
            sites[1:],
        )
    for name in sorted(set(registry.validated_explicit) - set(reads)):
        yield (
            registry.run_sh,
            registry.validated_explicit[name],
            f"deployment knob {name} is validated by the deploy/run.sh "
            "preflight but read nowhere in the tree (dead validation)",
            [],
        )


def check_lo302(registry: ProjectRegistry) -> Iterator[tuple]:
    reads = _reads_for_contract(registry)
    seen: set[str] = set()
    for knob in registry.manifest_knobs:
        if knob.env in reads or knob.env in seen:
            continue
        seen.add(knob.env)
        where = (
            f"manifest key {knob.manifest_key!r}"
            if knob.manifest_key
            else "a manifest knob list"
        )
        yield (
            knob.path,
            knob.line,
            f"deploy/cluster.py plumbs {knob.env} (via {where}) but no "
            "code reads that env name — the manifest spelling has "
            "drifted from the code's",
            [],
        )


def check_lo303(registry: ProjectRegistry) -> Iterator[tuple]:
    if not registry.doc_metrics and not registry.metrics:
        return
    for name in sorted(set(registry.metrics) - set(registry.doc_metrics)):
        decl = registry.metrics[name]
        yield (
            decl.path,
            decl.line,
            f"metric family {name} ({decl.kind}) is declared here but has "
            "no row in docs/observability.md's catalog",
            [],
        )
    for name in sorted(set(registry.doc_metrics) - set(registry.metrics)):
        row = registry.doc_metrics[name]
        yield (
            row.path,
            row.line,
            f"docs/observability.md documents metric {name} but no code "
            "declares it (stale row, or a renamed family)",
            [],
        )


def check_lo304(registry: ProjectRegistry) -> Iterator[tuple]:
    declared = {
        fault_env_name(point): (point, line)
        for point, line in registry.fault_points.items()
    }
    for env in sorted(set(declared) - set(registry.doc_faults)):
        point, line = declared[env]
        yield (
            registry.fault_points_path,
            line,
            f"fault point {point} ({env}) is registered in FAULT_POINTS "
            "but has no docs fault-table row",
            [],
        )
    for env in sorted(set(registry.doc_faults) - set(declared)):
        row = registry.doc_faults[env]
        yield (
            row.path,
            row.line,
            f"docs fault table names {env} but testing/faults.py registers "
            "no such fault point",
            [],
        )


def check_lo305(registry: ProjectRegistry) -> Iterator[tuple]:
    for name in sorted(registry.env_reads):
        for read in registry.env_reads[name]:
            if not read.direct or read.via_helper:
                continue
            if not read.path.startswith("learningorchestra_tpu/"):
                continue  # deploy/*.py launchers set the env; boot code
            if os.path.basename(read.path) in _CONFIG_BASENAMES:
                continue
            yield (
                read.path,
                read.line,
                f"inline os.environ read of {name} outside a config "
                "helper — centralize into a _int_env/_float_env-style "
                "read-once helper (sched/config.py pattern) or justify "
                "with an allow",
                [],
            )


def check_lo306(registry: ProjectRegistry) -> Iterator[tuple]:
    if not registry.doc_knobs:
        return
    reads = _reads_for_contract(registry)
    for name in sorted(set(reads) - set(registry.doc_knobs)):
        sites = [(read.path, read.line) for read in reads[name]]
        yield (
            sites[0][0],
            sites[0][1],
            f"deployment knob {name} is read here but has no knob-table "
            "row in any docs/*.md",
            sites[1:],
        )


# Registered into rules.RULES for --list-rules/--select/doc parity;
# run_rules skips these ids — they run once per PROJECT, not per file.
CONTRACT_RULES = {
    "LO301": (
        check_lo301,
        "knob read in code but absent from the run.sh preflight "
        "(or validated there but read nowhere)",
    ),
    "LO302": (
        check_lo302,
        "cluster-manifest knob whose env spelling no code reads",
    ),
    "LO303": (
        check_lo303,
        "metric family declared but undocumented in observability.md "
        "(or documented but undeclared)",
    ),
    "LO304": (
        check_lo304,
        "fault point without a docs fault-table row (or vice versa)",
    ),
    "LO305": (
        check_lo305,
        "inline os.environ read outside config/boot helpers",
    ),
    "LO306": (
        check_lo306,
        "knob read in code with no docs knob-table row",
    ),
}

PROJECT_RULE_IDS = frozenset(CONTRACT_RULES)


class _LineCache:
    def __init__(self, root: str):
        self.root = root
        self._cache: dict[str, list[str]] = {}

    def lines(self, rel_path: str) -> list[str]:
        cached = self._cache.get(rel_path)
        if cached is None:
            try:
                with open(
                    os.path.join(self.root, rel_path), encoding="utf-8"
                ) as handle:
                    cached = handle.read().splitlines()
            except (OSError, UnicodeDecodeError):
                cached = []
            self._cache[rel_path] = cached
        return cached


def _site_allows(cache: _LineCache, rule: str, path: str, line: int) -> bool:
    lines = cache.lines(path)
    for lineno in (line, line - 1):
        if 1 <= lineno <= len(lines):
            allowed = _allowed_rules(lines[lineno - 1])
            if rule in allowed or "*" in allowed:
                return True
    return False


def project_findings(
    root: str, select: set[str] | None = None
) -> list[Finding]:
    """Run the LO30x family over the project rooted at ``root``.

    Returned finding paths are absolute (the CLI re-anchors for
    display; baseline keys relativize against the analysis root);
    suppression is resolved HERE against the artifact files, because
    the per-file pipeline never sees run.sh or markdown sources."""
    wanted = {
        rule_id
        for rule_id in CONTRACT_RULES
        if select is None
        or any(rule_id.startswith(token) for token in select)
    }
    if not wanted:
        return []
    registry = build_registry(root)
    cache = _LineCache(root)
    findings: list[Finding] = []
    for problem in registry.problems:
        findings.append(
            Finding(
                os.path.join(root, "deploy", "run.sh"),
                1,
                SYNTAX_RULE,
                problem,
            )
        )
    for rule_id in sorted(wanted):
        check, _description = CONTRACT_RULES[rule_id]
        for path, line, message, extra_sites in check(registry):
            if any(
                _site_allows(cache, rule_id, site_path, site_line)
                for site_path, site_line in [(path, line), *extra_sites]
            ):
                continue
            findings.append(
                Finding(os.path.join(root, path), line, rule_id, message)
            )
    return findings


__all__ = [
    "CONTRACT_RULES",
    "PROJECT_RULE_IDS",
    "project_findings",
    "find_project_root",
]
