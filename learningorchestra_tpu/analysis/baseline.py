"""Baseline file: grandfathered findings that do not fail the build.

The workflow mirrors ratchet-style lint adoption: run the analyzer with
``--write-baseline`` once, check the file in, and from then on only NEW
findings exit nonzero. Keys are line-number-free (``path: RULE
message``) so unrelated edits that shift a grandfathered finding do not
resurrect it; each occurrence consumes one baseline entry, so adding a
second instance of a baselined pattern still fails. Paths in keys are
relative to the BASELINE FILE's directory (posix separators), so the
same baseline matches no matter what working directory or path spelling
the analyzer was invoked with.
"""

from __future__ import annotations

import os
from collections import Counter

from learningorchestra_tpu.analysis.core import Finding


def baseline_root(path: str) -> str:
    """The directory keys are anchored to: where the baseline lives."""
    return os.path.dirname(os.path.abspath(path)) or "."

_HEADER = (
    "# learningorchestra_tpu.analysis baseline — grandfathered findings.\n"
    "# Regenerate with: python -m learningorchestra_tpu.analysis "
    "--write-baseline <paths>\n"
)


def load_baseline(path: str) -> Counter:
    entries: Counter = Counter()
    with open(path, encoding="utf-8") as handle:
        for line in handle:
            line = line.strip()
            if line and not line.startswith("#"):
                entries[line] += 1
    return entries


def write_baseline(path: str, findings: list[Finding]) -> None:
    root = baseline_root(path)
    keys = sorted(finding.baseline_key(root) for finding in findings)
    with open(path, "w", encoding="utf-8") as handle:
        handle.write(_HEADER)
        for key in keys:
            handle.write(key + "\n")


def apply_baseline(
    findings: list[Finding], baseline: Counter, root: str = "."
) -> list[Finding]:
    """Mark findings covered by the baseline (consuming entries), in
    stable (path, line) order so which duplicate gets grandfathered is
    deterministic. ``root`` must be the baseline file's directory —
    the anchor the keys were written against."""
    remaining = Counter(baseline)
    marked: list[Finding] = []
    for finding in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        key = finding.baseline_key(root)
        if remaining[key] > 0:
            remaining[key] -= 1
            finding = Finding(
                finding.path,
                finding.line,
                finding.rule,
                finding.message,
                baselined=True,
            )
        marked.append(finding)
    return marked
