"""``--changed`` mode: diff findings against the git merge-base.

The checked-in-baseline workflow (baseline.py) suits a tree whose
backlog is curated by hand. CI on a fork or a long-lived branch wants
the complement: *whatever the upstream already had is not this PR's
fault* — only findings introduced since the merge-base should block.

The mechanism reuses the baseline machinery wholesale: every analyzed
file is re-analyzed as it existed at ``git merge-base HEAD <ref>``
(base blobs fetched through one ``git cat-file --batch`` pipe — no
worktree mutation, no stash, no subprocess per file), the
base findings' line-number-free keys become an in-memory baseline
anchored at the repo root, and :func:`baseline.apply_baseline` marks
the survivors. A finding whose key existed at the base prints
``(baselined)``; only new ones fail the run.

Pure stdlib + the ``git`` binary; any git failure raises
:class:`ChangedModeError` so the CLI can exit ``2`` (usage error)
instead of silently analyzing nothing.
"""

from __future__ import annotations

import os
import subprocess
from collections import Counter

from learningorchestra_tpu.analysis.core import (
    analyze_source,
    iter_python_files,
)

_GIT_TIMEOUT_S = 30


class ChangedModeError(RuntimeError):
    """--changed cannot run: not a git repo, unknown ref, git missing."""


def _git(args: list[str], cwd: str) -> str:
    try:
        result = subprocess.run(
            ["git", *args],
            capture_output=True,
            text=True,
            cwd=cwd,
            timeout=_GIT_TIMEOUT_S,
        )
    except FileNotFoundError:
        raise ChangedModeError("--changed needs the `git` binary") from None
    except subprocess.TimeoutExpired:
        raise ChangedModeError(
            f"git {' '.join(args[:2])} timed out"
        ) from None
    if result.returncode != 0:
        raise ChangedModeError(
            f"git {' '.join(args[:2])} failed: "
            f"{result.stderr.strip() or result.stdout.strip()}"
        )
    return result.stdout


def resolve_merge_base(ref: str, cwd: str = ".") -> tuple[str, str]:
    """``(repo_root, merge_base_sha)`` for diffing against ``ref``.
    An empty ``ref`` tries ``origin/main`` then ``main`` — the branch
    the deploy preflight and CI diff against by default."""
    repo_root = _git(["rev-parse", "--show-toplevel"], cwd).strip()
    candidates = [ref] if ref else ["origin/main", "main"]
    errors = []
    for candidate in candidates:
        try:
            sha = _git(["merge-base", "HEAD", candidate], repo_root).strip()
        except ChangedModeError as error:
            errors.append(str(error))
            continue
        return repo_root, sha
    raise ChangedModeError(
        "no merge-base found (tried "
        f"{', '.join(candidates)}): {errors[-1] if errors else 'no refs'}"
    )


def _base_blobs(
    rels: list[str], repo_root: str, base_sha: str
) -> dict[str, str]:
    """``rel → source`` at the merge-base, fetched through ONE
    ``git cat-file --batch`` pipe instead of a subprocess per file —
    the full-package preflight reads ~100 base blobs. Paths missing at
    the base (files added since) are simply absent from the result."""
    if not rels:
        return {}
    request = "".join(f"{base_sha}:{rel}\n" for rel in rels)
    try:
        result = subprocess.run(
            ["git", "cat-file", "--batch"],
            input=request.encode(),
            capture_output=True,
            cwd=repo_root,
            timeout=_GIT_TIMEOUT_S,
        )
    except FileNotFoundError:
        raise ChangedModeError("--changed needs the `git` binary") from None
    except subprocess.TimeoutExpired:
        raise ChangedModeError("git cat-file --batch timed out") from None
    if result.returncode != 0:
        raise ChangedModeError(
            f"git cat-file failed: {result.stderr.decode().strip()}"
        )
    sources: dict[str, str] = {}
    payload = result.stdout
    offset = 0
    for rel in rels:
        newline = payload.index(b"\n", offset)
        header = payload[offset:newline].decode()
        offset = newline + 1
        # "<oid> <type> <size>" for a hit; "<request> missing" (or
        # "ambiguous"/"dangling") otherwise — a miss carries no body
        parts = header.split()
        if len(parts) == 3 and parts[2].isdigit():
            size = int(parts[2])
            blob = payload[offset : offset + size]
            offset += size + 1  # body + trailing newline
            if parts[1] == "blob":
                sources[rel] = blob.decode("utf-8", errors="replace")
    return sources


def base_findings(
    paths: list[str],
    select: set[str] | None,
    repo_root: str,
    base_sha: str,
) -> Counter:
    """The merge-base's findings for every file the current run
    analyzes, keyed like a baseline anchored at ``repo_root``. Files
    that did not exist at the base (new files) contribute nothing —
    every finding in them is genuinely new."""
    rels = []
    for file_path in iter_python_files(paths):
        rel = os.path.relpath(os.path.abspath(file_path), repo_root)
        rel = rel.replace(os.sep, "/")
        if not rel.startswith(".."):  # inside the repo
            rels.append(rel)
    keys: Counter = Counter()
    for rel, source in _base_blobs(rels, repo_root, base_sha).items():
        # the finding's path must equal the CURRENT run's spelling for
        # the key to collide — analyze under the repo-relative path and
        # key against repo_root, same anchor the caller applies
        for finding in analyze_source(
            source, os.path.join(repo_root, rel), select
        ):
            keys[finding.baseline_key(repo_root)] += 1
    return keys


def base_project_keys(
    select: set[str] | None, repo_root: str, base_sha: str
) -> Counter:
    """The merge-base's LO30x project-contract findings, keyed like a
    baseline. The contract pass reads non-Python artifacts (run.sh,
    docs tables), so blob-by-blob analysis is not enough: the base TREE
    is materialized once via ``git archive`` into a tempdir and the
    project pass runs there. Contract finding paths are root-relative,
    so the keys collide with the current run's regardless of where the
    tempdir lives."""
    import io
    import shutil
    import tarfile
    import tempfile

    try:
        result = subprocess.run(
            ["git", "archive", "--format=tar", base_sha],
            capture_output=True,
            cwd=repo_root,
            timeout=_GIT_TIMEOUT_S,
        )
    except FileNotFoundError:
        raise ChangedModeError("--changed needs the `git` binary") from None
    except subprocess.TimeoutExpired:
        raise ChangedModeError("git archive timed out") from None
    if result.returncode != 0:
        raise ChangedModeError(
            f"git archive failed: {result.stderr.decode().strip()}"
        )
    from learningorchestra_tpu.analysis.contracts import project_findings
    from learningorchestra_tpu.analysis.registry import is_project_root

    tmp_root = tempfile.mkdtemp(prefix="lo-analysis-base-")
    try:
        with tarfile.open(fileobj=io.BytesIO(result.stdout)) as archive:
            archive.extractall(tmp_root, filter="data")
        if not is_project_root(tmp_root):
            return Counter()  # the base predates the contract artifacts
        keys: Counter = Counter()
        for finding in project_findings(tmp_root, select):
            keys[finding.baseline_key(tmp_root)] += 1
        return keys
    finally:
        shutil.rmtree(tmp_root, ignore_errors=True)
