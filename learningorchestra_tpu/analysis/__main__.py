"""``python -m learningorchestra_tpu.analysis`` entry point."""

import sys

from learningorchestra_tpu.analysis.cli import main

sys.exit(main())
