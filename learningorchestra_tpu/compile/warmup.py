"""Publish-time serve warmup: pay the first predict's compile at
checkpoint publication, not on a user's request.

When the builder (or the sweep's argmax winner) publishes a checkpoint
(the ``os.replace`` in ml/checkpoint.py), the model_builder service's
publish handler (registered via
:func:`learningorchestra_tpu.compile.set_publish_handler`) submits a
LOW-priority device job running :func:`warm_artifact`: load the model
through the serve registry (priming its device-resident cache) and
execute one real forward at the serving path's fixed dispatch shape —
``grid_size(1, max_batch)`` padded rows, exactly what the MicroBatcher
dispatches (serve/batcher.py). An AOT ``lower().compile()`` alone
would warm the persistent cache but NOT the in-process jit call path
(measured: the next call still re-enters backend compile), so warmup
executes the real call. Low priority: a warmup must never delay the
builds and predicts the device queue exists for — it fills idle lanes.

The compile (if any) is attributed to the AOT plane in the flight
recorder (``compile:aot`` span, ``warmup:...`` manifest key), so boot
and publish-time compiles never masquerade as request-path stalls.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def warm_artifact(
    path: str,
    features: Optional[int] = None,
    serve=None,
    mesh=None,
    max_batch: Optional[int] = None,
) -> bool:
    """Run the serving path's fixed-shape forward for ``path`` once.

    ``features`` is the training feature width (the builder knows it at
    publish time; tree checkpoints don't record it). Falls back to the
    model's own parameter shapes where they encode the width (logistic,
    naive bayes) and skips — returning False — when the width is
    unknowable: a wrong-width warmup would compile a program the serve
    path never dispatches."""
    from learningorchestra_tpu.utils import jitcache
    from learningorchestra_tpu.utils.shapegrid import grid_size

    if max_batch is None:
        from learningorchestra_tpu.serve import config as serve_config

        max_batch = serve_config.max_batch()
    if serve is not None:
        model = serve.registry.get(path)
    else:
        from learningorchestra_tpu.ml.checkpoint import load_model

        model = load_model(path, mesh)
    if features is None:
        params = getattr(model, "params", None)
        if params is not None and "w" in params:
            features = int(params["w"].shape[0])
        elif getattr(model, "theta", None) is not None:
            features = int(model.theta.shape[1])
        else:
            return False
    rows = np.zeros(
        (grid_size(1, max_batch), int(features)), np.float32
    )
    with jitcache.compile_source("aot", f"warmup:{path.rsplit('/', 1)[-1]}"):
        model.predict_both(rows)
    return True
