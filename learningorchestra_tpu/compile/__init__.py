"""The AOT compile plane (docs/compile.md).

Four pieces, one goal — a fleet member never pays a compile another
member (or its own boot) already paid:

- :mod:`~learningorchestra_tpu.compile.manifest` enumerates the finite
  program universe off the shared shape grid;
- :mod:`~learningorchestra_tpu.compile.aot` lowers + compiles it at
  boot (background, off the device queue) into the persistent cache;
- :mod:`~learningorchestra_tpu.compile.fleetcache` moves serialized
  executables through the ``__lo_executables__`` store collection;
- :mod:`~learningorchestra_tpu.compile.warmup` runs the serve path's
  fixed dispatch shape when a checkpoint publishes.

This module owns the process-global **publish hook**: checkpoint
writers (ml/builder.py, ml/sweep.py) call :func:`checkpoint_published`
after their atomic ``os.replace``; a service that can warm the serve
path (services/model_builder.py) registers the handler. Default is a
no-op — library callers, tests and scripts publish checkpoints without
dragging in the serve plane."""

from __future__ import annotations

import threading
from typing import Callable, Optional

from learningorchestra_tpu.compile.aot import (  # noqa: F401
    AotPlane,
    backend_fingerprint,
    boot_compile_plane,
    compile_spec,
    deserialize_compiled,
    serialize_compiled,
)
from learningorchestra_tpu.compile.manifest import (  # noqa: F401
    ProgramSpec,
    enumerate_programs,
    specs_for_artifact,
)

_HANDLER: Optional[Callable[[str, Optional[int]], None]] = None
_HANDLER_LOCK = threading.Lock()


def set_publish_handler(
    handler: Optional[Callable[[str, Optional[int]], None]],
):
    """Install the process-wide checkpoint-publication handler
    (``handler(path, features)``); returns the previous one. Latest
    registration wins — registry entries key on absolute checkpoint
    paths, so any live serve plane can warm any artifact."""
    global _HANDLER
    with _HANDLER_LOCK:
        previous, _HANDLER = _HANDLER, handler
    return previous


def checkpoint_published(
    path: str, features: Optional[int] = None
) -> None:
    """Notify the compile plane that ``path`` just became (or replaced)
    a published checkpoint. Never raises into the publishing build:
    warmup is an optimization, a failed hook must not fail the fit."""
    with _HANDLER_LOCK:
        handler = _HANDLER
    if handler is None:
        return
    try:
        handler(path, features)
    except Exception:  # noqa: BLE001 — publication outlives the hook
        pass
