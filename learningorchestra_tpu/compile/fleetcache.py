"""Fleet-shared executable cache: the ``__lo_executables__`` collection.

The persistent XLA cache (utils/jitcache.py) already holds serialized
compiled executables as content-addressed files — one per (program,
compiler version, topology) key. This module moves those files through
the store so the whole fleet shares one warm cache: a runner finishing
an AOT pass (or any request-path compile, once published) uploads its
fresh entries; a fresh runner joining the fleet — or restarting after
the kill -9 chaos drill — pulls them into its local cache dir before
its first dispatch and replays the bench suite with near-zero compile
misses. Cache misses fall through to local compile-then-publish, so
the plane is never load-bearing: an empty or unreachable collection
just means a cold boot.

Wire shape: each cache file becomes chunked data rows
``{artifact, seq, data(base64)}`` plus ONE meta row
``{artifact, meta: 1, chunks, sha256, fingerprint}`` written LAST —
a reader never sees an artifact whose chunks aren't all landed. The
rows ride the store's existing columnar wire (string columns compress
like any other payload). Trust is decided on the meta row alone: a
``fingerprint`` (compile/aot.py's jax/jaxlib/backend envelope) that
doesn't match the local runtime is DISCARDED without touching the
payload — a version-mismatched executable is recompiled, never
deserialized wrong — and a chunk set failing its sha256 is discarded
the same way. Rev-invalidated: :func:`fetch` is a no-op while the
collection rev hasn't moved since this process last looked.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import threading

COLLECTION = "__lo_executables__"

# 1 MiB of raw bytes per chunk row (~1.37 MiB base64): big enough that
# real cache entries (KB..MB) take a handful of rows, small enough to
# stay friendly to the store's per-document handling.
CHUNK_BYTES = 1 << 20

# fetch() no-op guard: collection rev seen per store object
_REV_SEEN: dict[int, int] = {}
_REV_LOCK = threading.Lock()


def _fingerprint_json() -> str:
    from learningorchestra_tpu.compile.aot import backend_fingerprint

    return json.dumps(backend_fingerprint(), sort_keys=True)


def _metrics():
    from learningorchestra_tpu.compile.aot import _aot_metrics

    return _aot_metrics()


def _published_artifacts(store) -> set[str]:
    return {
        doc["artifact"]
        for doc in store.find(COLLECTION, {"meta": 1})
        if "artifact" in doc
    }


def publish(store, cache_dir: str) -> dict:
    """Upload every local cache entry the collection doesn't already
    hold. Returns ``{"published": n, "skipped": m}``."""
    stats = {"published": 0, "skipped": 0}
    if not os.path.isdir(cache_dir):
        return stats
    try:
        existing = _published_artifacts(store)
    except Exception:  # unreachable store: cold boot semantics
        return stats
    fingerprint = _fingerprint_json()
    for entry in sorted(os.listdir(cache_dir)):
        path = os.path.join(cache_dir, entry)
        if not os.path.isfile(path):
            continue
        if entry in existing:
            stats["skipped"] += 1
            continue
        with open(path, "rb") as handle:
            blob = handle.read()
        digest = hashlib.sha256(blob).hexdigest()
        rows = [
            {
                "artifact": entry,
                "seq": seq,
                "data": base64.b64encode(
                    blob[offset:offset + CHUNK_BYTES]
                ).decode("ascii"),
            }
            for seq, offset in enumerate(
                range(0, len(blob), CHUNK_BYTES)
            )
        ] or [{"artifact": entry, "seq": 0, "data": ""}]
        try:
            store.insert_many(COLLECTION, rows)
            # meta row LAST: its presence means every chunk landed
            store.insert_one(COLLECTION, {
                "artifact": entry,
                "meta": 1,
                "chunks": len(rows),
                "size": len(blob),
                "sha256": digest,
                "fingerprint": fingerprint,
            })
        except Exception:
            return stats  # partial publish: meta row absent → invisible
        stats["published"] += 1
        _metrics()["published"].inc()
    return stats


def fetch(store, cache_dir: str, force: bool = False) -> dict:
    """Pull fleet artifacts this process's cache dir is missing.
    Returns ``{"fetched": n, "discarded": d, "skipped": s}``;
    a no-op (all zeros) while the collection rev hasn't moved."""
    stats = {"fetched": 0, "discarded": 0, "skipped": 0}
    try:
        rev = store.collection_rev(COLLECTION)
    except Exception:
        return stats
    with _REV_LOCK:
        if not force and _REV_SEEN.get(id(store)) == rev:
            return stats
    os.makedirs(cache_dir, exist_ok=True)
    local_fingerprint = _fingerprint_json()
    try:
        metas = [
            doc for doc in store.find(COLLECTION, {"meta": 1})
            if "artifact" in doc
        ]
    except Exception:
        return stats
    for meta in metas:
        name = meta["artifact"]
        if os.sep in name or name in (".", ".."):
            stats["discarded"] += 1  # a path-traversal row is hostile,
            _metrics()["discarded"].inc()  # not merely stale
            continue
        path = os.path.join(cache_dir, name)
        if os.path.exists(path):
            stats["skipped"] += 1
            continue
        if meta.get("fingerprint") != local_fingerprint:
            # version mismatch: discard WITHOUT deserializing — the
            # local compiler recompiles and publishes under its own
            # fingerprint
            stats["discarded"] += 1
            _metrics()["discarded"].inc()
            continue
        chunks = sorted(
            (
                doc for doc in store.find(
                    COLLECTION, {"artifact": name}
                )
                if "data" in doc
            ),
            key=lambda doc: doc.get("seq", 0),
        )
        try:
            blob = b"".join(
                base64.b64decode(doc["data"]) for doc in chunks
            )
        except Exception:
            blob = None
        if (
            blob is None
            or len(chunks) != meta.get("chunks")
            or hashlib.sha256(blob).hexdigest() != meta.get("sha256")
        ):
            stats["discarded"] += 1  # corrupt payload: recompile locally
            _metrics()["discarded"].inc()
            continue
        tmp_path = f"{path}.tmp.{os.getpid()}"
        with open(tmp_path, "wb") as handle:
            handle.write(blob)
        os.replace(tmp_path, path)  # atomic: jax never reads a partial
        stats["fetched"] += 1
        _metrics()["fetched"].inc()
    with _REV_LOCK:
        _REV_SEEN[id(store)] = rev
    return stats
