"""Program-universe enumeration for the AOT compile plane.

Every dispatch shape in the product is already quantized onto the
quarter-octave grid (utils/shapegrid.py — shared by the data plane's
row padding, the serving MicroBatcher and the sweep coalescer), every
float matrix flows through ONE dtype policy (utils/dtypepolicy.py) and
every executable binds a mesh at its call site (the pjit contract).
That makes the set of programs a deployment can dispatch finite and
enumerable: (program kind x grid bucket x dtype policy x mesh
signature x class count). This module walks that universe and emits
:class:`ProgramSpec` rows the AOT compiler (compile/aot.py) lowers —
derived from the SAME shape math the dispatchers use
(``padded_row_count``, ``grid_size``), never a parallel re-derivation
that could drift.

Coverage is explicitly bounded (docs/compile.md): predict programs for
all five classifier kinds, the dominant build programs (the logistic
L-BFGS segment and the naive-bayes fit — the two module-level jitted
fits whose shapes the manifest can reconstruct exactly), and the sweep
plane's fused logistic segment at the job-axis pad floor. Everything
past ``LO_AOT_MAX_PROGRAMS`` lands on a RETURNED drop list the caller
logs — a silent cap would read as "precompiled everything" when it
didn't.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Optional

import numpy as np

from learningorchestra_tpu.utils.shapegrid import grid_size

# serve-path dispatch rows quantize to grid_size(total, max_batch);
# build rows ride the same grid via padded_row_count. The build ladder
# stops at this many rows by default — past it, per-program compiles
# amortize over seconds of execution and AOT buys little.
_BUILD_ROWS_CEILING = 4096
_DEFAULT_FEATURES = (8,)
_DEFAULT_CLASSES = (2,)
# the sweep plane pads its job axis to at least this (ml/sweep.py)
_SWEEP_JOB_FLOOR = 8


@dataclasses.dataclass(frozen=True)
class ProgramSpec:
    """One precompilable program: enough to rebuild the exact abstract
    arguments (`lower_args`) the live dispatcher would trace with."""

    program: str        # "predict:lr" | "build:nb" | "sweep:lr" | ...
    rows: int           # padded axis-0 dispatch rows (post mesh align)
    features: int
    num_classes: int
    dtype: str          # X dtype after the wire dtype policy
    mesh_sig: tuple     # core/devcache.mesh_signature(mesh)
    statics: tuple = () # sorted (name, value) static args, e.g. max_depth

    @property
    def key(self) -> str:
        """Stable content key — the span attribute, the fleet-cache row
        id component, and the dedup identity. Mesh signature included
        as a DETERMINISTIC digest (never ``hash()`` — string hashing is
        per-process salted, and this key must agree across the fleet):
        an executable is only valid for the topology it bound."""
        import hashlib

        statics = ",".join(f"{k}={v}" for k, v in self.statics)
        mesh_digest = hashlib.sha1(
            repr(self.mesh_sig).encode()
        ).hexdigest()[:10]
        return (
            f"{self.program}|r{self.rows}|f{self.features}"
            f"|c{self.num_classes}|{self.dtype}|{statics}"
            f"|mesh{mesh_digest}"
        )


def _policy_dtype_name() -> str:
    from learningorchestra_tpu.parallel.sharding import policy_dtype

    return np.dtype(policy_dtype(np.float32)).name


def _padded_rows(n: int, mesh) -> int:
    from learningorchestra_tpu.parallel.sharding import (
        DATA_AXIS,
        padded_row_count,
    )

    return padded_row_count(n, mesh.shape[DATA_AXIS])


def serve_row_buckets(mesh, max_batch: Optional[int] = None) -> list[int]:
    """Every axis-0 shape the serving path can dispatch: the batcher
    pads each flush to ``grid_size(total, max_batch)`` and prepare_xy
    then aligns to the mesh's data axis — the composition, deduped."""
    if max_batch is None:
        from learningorchestra_tpu.serve import config as serve_config

        max_batch = serve_config.max_batch()
    return sorted(
        {_padded_rows(grid_size(n, max_batch), mesh)
         for n in range(1, max_batch + 1)}
    )


def build_row_buckets(mesh, ceiling: int = _BUILD_ROWS_CEILING) -> list[int]:
    """The quarter-octave ladder a training set's row count pads onto,
    up to ``ceiling`` raw rows (the grid is pass-through below 8, so
    start the ladder at the first bucketed value)."""
    buckets: set[int] = set()
    n = 8
    while n <= ceiling:
        buckets.add(_padded_rows(n, mesh))
        n = grid_size(n + 1)  # hop to the next grid bucket
    return sorted(buckets)


def enumerate_programs(
    mesh,
    features: Iterable[int] = _DEFAULT_FEATURES,
    num_classes: Iterable[int] = _DEFAULT_CLASSES,
    max_batch: Optional[int] = None,
    build_rows_ceiling: int = _BUILD_ROWS_CEILING,
    max_programs: Optional[int] = None,
) -> tuple[list[ProgramSpec], list[ProgramSpec]]:
    """The (kept, dropped) program universe for ``mesh``.

    Ordered by first-request impact — serve-path predict programs
    first (they gate the first POST /predict), then build, then sweep
    — so a tight ``max_programs`` cap keeps the programs whose compile
    a user actually waits on. The drop list is returned, NEVER
    swallowed: the caller logs it (no silent caps)."""
    from learningorchestra_tpu.core.devcache import mesh_signature
    from learningorchestra_tpu.ml import trees as lo_trees

    sig = mesh_signature(mesh)
    dtype = _policy_dtype_name()
    specs: list[ProgramSpec] = []

    def add(program, rows, f, c, statics=()):
        specs.append(ProgramSpec(
            program=program, rows=rows, features=f, num_classes=c,
            dtype=dtype, mesh_sig=sig, statics=tuple(statics),
        ))

    serve_rows = serve_row_buckets(mesh, max_batch)
    fit_rows = build_row_buckets(mesh, build_rows_ceiling)
    for f in features:
        for c in num_classes:
            for rows in serve_rows:
                add("predict:lr", rows, f, c)
                add("predict:nb", rows, f, c)
                add("predict:dt", rows, f, c,
                    [("max_depth", lo_trees.MAX_DEPTH), ("trees", 1)])
                add("predict:rf", rows, f, c,
                    [("max_depth", lo_trees.MAX_DEPTH),
                     ("trees", lo_trees.NUM_TREES)])
                add("predict:gb", rows, f, c,
                    [("max_depth", lo_trees.MAX_DEPTH),
                     ("rounds", lo_trees.GBT_ROUNDS)])
            for rows in fit_rows:
                add("build:lr", rows, f, c,
                    [("iters", lr_segment_iters(rows, f))])
                add("build:nb", rows, f, c)
            add("sweep:lr", _padded_rows(min(fit_rows), mesh), f, c,
                [("iters", lr_segment_iters(min(fit_rows), f)),
                 ("jobs", _SWEEP_JOB_FLOOR)])
    if max_programs is None:
        return specs, []
    return specs[:max_programs], specs[max_programs:]


def lr_segment_iters(
    rows: int, features: int, max_iter: int = 100
) -> int:
    """The static ``iters`` the logistic fit would segment ``max_iter``
    into at this shape — the SAME derivation as logistic._fit (budget,
    then the convergence-check cap), so the manifest's build program is
    the one the live fit dispatches, not a near miss."""
    from learningorchestra_tpu.ml import logistic as lo_logistic
    from learningorchestra_tpu.ml.base import largest_divisor, segment_steps

    iters = segment_steps(
        max_iter, rows, lo_logistic._LR_ROW_ITERS_BUDGET, features
    )
    capped = largest_divisor(
        max_iter, min(iters, lo_logistic._LR_CHECK_ITERS)
    )
    if capped >= min(iters, 5):
        iters = capped
    return iters


def specs_for_artifact(path: str, mesh) -> list[ProgramSpec]:
    """Exact predict-program specs for a published checkpoint — shapes
    read from the artifact's arrays, one spec per serve-path row
    bucket. This is what publish-time warmup precompiles so the first
    POST /models/<name>/predict never eats the compile."""
    import json
    import zipfile

    from learningorchestra_tpu.core.devcache import mesh_signature

    with zipfile.ZipFile(path) as archive:
        header = json.loads(archive.read("__model__.json"))
        shapes = {}
        with np.load(path) as data:
            for name in data.files:
                member = data[name]
                # the zip holds the JSON header next to the arrays;
                # np.load surfaces non-.npy members as raw bytes
                if hasattr(member, "shape"):
                    shapes[name] = member.shape
    kind = header["kind"]
    scalars = header.get("scalars", {})
    sig = mesh_signature(mesh)
    dtype = _policy_dtype_name()
    if kind == "logistic":
        program, f, c, statics = (
            "predict:lr", shapes["w"][0], shapes["w"][1], (),
        )
    elif kind == "naive_bayes":
        program, f, c, statics = (
            "predict:nb", shapes["theta"][1], shapes["theta"][0], (),
        )
    elif kind == "tree_ensemble":
        trees, c = shapes["leaf_probs"][0], shapes["leaf_probs"][2]
        program, f = "predict:rf", None  # features not in the heaps
        statics = (
            ("max_depth", int(scalars["max_depth"])), ("trees", trees),
        )
    elif kind == "gbt":
        program, c = "predict:gb", 2  # boosted margins are binary
        f = None
        statics = (
            ("max_depth", int(scalars["max_depth"])),
            ("rounds", shapes["features_heap"][0]),
        )
    else:
        return []
    if f is None:
        # tree checkpoints don't record the feature width; warmup calls
        # the model directly (compile/warmup.py) so the manifest row is
        # advisory — use the default width for the spec's identity.
        f = _DEFAULT_FEATURES[0]
    return [
        ProgramSpec(
            program=program, rows=rows, features=int(f),
            num_classes=int(c), dtype=dtype, mesh_sig=sig,
            statics=statics,
        )
        for rows in serve_row_buckets(mesh)
    ]


def lower_args(spec: ProgramSpec):
    """``(jitted_fn, args, static_kwargs)`` rebuilding exactly what the
    live dispatcher traces for ``spec`` — ShapeDtypeStructs with the
    call site's sharding, so the persistent-cache key the AOT compile
    writes is the one the runtime jit lookup computes."""
    import jax
    import jax.numpy as jnp

    from learningorchestra_tpu.ml.base import resolve_mesh
    from learningorchestra_tpu.parallel.sharding import row_sharded

    mesh = resolve_mesh(None)
    from learningorchestra_tpu.core.devcache import mesh_signature

    if mesh_signature(mesh) != spec.mesh_sig:
        raise ValueError(
            f"spec {spec.key} was enumerated for another mesh"
        )
    sharded = row_sharded(mesh)
    sds = jax.ShapeDtypeStruct
    rows, f, c = spec.rows, spec.features, spec.num_classes
    statics = dict(spec.statics)
    X = sds((rows, f), jnp.dtype(spec.dtype), sharding=sharded)
    f32 = jnp.float32

    if spec.program == "predict:lr":
        from learningorchestra_tpu.ml import logistic as lo

        params = {"w": sds((f, c), f32), "b": sds((c,), f32)}
        return lo._forward, (params, X, sds((f,), f32), sds((f,), f32)), {}
    if spec.program == "predict:nb":
        from learningorchestra_tpu.ml import naive_bayes as nb

        return nb._forward, (sds((c, f), f32), sds((c,), f32), X), {}
    if spec.program in ("predict:dt", "predict:rf"):
        from learningorchestra_tpu.ml import trees as lo_trees

        depth, trees = statics["max_depth"], statics["trees"]
        heap = (trees, 2 ** depth - 1)
        return (
            lo_trees._ensemble_forward,
            (X, sds(heap, jnp.int32), sds(heap, f32),
             sds((trees, 2 ** depth, c), f32)),
            {"max_depth": depth},
        )
    if spec.program == "predict:gb":
        from learningorchestra_tpu.ml import trees as lo_trees

        depth, rounds = statics["max_depth"], statics["rounds"]
        heap = (rounds, 2 ** depth - 1)
        return (
            lo_trees._gbt_forward,
            (X, sds((), f32), sds(heap, jnp.int32), sds(heap, f32),
             sds((rounds, 2 ** depth), f32), sds((), f32)),
            {"max_depth": depth},
        )
    if spec.program == "build:lr":
        from learningorchestra_tpu.ml import logistic as lo

        params = {"w": sds((f, c), f32), "b": sds((c,), f32)}
        state = jax.eval_shape(lo._lbfgs_state, params)
        return (
            lo._fit_segment_runner(),
            (params, state, X,
             sds((rows,), jnp.int32, sharding=sharded),
             sds((rows,), f32, sharding=sharded)),
            {"iters": statics["iters"], "l2": sds((), f32)},
        )
    if spec.program == "build:nb":
        from learningorchestra_tpu.ml import naive_bayes as nb

        return (
            nb._fit,
            (X, sds((rows,), jnp.int32, sharding=sharded),
             sds((rows,), f32, sharding=sharded)),
            {"num_classes": c, "smoothing": sds((), f32)},
        )
    if spec.program == "sweep:lr":
        from learningorchestra_tpu.ml import logistic as lo
        from learningorchestra_tpu.ml import sweep as lo_sweep

        jobs = statics["jobs"]
        params = {
            "w": sds((jobs, f, c), f32), "b": sds((jobs, c), f32),
        }
        state = jax.eval_shape(jax.vmap(lo._lbfgs_state), params)
        return (
            lo_sweep._lr_fused_segment,
            (params, state, sds((jobs, rows, f), jnp.dtype(spec.dtype)),
             sds((jobs, rows), jnp.int32), sds((jobs, rows), f32),
             sds((jobs,), f32)),
            {"iters": statics["iters"]},
        )
    raise ValueError(f"unknown program {spec.program!r}")
