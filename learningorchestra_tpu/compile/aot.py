"""AOT compiler: lower + compile the program manifest ahead of demand.

The mechanism is the persistent XLA compilation cache
(utils/jitcache.py): ``jit(fn).lower(...).compile()`` writes the same
serialized-executable cache entry a request-path jit dispatch would,
so a boot-time pass over the manifest (compile/manifest.py) turns
every first-request compile into a cache load — measured here at ~3 ms
versus ~46 ms for even the smallest real compile, and two orders more
for tree fits. Where the installed jax additionally supports direct
executable serialization (``jax.experimental.serialize_executable``),
:func:`serialize_compiled` / :func:`deserialize_compiled` round-trip a
``Compiled`` handle in-process — the bit-identity contract the tests
pin; when it doesn't, the plane falls back cleanly to cache warming
alone.

Keying follows the devcache discipline: an artifact is only trusted
under the exact (jax, jaxlib, backend platform + version) fingerprint
that produced it (:func:`backend_fingerprint`) — the fleet cache
(compile/fleetcache.py) discards on mismatch WITHOUT deserializing,
never loads wrong.

The pass runs off the device queue's hot lane: a plain daemon thread
(compilation is host CPU work — it never occupies a device-class
scheduler slot), every compile attributed to its manifest key via
``jitcache.compile_source`` so the flight recorder separates boot
compiles from request-path stalls.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional

from learningorchestra_tpu.compile import config as compile_config
from learningorchestra_tpu.compile.manifest import (
    ProgramSpec,
    enumerate_programs,
    lower_args,
    specs_for_artifact,
)

_METRICS = None
_METRICS_LOCK = threading.Lock()


def _aot_metrics() -> dict:
    global _METRICS
    with _METRICS_LOCK:
        if _METRICS is None:
            from learningorchestra_tpu.telemetry.metrics import (
                global_registry,
            )

            registry = global_registry()
            _METRICS = {
                "compiled": registry.counter(
                    "lo_aot_programs_compiled_total",
                    "Manifest programs compiled by the AOT pass",
                ),
                "published": registry.counter(
                    "lo_aot_programs_published_total",
                    "Executable artifacts published to the fleet cache",
                ),
                "fetched": registry.counter(
                    "lo_aot_programs_fetched_total",
                    "Executable artifacts pulled from the fleet cache",
                ),
                "discarded": registry.counter(
                    "lo_aot_programs_discarded_total",
                    "Fleet artifacts dropped (version-fingerprint "
                    "mismatch or corrupt payload) and recompiled",
                ),
            }
        return _METRICS


def backend_fingerprint() -> dict:
    """The version envelope an executable artifact is only valid under
    — same role as the devcache key's dtype/mesh components: a
    fingerprint mismatch means "recompile", never "deserialize and
    hope". Platform version covers the XLA build; jax/jaxlib cover
    the tracing + serialization format."""
    import jax
    import jaxlib.version

    device = jax.devices()[0]
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.version.__version__,
        "platform": jax.default_backend(),
        "platform_version": str(
            getattr(device.client, "platform_version", "")
        ),
    }


@contextlib.contextmanager
def persist_all_compiles():
    """Drop the persistent cache's admission thresholds for the block.

    The defaults (min compile time 1 s) exist to keep request-path
    trivia out of the cache — but the AOT pass compiles exactly the
    programs the fleet WILL dispatch, and a sub-second serve forward
    skipped at boot is precisely the compile the first predict would
    then eat. Process-global config: a concurrent request compile also
    persisting during the window is harmless (same cache, same keys)."""
    import jax

    old_time = jax.config.jax_persistent_cache_min_compile_time_secs
    old_size = jax.config.jax_persistent_cache_min_entry_size_bytes
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    try:
        yield
    finally:
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", old_time
        )
        jax.config.update(
            "jax_persistent_cache_min_entry_size_bytes", old_size
        )


def compile_spec(spec: ProgramSpec, source: str = "aot"):
    """Lower + compile one manifest entry, attributed to its manifest
    key in the flight recorder. Returns the ``Compiled`` handle (the
    persistent-cache write is the side effect the plane exists for),
    or raises whatever the lowering raised — the caller decides
    whether a spec failure is fatal (the background pass logs and
    continues; tests assert)."""
    from learningorchestra_tpu.utils import jitcache

    fn, args, statics = lower_args(spec)
    with jitcache.compile_source(source, spec.key):
        with persist_all_compiles():
            compiled = fn.lower(*args, **statics).compile()
    _aot_metrics()["compiled"].inc()
    return compiled


def serialize_compiled(compiled) -> Optional[bytes]:
    """One self-contained payload for a ``Compiled`` handle (executable
    bytes + arg/result pytree defs, pickled together), or None when the
    installed jax lacks executable serialization — callers fall back to
    persistent-cache warming, never half-serialize."""
    try:
        import pickle

        from jax.experimental import serialize_executable
    except ImportError:
        return None
    payload, in_tree, out_tree = serialize_executable.serialize(compiled)
    return pickle.dumps((payload, in_tree, out_tree))


def deserialize_compiled(blob: bytes):
    """Load a :func:`serialize_compiled` payload back into a callable
    executable. Only valid under the same :func:`backend_fingerprint`
    that serialized it — the fleet cache enforces that BEFORE this
    runs; corrupt payloads raise (callers discard and recompile)."""
    import pickle

    from jax.experimental import serialize_executable

    payload, in_tree, out_tree = pickle.loads(blob)
    return serialize_executable.deserialize_and_load(
        payload, in_tree, out_tree
    )


class AotPlane:
    """The boot-time precompile pass, runnable synchronously (tests,
    scripts) or as a background daemon thread (the runner).

    One pass: fleet-fetch serialized artifacts into the local cache
    dir → enumerate the manifest (+ exact specs for every published
    checkpoint in ``models_dir``) → compile everything under the cap
    (dropped entries are LOGGED, satisfying the no-silent-caps
    contract) → publish fresh cache entries back to the fleet."""

    def __init__(
        self,
        mesh=None,
        store=None,
        models_dir: str = "",
        cache_dir: Optional[str] = None,
        max_programs: Optional[int] = None,
        publish: Optional[bool] = None,
    ):
        self.mesh = mesh
        self.store = store
        self.models_dir = models_dir
        self.cache_dir = cache_dir
        self.max_programs = (
            compile_config.max_programs()
            if max_programs is None
            else max_programs
        )
        self.publish = (
            compile_config.publish_enabled() if publish is None else publish
        )
        self._stats: dict = {"state": "idle"}
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    def stats(self) -> dict:
        with self._lock:
            return dict(self._stats)

    def _specs(self) -> tuple[list[ProgramSpec], list[ProgramSpec]]:
        import os

        from learningorchestra_tpu.ml.base import resolve_mesh
        from learningorchestra_tpu.ml.checkpoint import CHECKPOINT_SUFFIX

        mesh = self.mesh = resolve_mesh(self.mesh)
        specs, _ = enumerate_programs(mesh)
        seen = {s.key for s in specs}
        if self.models_dir and os.path.isdir(self.models_dir):
            for entry in sorted(os.listdir(self.models_dir)):
                if not entry.endswith(CHECKPOINT_SUFFIX):
                    continue
                try:
                    derived = specs_for_artifact(
                        os.path.join(self.models_dir, entry), mesh
                    )
                except Exception:  # corrupt checkpoint: not this plane's
                    continue      # problem — the serve path 500s it
                for spec in derived:
                    if spec.key not in seen:
                        seen.add(spec.key)
                        specs.append(spec)
        return specs[: self.max_programs], specs[self.max_programs:]

    def run(self) -> dict:
        """The synchronous pass; returns (and retains, for
        /debug-style introspection) its stats dict."""
        import time

        from learningorchestra_tpu.compile import fleetcache
        from learningorchestra_tpu.utils import jitcache

        started = time.perf_counter()
        stats: dict = {
            "state": "running", "compiled": 0, "failed": 0,
            "fetched": 0, "discarded": 0, "published": 0, "dropped": 0,
        }
        # published ONCE: stats() snapshots this same dict under the
        # lock, so progress is visible live and there is no second
        # assignment for a reader to race between
        with self._lock:
            self._stats = stats
        cache_dir = self.cache_dir or jitcache.enable_compile_cache()
        source = "aot"
        if self.store is not None and cache_dir:
            fetch_stats = fleetcache.fetch(self.store, cache_dir)
            stats["fetched"] = fetch_stats["fetched"]
            stats["discarded"] = fetch_stats["discarded"]
            if fetch_stats["fetched"]:
                # warm pass over fleet-fetched artifacts: compiles now
                # resolve as cache loads and the recorder should say
                # the fleet (not this process's compiler) paid for them
                source = "fleetcache"
        kept, dropped = self._specs()
        stats["dropped"] = len(dropped)
        if dropped:
            # no silent caps: name what the cap excluded
            print(
                f"[aot] LO_AOT_MAX_PROGRAMS={self.max_programs} dropped "
                f"{len(dropped)} programs: "
                + ", ".join(s.key for s in dropped[:8])
                + ("..." if len(dropped) > 8 else ""),
                flush=True,
            )
        for spec in kept:
            try:
                compile_spec(spec, source=source)
                stats["compiled"] += 1
            except Exception as error:  # noqa: BLE001 — pass is advisory
                stats["failed"] += 1
                print(f"[aot] {spec.key} failed: {error}", flush=True)
        if self.store is not None and cache_dir and self.publish:
            publish_stats = fleetcache.publish(self.store, cache_dir)
            stats["published"] = publish_stats["published"]
        stats["seconds"] = round(time.perf_counter() - started, 3)
        stats["state"] = "done"
        return stats

    def start(self) -> "AotPlane":
        """Run the pass on a background daemon thread — boot returns
        immediately; the thread never holds a device-class slot."""
        thread = threading.Thread(
            target=self.run, name="lo-aot-precompile", daemon=True
        )
        self._thread = thread
        thread.start()
        return self

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)


def boot_compile_plane(
    store=None, models_dir: str = "", cache_dir: Optional[str] = None
) -> Optional[AotPlane]:
    """The runner's boot hook: start the background precompile pass
    when ``LO_AOT=1``, else do nothing (the knob is validated either
    way — a typo'd LO_AOT refuses bring-up upstream in the preflight)."""
    if not compile_config.aot_enabled():
        return None
    return AotPlane(
        store=store, models_dir=models_dir, cache_dir=cache_dir
    ).start()
