"""AOT compile-plane deployment knobs (docs/compile.md).

=========================  =======  =====================================
knob                       default  meaning
=========================  =======  =====================================
``LO_AOT``                 0        run the boot-time AOT precompile
                                    pass over the program manifest
                                    (compile/aot.py). Off by default:
                                    the pass spends compiler seconds at
                                    boot, which a short-lived test or
                                    script process never amortizes.
``LO_AOT_MAX_PROGRAMS``    64       cap on manifest entries the AOT
                                    pass compiles; everything past the
                                    cap lands on a LOGGED drop list
                                    (no silent caps). 0 = enumerate
                                    only, compile nothing.
``LO_AOT_PUBLISH``         1        publish compiled executables into
                                    the ``__lo_executables__`` store
                                    collection so the rest of the
                                    fleet skips the compile
                                    (compile/fleetcache.py). Only
                                    matters when a store is attached.
=========================  =======  =====================================

Same fail-fast posture as sched/config.py: a malformed value raises at
read time, so deploy/run.sh's preflight and the runner's boot print
refuse bring-up instead of silently picking a side.
"""

from __future__ import annotations

import os


def _flag_env(name: str, default: bool) -> bool:
    """Strict 0/1 — ``LO_AOT=yes`` silently meaning "off" (or "on") is
    exactly the ambiguity the preflight exists to kill."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    if raw not in ("0", "1"):
        raise ValueError(f"{name} must be 0 or 1, got {raw!r}")
    return raw == "1"


def aot_enabled() -> bool:
    """Whether the boot-time AOT precompile pass runs (``LO_AOT``)."""
    return _flag_env("LO_AOT", False)


def publish_enabled() -> bool:
    """Whether locally compiled executables are published to the fleet
    cache (``LO_AOT_PUBLISH``)."""
    return _flag_env("LO_AOT_PUBLISH", True)


def max_programs() -> int:
    """Manifest-entry cap for the AOT pass (``LO_AOT_MAX_PROGRAMS``).
    Strictly integral >= 0 — ``6.5`` silently truncating would halve
    the precompiled universe without a trace."""
    raw = os.environ.get("LO_AOT_MAX_PROGRAMS", "").strip()
    if not raw:
        return 64
    try:
        value = int(raw)
    except ValueError:
        raise ValueError(
            f"LO_AOT_MAX_PROGRAMS must be an integer, got {raw!r}"
        ) from None
    if value < 0:
        raise ValueError(
            f"LO_AOT_MAX_PROGRAMS must be >= 0, got {value}"
        )
    return value


def validate_env() -> dict:
    """Read every compile knob (raising on malformed values) and return
    the resolved configuration — run.sh preflight and runner boot."""
    return {
        "LO_AOT": 1 if aot_enabled() else 0,
        "LO_AOT_MAX_PROGRAMS": max_programs(),
        "LO_AOT_PUBLISH": 1 if publish_enabled() else 0,
    }
