"""In-store time-series retention for fleet metrics.

Every process already serves ``GET /metrics`` (telemetry/metrics.py);
until now the cluster driver scraped them, logged a one-line summary,
and threw the samples away. This module keeps them: a collector parses
each member's Prometheus exposition into per-family values and appends
one delta-compressed document per ``(instance, tick)`` into the bounded
ring collection ``__lo_metrics__`` — rev-bumped like every other
collection, capped by ``LO_TSDB_POINTS`` ticks per instance, labelled
``{instance, service}`` — so ``GET /metrics/history`` (utils/web.py)
can answer "p99 of ``lo_serve_request_seconds`` over the last 10
minutes, per replica" as one HTTP call with the rollup computed
server-side.

Retention format (one document per instance per scrape tick)::

    {"instance": "10.0.0.7:5002", "service": "model_builder",
     "ts": 1754000000.0, "vals": {family: value, ...}}

``vals`` is delta-compressed: a family appears only when its value
changed since the instance's previous tick (readers fold forward).
Scalar families (counters summed across label sets, gauges) store a
float; histogram families store ``{"buckets": {le: cumulative_count},
"sum": s, "count": n}`` so windowed percentiles come from bucket-count
deltas, Prometheus ``histogram_quantile`` style.

Stdlib-only, like the rest of ``telemetry/``.
"""

from __future__ import annotations

import re
import threading
import time
import traceback
from typing import Any, Optional

from learningorchestra_tpu.sched.config import _float_env, _int_env

COLLECTION = "__lo_metrics__"

# Derived while parsing: lo_http_requests_total samples whose status
# label is 5xx, summed separately — the label-collapsed family total
# can't distinguish a 500 storm from healthy traffic, and the SLO 5xx
# rule needs exactly that split.
DERIVED_5XX = "lo_http_requests_5xx_total"

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)\s*$"
)
_STATUS_RE = re.compile(r'status="([^"]*)"')
_LE_RE = re.compile(r'le="([^"]*)"')


# --- knobs -------------------------------------------------------------------

def tsdb_points() -> int:
    """Ring cap: scrape ticks retained per instance in
    ``__lo_metrics__`` (``LO_TSDB_POINTS``, strictly integral >= 1).
    At the default 60s interval, 512 points is ~8.5 hours of history
    per member."""
    return _int_env("LO_TSDB_POINTS", 512)


def metrics_interval_s() -> float:
    """Seconds between scrape ticks (``LO_METRICS_INTERVAL_S`` — the
    same knob the cluster driver's scrape loop uses, so the in-store
    history and the driver's summary log advance together)."""
    return _float_env("LO_METRICS_INTERVAL_S", 60.0)


def _flag01_env(name: str, default: bool) -> bool:
    """Strict 0/1 flag (sched/config.py's ``resume_enabled`` pattern):
    ``yes`` silently meaning "off" is exactly what the preflight
    refuses."""
    import os

    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    if raw not in ("0", "1"):
        raise ValueError(f"{name} must be 0 or 1, got {raw!r}")
    return raw == "1"


def collect_enabled() -> bool:
    """The single-process fallback collector (services/runner.py).
    Strict 0/1: the cluster driver sets ``LO_TSDB_COLLECT=0`` in every
    member's environment because ITS collector owns the scrape — a
    runner-side collector double-appending the same registry would
    halve the effective retention window."""
    return _flag01_env("LO_TSDB_COLLECT", True)


# --- exposition parsing ------------------------------------------------------

def parse_samples(text: str) -> dict[str, Any]:
    """Prometheus exposition text → per-family values.

    Counters/gauges sum across label sets to one float; histogram
    families (``_bucket``/``_sum``/``_count`` suffixes) merge into one
    bucket snapshot. Raises ``ValueError`` on a malformed or truncated
    body — callers treat that as a per-member skip, never a crash
    (deploy/cluster.py's scrape loop, the ingest route)."""
    scalars: dict[str, float] = {}
    hists: dict[str, dict] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        match = _SAMPLE_RE.match(line)
        if match is None:
            raise ValueError(f"malformed sample line: {line!r}")
        name, labels, raw = match.groups()
        value = float(raw)  # ValueError on a torn value token
        if name.endswith("_bucket"):
            family = name[: -len("_bucket")]
            le = _LE_RE.search(labels or "")
            if le is None:
                raise ValueError(f"bucket sample without le: {line!r}")
            hist = hists.setdefault(
                family, {"buckets": {}, "sum": 0.0, "count": 0.0}
            )
            buckets = hist["buckets"]
            buckets[le.group(1)] = buckets.get(le.group(1), 0.0) + value
        elif name.endswith("_sum") or name.endswith("_count"):
            family, part = name.rsplit("_", 1)
            hist = hists.setdefault(
                family, {"buckets": {}, "sum": 0.0, "count": 0.0}
            )
            hist[part] += value
        else:
            scalars[name] = scalars.get(name, 0.0) + value
            if name == "lo_http_requests_total":
                scalars.setdefault(DERIVED_5XX, 0.0)
                status = _STATUS_RE.search(labels or "")
                if status is not None and status.group(1).startswith("5"):
                    scalars[DERIVED_5XX] += value
    out: dict[str, Any] = dict(scalars)
    out.update(hists)
    return out


# --- retention ---------------------------------------------------------------

class TSDB:
    """Appender for ``__lo_metrics__`` over any :class:`DocumentStore`.

    Delta compression state is per-process; a fresh instance (collector
    restart) reseeds each instance's last-known values from the store
    before its first append, so history stays fold-forward-continuous
    across restarts and revs keep advancing from the store's own
    sequence (no rev aliasing — core/store.py's per-boot random base)."""

    def __init__(self, store, points: Optional[int] = None):
        self._store = store
        self._points = int(points) if points is not None else tsdb_points()
        self._lock = threading.Lock()
        self._last: dict[str, dict] = {}

    def _reseed_locked(self, instance: str) -> dict:
        vals: dict = {}
        try:
            for doc in self._store.find(COLLECTION, {"instance": instance}):
                vals.update(doc.get("vals") or {})
        except Exception:  # noqa: BLE001 — an empty seed only costs
            return {}  # one uncompressed tick, never the append
        return vals

    def append(
        self,
        instance: str,
        service: str,
        vals: dict[str, Any],
        ts: Optional[float] = None,
    ) -> dict:
        """Append one tick for ``instance``; returns the stored doc."""
        ts = time.time() if ts is None else float(ts)
        with self._lock:
            if instance not in self._last:
                self._last[instance] = self._reseed_locked(instance)
            last = self._last[instance]
            changed = {
                family: value
                for family, value in vals.items()
                if last.get(family) != value
            }
            self._last[instance] = dict(vals)
            document = {
                "instance": instance,
                "service": service,
                "ts": round(ts, 3),
                "vals": changed,
            }
            self._store.insert_one(COLLECTION, document)
            # Ring discipline: the budget scales with the instances this
            # appender has seen, so an N-member plane keeps ~points
            # ticks per member (every member lands each tick).
            budget = self._points * max(1, len(self._last))
            try:
                self._store.trim_collection(COLLECTION, budget)
            except NotImplementedError:
                pass  # a backend without the primitive grows unbounded
        return document


# --- history + rollups -------------------------------------------------------

def history(
    store,
    family: str,
    instance: Optional[str] = None,
) -> dict[str, list]:
    """Fold-forward read: ``{instance: [(ts, value), ...]}`` for one
    family, delta compression undone (ticks where the family did not
    change repeat the carried value, so windowed rollups always have a
    baseline)."""
    series: dict[str, list] = {}
    carry: dict[str, Any] = {}
    for doc in store.find(COLLECTION):
        inst = doc.get("instance")
        if inst is None or (instance is not None and inst != instance):
            continue
        vals = doc.get("vals") or {}
        if family in vals:
            carry[inst] = vals[family]
        if inst not in carry or doc.get("ts") is None:
            continue
        series.setdefault(inst, []).append((doc["ts"], carry[inst]))
    return series


def services_of(store) -> dict[str, str]:
    """``{instance: service}`` labels currently present in the ring."""
    labels: dict[str, str] = {}
    for doc in store.find(COLLECTION):
        inst = doc.get("instance")
        if inst is not None and doc.get("service"):
            labels[inst] = doc["service"]
    return labels


def _quantile(deltas: dict[str, float], q: float) -> Optional[float]:
    """Prometheus ``histogram_quantile``: linear interpolation within
    the bucket where the rank falls; the open ``+Inf`` bucket reports
    its lower bound."""
    items = sorted(
        (float("inf") if le in ("+Inf", "inf", "Inf") else float(le), c)
        for le, c in deltas.items()
    )
    if not items:
        return None
    total = items[-1][1]
    if total <= 0:
        return None
    rank = q * total
    prev_le, prev_c = 0.0, 0.0
    for le, cumulative in items:
        if cumulative >= rank:
            if le == float("inf"):
                return prev_le
            if cumulative == prev_c:
                return le
            return prev_le + (le - prev_le) * (rank - prev_c) / (
                cumulative - prev_c
            )
        prev_le, prev_c = le, cumulative
    return items[-1][0]


def rollup(
    family: str,
    points: list,
    window_s: float = 600.0,
    now: Optional[float] = None,
) -> Optional[dict]:
    """Windowed rollup over one instance's ``(ts, value)`` points.

    Counters (``*_total``) → ``rate`` per second; histograms → windowed
    ``p50``/``p99``/``mean`` + ``count_rate`` from bucket-count deltas
    (baseline = last snapshot at or before the window start, so samples
    observed before the window never pollute it); gauges →
    ``last``/``avg``/``min``/``max``. A counter reset inside the window
    (member restart) falls back to the post-restart totals."""
    if not points:
        return None
    now = points[-1][0] if now is None else float(now)
    since = now - window_s
    baseline = None
    window = []
    for ts, value in points:
        if ts <= since:
            baseline = (ts, value)
        elif ts <= now:
            window.append((ts, value))
    if not window:
        return None
    last_ts, last = window[-1]
    base_ts = baseline[0] if baseline is not None else since
    span = max(last_ts - base_ts, 1e-9)
    out: dict[str, Any] = {
        "samples": len(window),
        "window_s": window_s,
        "from": round(base_ts, 3),
        "to": round(last_ts, 3),
    }
    if isinstance(last, dict):
        base = {"buckets": {}, "sum": 0.0, "count": 0.0}
        if baseline is not None and isinstance(baseline[1], dict):
            base = baseline[1]
        last_buckets = last.get("buckets") or {}
        base_buckets = base.get("buckets") or {}
        deltas = {
            le: count - base_buckets.get(le, 0.0)
            for le, count in last_buckets.items()
        }
        count_delta = (last.get("count") or 0.0) - (base.get("count") or 0.0)
        sum_delta = (last.get("sum") or 0.0) - (base.get("sum") or 0.0)
        if count_delta < 0 or any(d < 0 for d in deltas.values()):
            deltas = dict(last_buckets)  # reset: counts since restart
            count_delta = last.get("count") or 0.0
            sum_delta = last.get("sum") or 0.0
        out["count"] = count_delta
        out["count_rate"] = round(count_delta / span, 6)
        if count_delta > 0:
            out["mean"] = round(sum_delta / count_delta, 6)
        for name, q in (("p50", 0.5), ("p99", 0.99)):
            value = _quantile(deltas, q)
            if value is not None:
                out[name] = round(value, 6)
        return out
    if family.endswith("_total"):
        base_value = baseline[1] if baseline is not None else 0.0
        if not isinstance(base_value, (int, float)):
            base_value = 0.0
        delta = last - base_value
        if delta < 0:
            delta = last  # counter reset inside the window
        out["delta"] = delta
        out["rate"] = round(delta / span, 6)
        return out
    values = [value for _, value in window if isinstance(value, (int, float))]
    if not values:
        return None
    out["last"] = values[-1]
    out["avg"] = round(sum(values) / len(values), 6)
    out["min"] = min(values)
    out["max"] = max(values)
    return out


def window_rollups(
    store,
    family: str,
    window_s: float = 600.0,
    now: Optional[float] = None,
    instance: Optional[str] = None,
) -> dict[str, dict]:
    """Per-instance rollups for one family — the server-side half of
    ``GET /metrics/history``."""
    out = {}
    for inst, points in history(store, family, instance=instance).items():
        rolled = rollup(family, points, window_s=window_s, now=now)
        if rolled is not None:
            out[inst] = rolled
    return out


# --- collector ---------------------------------------------------------------

class Collector:
    """Single-process fallback collector: snapshot the local registry
    each tick and append it as instance ``local`` (the cluster driver's
    collector replaces this in fleet deployments — it scrapes every
    member over HTTP and posts into the store head's ingest route).
    Ticks also republish the SLO gauges (telemetry/slo.py) so
    ``lo_slo_burning{rule}`` moves with the data it judges."""

    def __init__(
        self,
        store,
        registry,
        instance: str = "local",
        service: str = "runner",
        interval_s: Optional[float] = None,
        points: Optional[int] = None,
    ):
        self._store = store
        self._registry = registry
        self._instance = instance
        self._service = service
        self._interval = (
            metrics_interval_s() if interval_s is None else float(interval_s)
        )
        self._tsdb = TSDB(store, points=points)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.ticks = 0
        self.errors = 0

    def collect_once(self, ts: Optional[float] = None) -> None:
        """One scrape tick; failures are counted and swallowed — the
        observability plane must never take down what it observes."""
        try:
            vals = parse_samples(self._registry.render())
            self._tsdb.append(self._instance, self._service, vals, ts=ts)
            self.ticks += 1
        except Exception:  # noqa: BLE001 — best-effort, like the journal
            self.errors += 1
            traceback.print_exc()
            return
        try:
            from learningorchestra_tpu.telemetry import slo as _slo

            _slo.publish(self._store, now=ts)
        except Exception:  # noqa: BLE001
            self.errors += 1
            traceback.print_exc()

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            self.collect_once()

    def start(self) -> "Collector":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="lo-tsdb-collector"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
