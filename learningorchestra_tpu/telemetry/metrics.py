"""Thread-safe process metrics rendered in Prometheus text exposition.

One :class:`MetricsRegistry` per process (``global_registry``); services,
the job manager, the SPMD dispatcher and the store all declare their
metrics against it, and every ``WebApp`` serves its ``render()`` at
``GET /metrics`` (text format version 0.0.4, the format every Prometheus
scraper and ``promtool`` accepts). Declarations are get-or-create so
seven services sharing one process share one ``lo_http_requests_total``
family; a re-declaration with a different kind or label set is a
programming error and raises.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional, Sequence

# Prometheus' default buckets stop at 10 s; model builds run minutes, so
# the tail extends to 10 min before +Inf.
DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
    10.0, 30.0, 60.0, 120.0, 300.0, 600.0,
)

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_label(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _format_value(value: float) -> str:
    if value == float("inf"):
        return "+Inf"
    if isinstance(value, float) and value.is_integer():
        return str(int(value))
    return repr(value)


def _labels_text(names: Sequence[str], values: Sequence[str]) -> str:
    if not names:
        return ""
    pairs = ",".join(
        f'{name}="{_escape_label(value)}"'
        for name, value in zip(names, values)
    )
    return "{" + pairs + "}"


class _Child:
    """One labelset's value cell — what ``.labels(...)`` hands back."""

    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value -= amount

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)


class _HistogramChild:
    __slots__ = ("_lock", "buckets", "counts", "sum", "count")

    def __init__(self, lock: threading.Lock, buckets: tuple[float, ...]):
        self._lock = lock
        self.buckets = buckets
        self.counts = [0] * len(buckets)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        with self._lock:
            self.sum += value
            self.count += 1
            # per-bucket counts, cumulated at render time
            for index, bound in enumerate(self.buckets):
                if value <= bound:
                    self.counts[index] += 1
                    break


class Metric:
    """A family: name + help + kind + labelled children."""

    def __init__(
        self,
        name: str,
        help_text: str,
        kind: str,
        label_names: tuple[str, ...],
        buckets: tuple[float, ...] = DEFAULT_BUCKETS,
        fn: Optional[Callable[[], float]] = None,
    ):
        self.name = name
        self.help = help_text
        self.kind = kind
        self.label_names = label_names
        self.buckets = tuple(sorted(buckets))
        self.fn = fn
        self._lock = threading.Lock()
        self._children: dict[tuple[str, ...], object] = {}

    def labels(self, *values: object) -> object:
        if len(values) != len(self.label_names):
            raise ValueError(
                f"{self.name} expects labels {self.label_names}, "
                f"got {values!r}"
            )
        key = tuple(str(v) for v in values)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                if self.kind == "histogram":
                    child = _HistogramChild(self._lock, self.buckets)
                else:
                    child = _Child(self._lock)
                self._children[key] = child
        return child

    # label-less convenience: metric.inc() / .set() / .observe()
    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self.labels().dec(amount)

    def set(self, value: float) -> None:
        self.labels().set(value)

    def observe(self, value: float) -> None:
        self.labels().observe(value)

    def value(self, *label_values: object) -> float:
        child = self.labels(*label_values)
        return child.value

    def render(self) -> list[str]:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} {self.kind}",
        ]
        if self.fn is not None:
            lines.append(f"{self.name} {_format_value(float(self.fn()))}")
            return lines
        with self._lock:
            children = list(self._children.items())
        if not children and not self.label_names:
            # a declared scalar counter/gauge always renders (0), so
            # dashboards see the family before its first increment
            if self.kind in ("counter", "gauge"):
                lines.append(f"{self.name} 0")
            return lines
        for key, child in sorted(children):
            labels = _labels_text(self.label_names, key)
            if self.kind == "histogram":
                cumulative = 0
                for bound, count in zip(child.buckets, child.counts):
                    cumulative += count
                    bucket_labels = _labels_text(
                        self.label_names + ("le",),
                        key + (_format_value(bound),),
                    )
                    lines.append(
                        f"{self.name}_bucket{bucket_labels} {cumulative}"
                    )
                inf_labels = _labels_text(
                    self.label_names + ("le",), key + ("+Inf",)
                )
                lines.append(f"{self.name}_bucket{inf_labels} {child.count}")
                lines.append(
                    f"{self.name}_sum{labels} {_format_value(child.sum)}"
                )
                lines.append(f"{self.name}_count{labels} {child.count}")
            else:
                lines.append(
                    f"{self.name}{labels} {_format_value(child.value)}"
                )
        return lines


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, Metric] = {}
        self._collectors: list[Callable[["MetricsRegistry"], None]] = []

    def _declare(
        self,
        name: str,
        help_text: str,
        kind: str,
        labels: Sequence[str],
        **kwargs,
    ) -> Metric:
        label_names = tuple(labels)
        with self._lock:
            metric = self._metrics.get(name)
            if metric is not None:
                if metric.kind != kind or metric.label_names != label_names:
                    raise ValueError(
                        f"metric {name!r} re-declared as {kind}"
                        f"{label_names} (was {metric.kind}"
                        f"{metric.label_names})"
                    )
                return metric
            metric = Metric(name, help_text, kind, label_names, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(
        self, name: str, help_text: str, labels: Sequence[str] = ()
    ) -> Metric:
        return self._declare(name, help_text, "counter", labels)

    def gauge(
        self,
        name: str,
        help_text: str,
        labels: Sequence[str] = (),
        fn: Optional[Callable[[], float]] = None,
    ) -> Metric:
        return self._declare(name, help_text, "gauge", labels, fn=fn)

    def histogram(
        self,
        name: str,
        help_text: str,
        labels: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ) -> Metric:
        return self._declare(
            name, help_text, "histogram", labels, buckets=tuple(buckets)
        )

    def declared_families(self) -> dict[str, str]:
        """Snapshot of ``{family name: kind}`` for every declared
        metric — the introspection surface the deployment-contract
        analyzer (analysis/contracts.py, LO303) and its anti-rot test
        compare against ``docs/observability.md``'s catalog."""
        with self._lock:
            return {
                name: metric.kind for name, metric in self._metrics.items()
            }

    def register_collector(
        self, collector: Callable[["MetricsRegistry"], None]
    ) -> None:
        """``collector(registry)`` runs at every render — the hook for
        gauges whose truth lives elsewhere (store occupancy, jitcache
        counters) and is cheaper to read at scrape time than to push on
        every mutation."""
        with self._lock:
            self._collectors.append(collector)

    def render(self) -> str:
        with self._lock:
            collectors = list(self._collectors)
        for collector in collectors:
            try:
                collector(self)
            except Exception:  # noqa: BLE001 — scraping must not 500
                # a failing collector (e.g. a store mid-shutdown) loses
                # its gauges for this scrape, never the whole endpoint
                continue
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        return "\n".join(
            line for metric in metrics for line in metric.render()
        ) + "\n"


_GLOBAL: Optional[MetricsRegistry] = None
_GLOBAL_LOCK = threading.Lock()


def global_registry() -> MetricsRegistry:
    """The process-wide registry every component reports into. First
    call also wires the jitcache collector so ``/metrics`` includes
    persistent-cache hit/miss and compile seconds on every service."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = MetricsRegistry()
            _register_jitcache(_GLOBAL)
        return _GLOBAL


def _register_jitcache(registry: MetricsRegistry) -> None:
    # utils/jitcache keeps live counters behind jax.monitoring listeners;
    # importing it is cheap (no jax import until the cache is enabled)
    from learningorchestra_tpu.utils import jitcache

    hits = registry.gauge(
        "lo_jitcache_persistent_hits",
        "Persistent XLA cache hits (serialized executable loaded)",
    )
    misses = registry.gauge(
        "lo_jitcache_persistent_misses",
        "Persistent XLA cache misses (program compiled and written)",
    )
    compile_s = registry.gauge(
        "lo_jitcache_backend_compile_seconds",
        "Cumulative seconds inside the XLA compiler this process",
    )
    trace_s = registry.gauge(
        "lo_jitcache_trace_seconds",
        "Cumulative jaxpr trace seconds this process",
    )

    def collect(_registry: MetricsRegistry) -> None:
        stats = jitcache.raw_stats()
        hits.set(stats["persistent_cache_hits"])
        misses.set(stats["persistent_cache_misses"])
        compile_s.set(stats["backend_compile_s"])
        trace_s.set(stats["trace_s"])

    registry.register_collector(collect)


# store id() → its "store" label value. The collector closure keeps a
# registered store alive for the life of the process (its gauges must
# keep answering), so ids never recycle here. Typical processes register
# exactly one store; the label exists so an atypical one (store server
# co-habiting with services, tests) reports each store distinctly
# instead of the collectors silently overwriting one shared gauge.
_REGISTERED_STORES: "dict[int, str]" = {}


def register_store(
    store: object,
    registry: Optional[MetricsRegistry] = None,
    role: Optional[dict] = None,
) -> None:
    """Expose a store's occupancy gauges (collection count, WAL bytes,
    spill bytes) on ``/metrics``, labelled by registration order.
    Idempotent per store instance; a store without ``telemetry_stats``
    (e.g. the remote-store client — the store SERVER scrapes its own)
    is a no-op.

    ``role`` (the store SERVER's HA role dict) additionally exports the
    replication health the failover story is judged by
    (docs/replication.md): ``lo_store_replication_lag`` (follower:
    acknowledged records not yet applied locally),
    ``lo_store_loss_window`` (what this server's last takeover
    measurably cost, in records), and ``lo_store_unreplicated_acks``
    (sync-repl mode: writes acknowledged after the replication wait
    timed out)."""
    if hasattr(store, "shard_occupancy"):
        # a sharded client fronting N groups: per-shard gauges instead
        # of the single-store family (every service create_app calls
        # this entry point — the sharded fleet reports without any
        # call-site changes)
        register_sharded_store(store, registry=registry)
        return
    stats_fn = getattr(store, "telemetry_stats", None)
    if stats_fn is None:
        return
    registry = registry or global_registry()
    key = id(store)
    with _GLOBAL_LOCK:
        if key in _REGISTERED_STORES:
            return
        label = str(len(_REGISTERED_STORES))
        _REGISTERED_STORES[key] = label
    collections = registry.gauge(
        "lo_store_collections",
        "Collections resident in the store",
        labels=("store",),
    )
    wal_bytes = registry.gauge(
        "lo_store_wal_bytes",
        "Bytes in the store's on-disk WAL",
        labels=("store",),
    )
    spill_bytes = registry.gauge(
        "lo_store_spill_bytes",
        "Bytes of column payloads spilled to disk-backed mappings",
        labels=("store",),
    )
    if role is not None:
        replication_lag = registry.gauge(
            "lo_store_replication_lag",
            "Acknowledged WAL records this follower has not applied yet",
            labels=("store",),
        )
        loss_window = registry.gauge(
            "lo_store_loss_window",
            "Records in the measured loss window of the last takeover",
            labels=("store",),
        )
        unreplicated_acks = registry.gauge(
            "lo_store_unreplicated_acks",
            "Writes acknowledged after the sync-replication wait timed out",
            labels=("store",),
        )

    def collect(_registry: MetricsRegistry) -> None:
        stats = stats_fn()
        collections.labels(label).set(stats["collections"])
        wal_bytes.labels(label).set(stats["wal_bytes"])
        spill_bytes.labels(label).set(stats["spill_bytes"])
        if role is not None:
            poller = role.get("poller")
            replication_lag.labels(label).set(
                poller.lag if poller is not None else 0
            )
            loss = role.get("loss_window") or {}
            loss_window.labels(label).set(loss.get("records", 0) or 0)
            unreplicated_acks.labels(label).set(
                role.get("unreplicated_acks", 0)
            )

    registry.register_collector(collect)


def register_sharded_store(
    store: object, registry: Optional[MetricsRegistry] = None
) -> None:
    """Expose a sharded client's fleet view on ``/metrics``
    (docs/observability.md, docs/dataplane.md): per-shard occupancy
    gauges (``lo_store_shard_collections`` / ``_wal_bytes`` /
    ``_spill_bytes``, labelled by shard index with the meta group at
    ``0``), the last observed shard-map rev
    (``lo_store_shardmap_rev``), and the scatter-gather fan-out
    histogram (``lo_store_shard_fanout`` — how many groups each routed
    call actually touched; a fleet whose reads keep fanning out to one
    group is mis-striped). Occupancy is polled from each group's
    ``/health`` at scrape time; a group mid-failover loses its gauges
    for that scrape, never the endpoint. Idempotent per store
    instance."""
    registry = registry or global_registry()
    key = id(store)
    with _GLOBAL_LOCK:
        if key in _REGISTERED_STORES:
            return
        _REGISTERED_STORES[key] = f"shard-fleet-{len(_REGISTERED_STORES)}"
    shard_collections = registry.gauge(
        "lo_store_shard_collections",
        "Collections resident on the shard group",
        labels=("shard",),
    )
    shard_wal_bytes = registry.gauge(
        "lo_store_shard_wal_bytes",
        "Bytes in the shard group's on-disk WAL",
        labels=("shard",),
    )
    shard_spill_bytes = registry.gauge(
        "lo_store_shard_spill_bytes",
        "Bytes of column payloads the shard group spilled to disk",
        labels=("shard",),
    )
    shardmap_rev = registry.gauge(
        "lo_store_shardmap_rev",
        "Last observed rev of the shard-map collection on the meta group",
    )
    fanout = registry.histogram(
        "lo_store_shard_fanout",
        "Shard groups touched per scatter-gather store call",
        buckets=(1, 2, 4, 8, 16, 32),
    )
    # the client-side hook shardstore.ShardedStore calls with each
    # routed call's width
    store.on_fanout = fanout.observe

    def collect(_registry: MetricsRegistry) -> None:
        for shard, stats in enumerate(store.shard_occupancy()):
            if not stats:
                continue  # group unreachable this scrape
            label = str(shard)
            shard_collections.labels(label).set(stats.get("collections", 0))
            shard_wal_bytes.labels(label).set(stats.get("wal_bytes", 0))
            shard_spill_bytes.labels(label).set(stats.get("spill_bytes", 0))
        shardmap_rev.set(store.shardmap_rev())

    registry.register_collector(collect)
