"""Declarative SLO rules evaluated against the in-store TSDB.

Each rule names a metric family, a windowed statistic (``p99``,
``rate``, ``last``), a comparison, and a threshold; evaluation walks
``__lo_metrics__`` (telemetry/tsdb.py) per instance and reports the
worst offender. Results surface three ways:

- ``GET /debug/slo`` (utils/web.py) — ok/burning per rule with the
  offending instance and observed value;
- a ``degraded`` field on ``/health`` — any burning rule flips it;
- ``lo_slo_burning{rule}`` gauges on ``/metrics`` — republished each
  scrape tick by the collector, so alerting closes the loop: the chaos
  drills (testing/faults.py) can assert a fault is *visible*, not just
  survived.

Thresholds are knobs (``LO_SLO_*``, preflight-validated in
deploy/run.sh, plumbed via the cluster manifest's ``slo`` section);
evaluation is cached per ``__lo_metrics__`` rev so a polled ``/health``
costs one rev probe, not a re-evaluation, until new samples land.
"""

from __future__ import annotations

import threading
from typing import Optional

from learningorchestra_tpu.sched.config import _float_env, _int_env
from learningorchestra_tpu.telemetry import metrics as _metrics
from learningorchestra_tpu.telemetry import tsdb as _tsdb


# --- knobs -------------------------------------------------------------------

def slo_window_s() -> float:
    """Evaluation window in seconds (``LO_SLO_WINDOW_S``, > 0)."""
    value = _float_env("LO_SLO_WINDOW_S", 600.0)
    if value <= 0:
        raise ValueError(f"LO_SLO_WINDOW_S must be > 0, got {value}")
    return value


def slo_serve_p99_s() -> float:
    """Serving latency objective: burning when the windowed p99 of
    ``lo_serve_request_seconds`` exceeds this (``LO_SLO_SERVE_P99_S``
    seconds, >= 0)."""
    return _float_env("LO_SLO_SERVE_P99_S", 1.0)


def slo_5xx_rate() -> float:
    """Error-rate objective: burning when 5xx responses per second
    (windowed, summed across routes) exceed this
    (``LO_SLO_5XX_RATE``, >= 0)."""
    return _float_env("LO_SLO_5XX_RATE", 0.5)


def slo_queue_depth() -> int:
    """Backlog objective: burning when ``lo_sched_queue_depth`` last
    sampled above this (``LO_SLO_QUEUE_DEPTH``, integral >= 1 — the
    default tracks ``LO_SCHED_QUEUE_CAP``'s default, so burning means
    admission control is about to 429)."""
    return _int_env("LO_SLO_QUEUE_DEPTH", 64)


def slo_replication_lag() -> int:
    """Durability objective: burning when a follower's
    ``lo_store_replication_lag`` last sampled above this many WAL
    records (``LO_SLO_REPL_LAG``, integral >= 1)."""
    return _int_env("LO_SLO_REPL_LAG", 1000)


def validate_env() -> None:
    """Deploy preflight hook (deploy/run.sh): force every SLO knob
    through its parser so a malformed value fails the boot, not the
    first evaluation tick."""
    slo_window_s()
    slo_serve_p99_s()
    slo_5xx_rate()
    slo_queue_depth()
    slo_replication_lag()


# --- rules -------------------------------------------------------------------

class Rule:
    """One objective: ``stat`` of ``family`` over ``window_s`` compared
    against ``threshold`` (burning when ``value <op> threshold``)."""

    __slots__ = ("name", "family", "stat", "op", "threshold", "window_s")

    def __init__(self, name, family, stat, op, threshold, window_s):
        self.name = name
        self.family = family
        self.stat = stat
        self.op = op
        self.threshold = threshold
        self.window_s = window_s

    def breached(self, value: float) -> bool:
        return value > self.threshold if self.op == ">" else (
            value < self.threshold
        )

    def as_dict(self) -> dict:
        return {
            "rule": self.name,
            "family": self.family,
            "stat": self.stat,
            "op": self.op,
            "threshold": self.threshold,
            "window_s": self.window_s,
        }


def default_rules() -> list[Rule]:
    window = slo_window_s()
    return [
        Rule(
            "serve_p99", "lo_serve_request_seconds", "p99", ">",
            slo_serve_p99_s(), window,
        ),
        Rule(
            "http_5xx_rate", _tsdb.DERIVED_5XX, "rate", ">",
            slo_5xx_rate(), window,
        ),
        Rule(
            "sched_queue_depth", "lo_sched_queue_depth", "last", ">",
            float(slo_queue_depth()), window,
        ),
        Rule(
            "store_replication_lag", "lo_store_replication_lag", "last",
            ">", float(slo_replication_lag()), window,
        ),
    ]


# --- evaluation --------------------------------------------------------------

def evaluate(
    store,
    rules: Optional[list[Rule]] = None,
    now: Optional[float] = None,
) -> dict:
    """All rules against the store's TSDB: per-rule ok/burning with the
    offending instance and observed value, plus the rolled-up
    ``degraded`` verdict ``/health`` reports."""
    rules = default_rules() if rules is None else rules
    out_rules = []
    burning = []
    for rule in rules:
        worst = None
        worst_instance = None
        for instance, points in _tsdb.history(store, rule.family).items():
            rolled = _tsdb.rollup(
                rule.family, points, window_s=rule.window_s, now=now
            )
            value = (rolled or {}).get(rule.stat)
            if value is None:
                continue
            if worst is None or (
                value > worst if rule.op == ">" else value < worst
            ):
                worst, worst_instance = value, instance
        entry = rule.as_dict()
        entry["value"] = worst
        entry["instance"] = worst_instance
        entry["burning"] = worst is not None and rule.breached(worst)
        if entry["burning"]:
            burning.append(rule.name)
        out_rules.append(entry)
    return {"rules": out_rules, "burning": burning, "degraded": bool(burning)}


_GAUGE = None
_GAUGE_LOCK = threading.Lock()


def _burning_gauge():
    global _GAUGE
    with _GAUGE_LOCK:
        if _GAUGE is None:
            _GAUGE = _metrics.global_registry().gauge(
                "lo_slo_burning",
                "1 while the SLO rule is breached, 0 otherwise",
                labels=("rule",),
            )
        return _GAUGE


def publish(
    store,
    rules: Optional[list[Rule]] = None,
    now: Optional[float] = None,
) -> dict:
    """Evaluate and republish the ``lo_slo_burning{rule}`` gauges —
    called each collector tick, and by the cached :func:`status`."""
    result = evaluate(store, rules=rules, now=now)
    gauge = _burning_gauge()
    for entry in result["rules"]:
        gauge.labels(entry["rule"]).set(1.0 if entry["burning"] else 0.0)
    return result


# One cached evaluation per store, keyed by the ring collection's rev:
# a polled /health re-evaluates only after new samples land, never per
# request. Keyed by id(store) — stores are process-lifetime objects and
# the cache is advisory (a stale hit after id reuse re-keys on the next
# rev mismatch).
_STATUS_CACHE: dict[int, tuple[int, dict]] = {}
_STATUS_LOCK = threading.Lock()


def status(store, now: Optional[float] = None) -> dict:
    rev = store.collection_rev(_tsdb.COLLECTION)
    with _STATUS_LOCK:
        cached = _STATUS_CACHE.get(id(store))
        if cached is not None and cached[0] == rev and now is None:
            return cached[1]
    result = publish(store, now=now)
    with _STATUS_LOCK:
        _STATUS_CACHE[id(store)] = (rev, result)
    return result
