"""Process-wide telemetry: a metrics registry and span-based tracing.

The reference's only observability artifact is a wall-clock ``fit_time``
in prediction metadata (SURVEY.md §5: "Tracing / profiling: absent").
This package closes the Dapper-style gap: every REST request gets a
correlation ID (utils/web.py middleware) that rides job records
(core/jobs.py), the SPMD broadcast envelope (parallel/spmd.py) and
``PhaseTimer`` phase timings (utils/profiling.py) as a single span tree,
and every :class:`~learningorchestra_tpu.utils.web.WebApp` exposes a
``GET /metrics`` Prometheus text endpoint over one process-wide
registry — stdlib only, no prometheus_client dependency.
"""

from learningorchestra_tpu.telemetry.metrics import (
    MetricsRegistry,
    global_registry,
    register_store,
)
from learningorchestra_tpu.telemetry.tracing import (
    Span,
    Trace,
    activate,
    add_attr,
    annotate,
    attach,
    capture,
    current_correlation_id,
    current_trace,
    mint_correlation_id,
    record_span,
    span,
)

__all__ = [
    "MetricsRegistry",
    "Span",
    "Trace",
    "activate",
    "add_attr",
    "annotate",
    "attach",
    "capture",
    "current_correlation_id",
    "current_trace",
    "global_registry",
    "mint_correlation_id",
    "record_span",
    "register_store",
    "span",
]
