"""Flight recorder: performance attribution on top of the span tree.

PR 2's telemetry says *that* a request was slow; this module says *why*.
Three instruments, all stdlib:

- **Byte-flow accounting** (:func:`account_wire` / :func:`account_h2d` /
  :func:`account_d2h` / :func:`account_decode` / :func:`account_compile`):
  the store wire, the devcache's host↔device transfers, the frame
  decoder, and the XLA compiler report bytes-and-seconds into process
  counters (``lo_wire_bytes_total``, ``lo_h2d_bytes_total``,
  ``lo_d2h_bytes_total``, ``lo_decode_seconds_total``,
  ``lo_compile_events_total``/``lo_compile_seconds_total``) — the same
  sites stamp the active span, so one instrumentation pass feeds both
  Prometheus and the per-job timeline.
- **Chrome trace-event export** (:func:`chrome_trace`): a job's span
  tree rendered as Chrome/Perfetto trace JSON — one row per thread
  (spans carry OS thread ids since this PR), ``X`` complete events with
  microsecond ``ts``/``dur``, and ``C`` counter tracks accumulating
  wire/H2D/D2H bytes along the timeline. Served at
  ``GET /jobs/<name>/profile`` (utils/web.py); ``?format=summary``
  returns the per-phase seconds/bytes/rows-per-second rollup
  (:func:`trace_summary`) instead.
- **Sampling profiler** (:func:`sample_stacks`): a wall-clock
  ``sys._current_frames()`` sampler serving folded flamegraph stacks at
  ``GET /debug/profile?seconds=N`` on every service. Default-off (no
  background thread until a request asks); ``LO_PROF_HZ=0`` disables
  the endpoint entirely. Concurrent requests SHARE one sampling thread
  (each returns its own window's delta), so N curious operators cost
  the same as one — the bounded-overhead property the tests pin.

Import cost: stdlib only; the metrics registry is imported lazily so
this module never forces jax or werkzeug into a process that only wants
the accounting helpers.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import Counter
from typing import Optional

from learningorchestra_tpu.telemetry import tracing as _tracing

# --- knobs -------------------------------------------------------------------

DEFAULT_HZ = 47  # prime: avoids aliasing with periodic work
DEFAULT_WINDOW_S = 60.0


def prof_hz() -> int:
    """``LO_PROF_HZ``: sampling-profiler rate in samples/second.
    ``0`` disables ``GET /debug/profile``; the default (47 Hz) keeps the
    endpoint available while costing nothing until a request samples."""
    from learningorchestra_tpu.sched.config import _int_env

    return _int_env("LO_PROF_HZ", DEFAULT_HZ, minimum=0)


def prof_window_s() -> float:
    """``LO_PROF_WINDOW_S``: the longest window one ``/debug/profile``
    request may sample for (its ``?seconds=`` is clamped to this)."""
    from learningorchestra_tpu.sched.config import _float_env

    value = _float_env("LO_PROF_WINDOW_S", DEFAULT_WINDOW_S, minimum=0.0)
    if value <= 0:  # the shared helper's minimum is inclusive
        raise ValueError(f"LO_PROF_WINDOW_S must be > 0, got {value}")
    return value


def validate_env() -> None:
    """Fail fast on malformed ``LO_PROF_*`` knobs — deploy/run.sh's
    preflight calls this so a typo refuses bring-up instead of silently
    serving an unprofiled stack."""
    prof_hz()
    prof_window_s()


# --- byte-flow metrics -------------------------------------------------------

_METRICS: Optional[dict] = None
_METRICS_LOCK = threading.Lock()


def _flow_metrics() -> dict:
    global _METRICS
    with _METRICS_LOCK:
        if _METRICS is None:
            from learningorchestra_tpu.telemetry.metrics import global_registry

            registry = global_registry()
            _METRICS = {
                "wire": registry.counter(
                    "lo_wire_bytes_total",
                    "Store-wire payload bytes moved (pre-compression)",
                    labels=("direction", "collection"),
                ),
                "h2d": registry.counter(
                    "lo_h2d_bytes_total",
                    "Bytes transferred host to device",
                ),
                "d2h": registry.counter(
                    "lo_d2h_bytes_total",
                    "Bytes transferred device to host",
                ),
                "decode": registry.counter(
                    "lo_decode_seconds_total",
                    "Seconds decoding wire frames into host columns",
                    labels=("collection",),
                ),
                "shm": registry.counter(
                    "lo_shm_bytes_total",
                    "Frame bytes served through the shared-memory ring "
                    "instead of the HTTP body",
                    labels=("collection",),
                ),
                "compile_events": registry.counter(
                    "lo_compile_events_total",
                    "XLA persistent-cache outcomes observed",
                    labels=("result", "source"),
                ),
                "compile_seconds": registry.counter(
                    "lo_compile_seconds_total",
                    "Seconds inside the XLA compiler",
                ),
            }
        return _METRICS


def account_wire(direction: str, collection: str, nbytes: int) -> None:
    """One wire payload moved (``direction`` = read|write). Counts into
    ``lo_wire_bytes_total`` and accumulates ``wire_bytes`` on the
    current span, so the job timeline and the Prometheus totals agree
    by construction."""
    _flow_metrics()["wire"].labels(direction, collection).inc(nbytes)
    _tracing.add_attr("wire_bytes", int(nbytes))


def account_h2d(nbytes: int) -> None:
    _flow_metrics()["h2d"].inc(nbytes)
    _tracing.add_attr("h2d_bytes", int(nbytes))


def account_d2h(nbytes: int) -> None:
    _flow_metrics()["d2h"].inc(nbytes)
    _tracing.add_attr("d2h_bytes", int(nbytes))


def account_decode(collection: str, seconds: float) -> None:
    _flow_metrics()["decode"].labels(collection).inc(seconds)
    _tracing.add_attr("decode_s", round(seconds, 6))


def account_shm(collection: str, nbytes: int) -> None:
    """One frame served through the shared-memory ring (core/shmring.py)
    — these bytes never rode the HTTP body, so they count here instead
    of ``lo_wire_bytes_total``."""
    _flow_metrics()["shm"].labels(collection).inc(nbytes)
    _tracing.add_attr("shm_bytes", int(nbytes))


def flow_totals() -> dict:
    """Current byte-flow totals summed over label sets — the snapshot
    bench.py diffs around a measured section (wire/decode/H2D deltas
    for the warm product build, per-transport wire benchmarks)."""
    metrics = _flow_metrics()
    out = {
        "wire_read_bytes": 0.0,
        "wire_write_bytes": 0.0,
        "shm_bytes": 0.0,
        "decode_s": 0.0,
        "h2d_bytes": 0.0,
        "d2h_bytes": 0.0,
    }
    wire = metrics["wire"]
    with wire._lock:
        for key, child in wire._children.items():
            out_key = f"wire_{key[0]}_bytes"
            out[out_key] = out.get(out_key, 0.0) + child.value
    for out_key, name in (
        ("shm_bytes", "shm"),
        ("decode_s", "decode"),
    ):
        metric = metrics[name]
        with metric._lock:
            out[out_key] = sum(
                child.value for child in metric._children.values()
            )
    for out_key, name in (("h2d_bytes", "h2d"), ("d2h_bytes", "d2h")):
        metric = metrics[name]
        with metric._lock:
            out[out_key] = sum(
                child.value for child in metric._children.values()
            )
    return out


def account_compile(
    result: Optional[str] = None,
    seconds: Optional[float] = None,
    source: str = "jit",
) -> None:
    """A persistent-cache event (``result`` = hit|miss) and/or compile
    seconds — utils/jitcache.py's jax.monitoring listeners feed this.
    ``source`` says which lane triggered the compile: ``jit`` (request
    path), ``aot`` (the boot precompile pass) or ``fleetcache`` (the
    warm pass replaying fleet-fetched artifacts), so a dashboard can
    tell boot-time compile spend from user-facing compile stalls."""
    metrics = _flow_metrics()
    if result is not None:
        metrics["compile_events"].labels(result, source).inc()
    if seconds is not None:
        metrics["compile_seconds"].inc(seconds)


# --- Chrome trace-event export ----------------------------------------------

# meta keys the exporter treats as byte flows (span attr -> counter track)
_BYTE_ATTRS = ("wire_bytes", "h2d_bytes", "d2h_bytes")


def _walk(span_dict: dict, depth: int = 0):
    yield span_dict, depth
    for child in span_dict.get("children", ()):
        yield from _walk(child, depth + 1)


def _iter_spans(trace_dict: dict):
    for root in trace_dict.get("spans", ()):
        yield from _walk(root)


def span_events(span_roots, pid: int, t0: float) -> list[dict]:
    """Span dict trees → Chrome ``ph: "X"`` complete events laid out
    one row per OS thread, plus per-thread ``M`` name/sort metadata.
    Shared by :func:`chrome_trace` (one process) and the fleet stitcher
    (telemetry/stitch.py — one ``pid`` row per plane member, all
    anchored to a common ``t0``)."""
    spans = [
        (span_dict, depth)
        for root in span_roots
        for span_dict, depth in _walk(root)
        if span_dict.get("start_ts") is not None
    ]
    events: list[dict] = []
    tids = []
    for span_dict, _depth in spans:
        tid = span_dict.get("tid") or 0
        if tid not in tids:
            tids.append(tid)
        ts_us = round((span_dict["start_ts"] - t0) * 1e6, 1)
        duration = span_dict.get("duration_s")
        event = {
            "name": span_dict["name"],
            "ph": "X",
            "ts": ts_us,
            "dur": (
                0.0 if duration is None else round(duration * 1e6, 1)
            ),
            "pid": pid,
            "tid": tid,
            "cat": span_dict["name"].split(":", 1)[0],
        }
        meta = span_dict.get("meta")
        if meta:
            event["args"] = meta
        events.append(event)
    # thread rows get names so Perfetto's left rail reads as a legend
    for index, tid in enumerate(sorted(tids)):
        events.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {"name": f"thread-{tid}"},
            }
        )
        events.append(
            {
                "name": "thread_sort_index",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {"sort_index": index},
            }
        )
    return events


def chrome_trace(trace) -> dict:
    """A trace (``Trace`` or its ``as_dict()``) as Chrome trace-event
    JSON — load it in Perfetto (ui.perfetto.dev) or ``chrome://tracing``.

    Spans become ``ph: "X"`` complete events laid out one row per OS
    thread; ``ts`` is microseconds relative to the earliest span (the
    absolute epoch anchor rides ``otherData``); byte-carrying spans
    additionally feed cumulative ``ph: "C"`` counter tracks (one series
    per flow: wire/h2d/d2h), so Perfetto draws bytes-moved-so-far under
    the timeline."""
    if hasattr(trace, "as_dict"):
        trace = trace.as_dict()
    spans = [
        (span_dict, depth)
        for span_dict, depth in _iter_spans(trace)
        if span_dict.get("start_ts") is not None
    ]
    t0 = min(
        (span_dict["start_ts"] for span_dict, _ in spans), default=0.0
    )
    pid = os.getpid()
    events = span_events(trace.get("spans", ()), pid, t0)
    # cumulative byte counters along the timeline, stamped at each
    # contributing span's END (when the bytes have actually moved)
    totals = dict.fromkeys(_BYTE_ATTRS, 0)
    flows = []
    for span_dict, _depth in spans:
        meta = span_dict.get("meta") or {}
        if any(meta.get(attr) for attr in _BYTE_ATTRS):
            end = span_dict["start_ts"] + (span_dict.get("duration_s") or 0.0)
            flows.append((end, meta))
    for end, meta in sorted(flows, key=lambda item: item[0]):
        for attr in _BYTE_ATTRS:
            totals[attr] += int(meta.get(attr) or 0)
        events.append(
            {
                "name": "bytes moved",
                "ph": "C",
                "ts": round((end - t0) * 1e6, 1),
                "pid": pid,
                "tid": 0,
                "args": {
                    attr.removesuffix("_bytes"): totals[attr]
                    for attr in _BYTE_ATTRS
                },
            }
        )
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "correlation_id": trace.get("correlation_id"),
            "name": trace.get("name"),
            "trace_start_ts": t0,
            "bytes_total": totals,
        },
    }


def trace_summary(trace) -> dict:
    """Per-phase rollup of a trace: for every span name, occurrence
    count, total seconds, bytes by flow, rows, and rows/second — the
    plain-JSON answer to "which phase moved" that ``bench.py
    --compare`` diffs across runs."""
    if hasattr(trace, "as_dict"):
        trace = trace.as_dict()
    phases: dict[str, dict] = {}
    wall_start, wall_end = None, None
    for span_dict, _depth in _iter_spans(trace):
        start = span_dict.get("start_ts")
        duration = span_dict.get("duration_s") or 0.0
        if start is not None:
            wall_start = start if wall_start is None else min(wall_start, start)
            wall_end = (
                start + duration
                if wall_end is None
                else max(wall_end, start + duration)
            )
        entry = phases.setdefault(
            span_dict["name"],
            {"count": 0, "seconds": 0.0, "rows": 0, "bytes": {}},
        )
        entry["count"] += 1
        entry["seconds"] += duration
        meta = span_dict.get("meta") or {}
        if isinstance(meta.get("rows"), (int, float)):
            entry["rows"] += int(meta["rows"])
        for attr in _BYTE_ATTRS:
            value = meta.get(attr)
            if value:
                entry["bytes"][attr.removesuffix("_bytes")] = (
                    entry["bytes"].get(attr.removesuffix("_bytes"), 0)
                    + int(value)
                )
        # a span's own payload size (write phases, serve forwards)
        if isinstance(meta.get("bytes"), (int, float)):
            entry["bytes"]["payload"] = entry["bytes"].get(
                "payload", 0
            ) + int(meta["bytes"])
    for entry in phases.values():
        entry["seconds"] = round(entry["seconds"], 6)
        if entry["rows"] and entry["seconds"] > 0:
            entry["rows_per_s"] = round(entry["rows"] / entry["seconds"], 1)
        if not entry["bytes"]:
            del entry["bytes"]
        if not entry["rows"]:
            del entry["rows"]
    return {
        "correlation_id": trace.get("correlation_id"),
        "name": trace.get("name"),
        "wall_s": (
            round(wall_end - wall_start, 6)
            if wall_start is not None
            else None
        ),
        "phases": phases,
    }


# --- sampling profiler -------------------------------------------------------


class _SamplerCore:
    """The process's ONE sampling thread, reference-counted.

    Requests ``acquire()`` a window; the first acquisition starts the
    thread, the last ``release()`` stops it. Each request reads the
    cumulative stack counts before and after its window and returns the
    delta, so concurrent ``/debug/profile`` requests share one thread's
    overhead instead of multiplying it — sampling cost is O(hz), never
    O(hz x clients)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counts: Counter = Counter()
        self._samples = 0
        self._users = 0
        self._thread: Optional[threading.Thread] = None
        self._hz = DEFAULT_HZ

    def acquire(self, hz: int) -> None:
        with self._lock:
            self._users += 1
            if self._thread is None:
                self._hz = hz
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name="lo-prof-sampler"
                )
                self._thread.start()

    def release(self) -> None:
        with self._lock:
            self._users -= 1
            if self._users <= 0:
                # every window's delta has been read by now (requests
                # snapshot BEFORE releasing) — drop the accumulated
                # stacks so repeated profiling of a long-lived threaded
                # server (one folded key per Thread-N handler name)
                # cannot grow this Counter for the life of the process
                self._counts.clear()
                self._samples = 0

    def snapshot(self) -> tuple[Counter, int]:
        with self._lock:
            return Counter(self._counts), self._samples

    def _run(self) -> None:
        # _hz is written under the lock in acquire(); snapshot it under
        # the same lock (LO203) instead of racing a concurrent first
        # acquirer's assignment
        with self._lock:
            interval = 1.0 / max(self._hz, 1)
        me = threading.get_ident()
        while True:
            with self._lock:
                if self._users <= 0:
                    self._thread = None
                    return
            names = {
                thread.ident: thread.name for thread in threading.enumerate()
            }
            sampled = Counter()
            for ident, frame in sys._current_frames().items():
                if ident == me:
                    continue
                stack = []
                depth = 0
                while frame is not None and depth < 64:
                    code = frame.f_code
                    module = os.path.splitext(
                        os.path.basename(code.co_filename)
                    )[0]
                    stack.append(f"{module}.{code.co_name}")
                    frame = frame.f_back
                    depth += 1
                stack.append(names.get(ident, f"tid-{ident}"))
                sampled[";".join(reversed(stack))] += 1
            with self._lock:
                self._counts.update(sampled)
                self._samples += 1
            time.sleep(interval)


_SAMPLER = _SamplerCore()


def sample_stacks(
    seconds: float, hz: Optional[int] = None
) -> tuple[dict[str, int], int]:
    """Sample every thread's Python stack for ``seconds`` and return
    ``(folded_stacks, samples)``: keys are semicolon-joined frames
    rooted at the thread name (flamegraph.pl / speedscope folded
    format), values are sample counts. Raises ``RuntimeError`` when
    profiling is disabled (``LO_PROF_HZ=0``)."""
    hz = prof_hz() if hz is None else hz
    if hz <= 0:
        raise RuntimeError("sampling profiler disabled (LO_PROF_HZ=0)")
    seconds = min(max(seconds, 1.0 / hz), prof_window_s())
    _SAMPLER.acquire(hz)
    try:
        before, samples_before = _SAMPLER.snapshot()
        time.sleep(seconds)
        after, samples_after = _SAMPLER.snapshot()
    finally:
        _SAMPLER.release()
    delta = after - before
    return dict(delta), samples_after - samples_before


def folded_text(stacks: dict[str, int]) -> str:
    """Folded stacks as text, heaviest first — pipe straight into
    flamegraph.pl or paste into speedscope.app."""
    lines = [
        f"{stack} {count}"
        for stack, count in sorted(
            stacks.items(), key=lambda item: (-item[1], item[0])
        )
    ]
    return "\n".join(lines) + ("\n" if lines else "")
