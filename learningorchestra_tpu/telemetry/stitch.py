"""Cross-process trace stitching: one Chrome trace for one cid.

The Dapper reconstruction step: every process keeps a per-cid span
export buffer (telemetry/tracing.py) drained by ``GET /debug/spans``;
this module fans out to the plane members named in ``LO_PLANE_MEMBERS``
(the cluster manifest's service URLs — deploy/cluster.py sets it in
every member's environment), merges each member's span groups with the
local buffer, and lays the result out as ONE Chrome trace-event JSON:
one process row per ``service@pid`` group (``M`` ``process_name``
metadata events), threads within it, all anchored to a common ``t0``.
``GET /traces/<cid>`` on every service (utils/web.py) serves exactly
this — a client-driven projection→histogram→build→predict pipeline
renders as a single timeline.

Groups are keyed ``service@pid``, so fanning out to a member list that
includes the serving process itself dedupes (the HTTP copy replaces
the identical local group) instead of duplicating rows. Members that
are down or mid-restart are skipped — a partial stitch beats a 502.
"""

from __future__ import annotations

import json
import os
import urllib.request
from typing import Optional

from learningorchestra_tpu.telemetry import profile as _profile
from learningorchestra_tpu.telemetry import tracing as _tracing

FETCH_TIMEOUT_S = 2.0


def plane_members() -> list[str]:
    """Base URLs of the fleet's span sources, from the comma-separated
    ``LO_PLANE_MEMBERS`` (empty = local-only: single-process runners
    stitch from their own buffer)."""
    # lo: allow[LO301,LO305] free-form URL list, no domain to preflight
    raw = os.environ.get("LO_PLANE_MEMBERS", "")
    return [url.strip().rstrip("/") for url in raw.split(",") if url.strip()]


def fetch_member_spans(
    base_url: str, correlation_id: str, since: Optional[float] = None
) -> dict:
    """One member's span groups for one cid; ``{}`` on any failure —
    stitching is best-effort per member."""
    url = f"{base_url}/debug/spans?cid={correlation_id}"
    if since is not None:
        url += f"&since={since}"
    try:
        with urllib.request.urlopen(url, timeout=FETCH_TIMEOUT_S) as resp:
            payload = json.loads(resp.read().decode("utf-8"))
    except Exception:  # noqa: BLE001 — down/mid-restart member = skip
        return {}
    entry = (payload.get("result") or {}).get(correlation_id) or {}
    groups = entry.get("groups")
    return groups if isinstance(groups, dict) else {}


def collect_groups(
    correlation_id: str,
    members: Optional[list[str]] = None,
    since: Optional[float] = None,
) -> dict[str, dict]:
    """Local buffer + every reachable member, merged by group key."""
    local = _tracing.exported_spans(correlation_id, since=since)
    groups = dict((local.get(correlation_id) or {}).get("groups") or {})
    for member in plane_members() if members is None else members:
        for proc, group in fetch_member_spans(
            member, correlation_id, since=since
        ).items():
            if isinstance(group, dict) and group.get("spans"):
                groups[proc] = group
    return groups


def stitched_trace(
    correlation_id: str,
    members: Optional[list[str]] = None,
    since: Optional[float] = None,
) -> dict:
    """The merged multi-process Chrome trace for one cid. Process rows
    (``pid``) are the sorted group keys, so the layout is deterministic
    regardless of which member answered first; ``otherData.processes``
    maps the synthetic pids back to ``service@pid`` identities."""
    groups = collect_groups(correlation_id, members=members, since=since)
    starts = [
        span["start_ts"]
        for group in groups.values()
        for span in group.get("spans", ())
        if span.get("start_ts") is not None
    ]
    t0 = min(starts, default=0.0)
    events: list[dict] = []
    processes: dict[int, str] = {}
    for index, proc in enumerate(sorted(groups)):
        group = groups[proc]
        events.extend(
            _profile.span_events(group.get("spans", ()), index, t0)
        )
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": index,
                "args": {"name": proc},
            }
        )
        events.append(
            {
                "name": "process_sort_index",
                "ph": "M",
                "pid": index,
                "args": {"sort_index": index},
            }
        )
        processes[index] = proc
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "correlation_id": correlation_id,
            "trace_start_ts": t0,
            "processes": processes,
        },
    }
