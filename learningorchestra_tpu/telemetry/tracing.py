"""Span-based tracing with request correlation IDs.

One :class:`Trace` is the whole story of one request: the REST
middleware (utils/web.py) mints a correlation ID, the job manager binds
the job's work to a trace carrying that ID, the SPMD dispatcher rides it
on the broadcast envelope so worker-side spans are attributable, and
``PhaseTimer`` phases land as spans — so ``GET /jobs/<name>/trace``
answers "where did this request's time go" across every layer.

Context propagation is ``contextvars``-based: span nesting follows the
thread of execution; fan-out threads (the builder's per-classifier pool)
re-attach with :func:`capture`/:func:`attach` because ``contextvars`` do
not cross ``ThreadPoolExecutor`` boundaries. :func:`span` is a cheap
no-op when no trace is active, so instrumented library code costs
nothing outside a request.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import threading
import time
import uuid
from typing import Iterator, Optional

_TRACE: contextvars.ContextVar[Optional["Trace"]] = contextvars.ContextVar(
    "lo_trace", default=None
)
_SPAN: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "lo_span", default=None
)

CORRELATION_HEADER = "X-Correlation-Id"


def mint_correlation_id() -> str:
    return uuid.uuid4().hex[:16]


class Span:
    """One timed operation; children nest within the parent's window.

    Flight-recorder fields (telemetry/profile.py): ``start_ts`` is the
    epoch anchor, ``duration_s`` comes from the monotonic clock (so two
    spans on different threads order correctly within a process), and
    ``tid`` is the OS thread id — the Chrome trace-event exporter lays
    spans out one row per thread from exactly these three fields. Typed
    attributes (bytes moved, rows, dtype, compile hit/miss) ride
    ``meta``."""

    __slots__ = (
        "name", "start_ts", "duration_s", "meta", "children", "tid",
        "_t0", "_trace",
    )

    def __init__(self, name: str, trace: "Trace", meta: Optional[dict] = None):
        self.name = name
        self.start_ts = time.time()
        self.duration_s: Optional[float] = None
        self.meta = meta or {}
        self.children: list[Span] = []
        self.tid = threading.get_native_id()
        self._t0 = time.perf_counter()
        self._trace = trace

    def finish(self) -> None:
        self.duration_s = time.perf_counter() - self._t0

    @property
    def end_ts(self) -> Optional[float]:
        """Epoch end: the start anchor plus the monotonic duration."""
        if self.duration_s is None:
            return None
        return self.start_ts + self.duration_s

    def as_dict(self) -> dict:
        out = {
            "name": self.name,
            "start_ts": round(self.start_ts, 6),
            "duration_s": (
                None if self.duration_s is None else round(self.duration_s, 6)
            ),
            "tid": self.tid,
            "children": [child.as_dict() for child in self.children],
        }
        if self.meta:
            out["meta"] = self.meta
        return out


class Trace:
    """A correlation ID plus its span tree. Thread-safe: fan-out threads
    attach spans concurrently (ml/builder.py's classifier pool)."""

    def __init__(self, correlation_id: Optional[str] = None, name: str = ""):
        self.correlation_id = correlation_id or mint_correlation_id()
        self.name = name
        self.spans: list[Span] = []
        self._lock = threading.Lock()

    def _add(self, span_obj: Span, parent: Optional[Span]) -> None:
        with self._lock:
            if parent is not None:
                parent.children.append(span_obj)
            else:
                self.spans.append(span_obj)

    def as_dict(self) -> dict:
        with self._lock:
            return {
                "correlation_id": self.correlation_id,
                "name": self.name,
                "spans": [span_obj.as_dict() for span_obj in self.spans],
            }


def current_trace() -> Optional[Trace]:
    return _TRACE.get()


def current_correlation_id() -> Optional[str]:
    trace = _TRACE.get()
    return trace.correlation_id if trace is not None else None


@contextlib.contextmanager
def activate(trace: Trace) -> Iterator[Trace]:
    """Make ``trace`` the ambient trace; new spans root at its top."""
    trace_token = _TRACE.set(trace)
    span_token = _SPAN.set(None)
    try:
        yield trace
    finally:
        _SPAN.reset(span_token)
        _TRACE.reset(trace_token)


def capture() -> tuple[Optional[Trace], Optional[Span]]:
    """Snapshot the ambient (trace, span) for hand-off to a pool thread."""
    return _TRACE.get(), _SPAN.get()


@contextlib.contextmanager
def attach(
    context: tuple[Optional[Trace], Optional[Span]]
) -> Iterator[None]:
    """Adopt a captured context in another thread: spans opened inside
    become children of the captured span, in the captured trace."""
    trace, parent = context
    trace_token = _TRACE.set(trace)
    span_token = _SPAN.set(parent)
    try:
        yield
    finally:
        _SPAN.reset(span_token)
        _TRACE.reset(trace_token)


@contextlib.contextmanager
def span(name: str, **meta) -> Iterator[Optional[Span]]:
    """Record a timed span under the ambient trace; no-op without one."""
    trace = _TRACE.get()
    if trace is None:
        yield None
        return
    parent = _SPAN.get()
    span_obj = Span(name, trace, meta=meta or None)
    trace._add(span_obj, parent)
    token = _SPAN.set(span_obj)
    try:
        yield span_obj
    finally:
        span_obj.finish()
        _SPAN.reset(token)


def annotate(**attrs) -> None:
    """Set typed attributes on the CURRENT span (no-op without one) —
    for instrumentation sites that learn a fact (registry hit/miss,
    decoded byte count) inside a span someone else opened."""
    span_obj = _SPAN.get()
    if span_obj is not None:
        span_obj.meta.update(attrs)


def add_attr(name: str, amount: float) -> None:
    """Accumulate a numeric attribute on the current span (no-op
    without one): ``bytes``-style totals built up across a chunk loop
    land on the one surrounding span instead of needing a span per
    chunk."""
    span_obj = _SPAN.get()
    if span_obj is not None:
        span_obj.meta[name] = span_obj.meta.get(name, 0) + amount


def record_span(name: str, duration_s: float, **meta) -> Optional[Span]:
    """Append an already-finished span ending NOW to the active trace
    (no-op without one). For events whose timing arrives as a duration
    after the fact — jax.monitoring hands compile times to
    utils/jitcache.py this way — so the timeline still shows WHEN the
    compiler ran and for how long."""
    trace = _TRACE.get()
    if trace is None:
        return None
    span_obj = Span(name, trace, meta=meta or None)
    span_obj.start_ts = time.time() - duration_s
    span_obj.duration_s = duration_s
    trace._add(span_obj, _SPAN.get())
    return span_obj


# --- worker-side trace retention -------------------------------------------
# SPMD worker processes have no REST surface; their traces (attributed by
# the broadcast correlation ID) park in a bounded ring an operator can
# dump (parallel/spmd.py logs the correlation id per job, and tests
# assert attribution through here).
_RECENT: "dict[str, Trace]" = {}
_RECENT_ORDER: list[str] = []
_RECENT_LOCK = threading.Lock()


def trace_ring() -> int:
    """Entries kept in the remembered-trace ring AND the per-cid span
    export buffer (``LO_TRACE_RING``, strictly integral >= 1 — was a
    hardcoded 256). Size it to the scrape interval: the stitcher
    (telemetry/stitch.py) can only merge spans that have not been
    evicted by newer requests before it fans out."""
    from learningorchestra_tpu.sched.config import _int_env

    return _int_env("LO_TRACE_RING", 256)


def remember_trace(trace: Trace) -> None:
    with _RECENT_LOCK:
        if trace.correlation_id not in _RECENT:
            _RECENT_ORDER.append(trace.correlation_id)
        _RECENT[trace.correlation_id] = trace
        limit = trace_ring()
        while len(_RECENT_ORDER) > limit:
            _RECENT.pop(_RECENT_ORDER.pop(0), None)


def recall_trace(correlation_id: str) -> Optional[Trace]:
    with _RECENT_LOCK:
        return _RECENT.get(correlation_id)


# --- cross-process span export ---------------------------------------------
# The Dapper shape: every process keeps a bounded per-cid buffer of its
# finished spans, drained over HTTP (``GET /debug/spans?cid=…`` —
# utils/web.py registers it on every app) and merged fleet-wide by the
# stitcher (telemetry/stitch.py). Groups are keyed "service@pid" so a
# multi-service process contributes one row per service and a fan-out
# that reaches the same process twice (a member list naming ourselves)
# dedupes instead of duplicating.
_EXPORT: dict[str, dict] = {}
_EXPORT_ORDER: list[str] = []
_EXPORT_LOCK = threading.Lock()


def export_trace(trace: Trace, service: Optional[str] = None) -> None:
    """Snapshot a trace's finished spans into the export buffer. Cheap
    and safe to call per request (the REST middleware does) — empty
    traces are skipped, and both the cid ring and each group's span
    list are bounded by :func:`trace_ring`."""
    snapshot = trace.as_dict()
    spans = snapshot.get("spans") or []
    if not spans:
        return
    label = service or "proc"
    pid = os.getpid()
    proc = f"{label}@{pid}"
    with _EXPORT_LOCK:
        entry = _EXPORT.get(trace.correlation_id)
        if entry is None:
            entry = {"ts": 0.0, "groups": {}}
            _EXPORT[trace.correlation_id] = entry
            _EXPORT_ORDER.append(trace.correlation_id)
        group = entry["groups"].setdefault(
            proc, {"service": label, "pid": pid, "spans": []}
        )
        group["spans"].extend(spans)
        limit = trace_ring()
        del group["spans"][:-limit]
        entry["ts"] = time.time()
        while len(_EXPORT_ORDER) > limit:
            _EXPORT.pop(_EXPORT_ORDER.pop(0), None)


def exported_spans(
    correlation_id: Optional[str] = None, since: Optional[float] = None
) -> dict:
    """Read the export buffer: ``{cid: {"ts": last_update, "groups":
    {"service@pid": {"service", "pid", "spans": [...]}}}}``, filtered
    to one cid and/or to entries updated after ``since``. Reads do not
    consume — eviction is the ring's job — so a stitcher retry sees
    the same spans."""
    with _EXPORT_LOCK:
        cids = (
            [correlation_id]
            if correlation_id is not None
            else list(_EXPORT_ORDER)
        )
        out = {}
        for cid in cids:
            entry = _EXPORT.get(cid)
            if entry is None:
                continue
            if since is not None and entry["ts"] <= since:
                continue
            out[cid] = {
                "ts": entry["ts"],
                "groups": {
                    proc: {
                        "service": group["service"],
                        "pid": group["pid"],
                        "spans": list(group["spans"]),
                    }
                    for proc, group in entry["groups"].items()
                },
            }
        return out
