"""learningorchestra_tpu — a TPU-native distributed data-science framework.

A ground-up reimplementation of the capabilities of
hiperbolt/learningOrchestra (see /root/reference): CSV dataset ingestion
into a document store, column projection, field type conversion, value
histograms, PCA / t-SNE image plots, and a multi-classifier model builder
(logistic regression, decision tree, random forest, gradient-boosted
trees, naive bayes) with user-supplied preprocessing — exposed through
the same REST microservice APIs and Python client.

Where the reference delegates distributed compute to an Apache Spark
cluster (reference: microservices/spark_image/Dockerfile:1-37) and
storage to a MongoDB replica set (reference: docker-compose.yml:27-91),
this framework is JAX/XLA-first:

- datasets are columnar tables sharded over a ``jax.sharding.Mesh``
  (``parallel/``), with ``jax.lax`` collectives over ICI in place of RDD
  shuffles;
- the classifiers and decompositions are JAX programs that keep the
  FLOPs on the MXU (``models/``, ``ops/``);
- storage is a built-in document store with the same
  collection-of-documents + metadata-row contract (``core/store.py``);
- the REST layer (``services/``) and Python client (``client.py``)
  reproduce the reference's routes, ports, status codes and error
  strings so existing callers keep working.

Subpackages appear as they land; consult the repo README for current
status.
"""

__version__ = "0.1.0"
