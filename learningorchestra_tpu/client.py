"""Python client — API-compatible with `learning_orchestra_client` 1.0.1.

Reference: learning_orchestra_client/learning_orchestra_client/
__init__.py:1-370. Same classes (``Context``, ``DatabaseApi``,
``Projection``, ``DataTypeHandler``, ``Histogram``, ``Tsne``, ``Pca``,
``Model``), same method signatures, same hard-coded service ports, same
poll-until-``finished`` synchronization (3 s interval,
``AsyncronousWait``) and the same ``ResponseTreat`` semantics (pretty
JSON string by default, raise on 4xx, raw text on 5xx). A user script
written against the reference client runs against this framework by
changing only the import.
"""

from __future__ import annotations

import json
import time

import requests

cluster_url = None


class Context:
    def __init__(self, ip_from_cluster: str):
        global cluster_url
        cluster_url = "http://" + ip_from_cluster


class AsyncronousWait:
    WAIT_TIME = 3
    METADATA_INDEX = 0

    def wait(self, filename: str, pretty_response: bool = True) -> None:
        if pretty_response:
            print(
                "\n----------" + " WAITING " + filename + " FINISH " + "----------"
            )
        database_api = DatabaseApi()
        while True:
            time.sleep(self.WAIT_TIME)
            response = database_api.read_file(
                filename, limit=1, pretty_response=False
            )
            if len(response["result"]) == 0:
                continue
            if response["result"][self.METADATA_INDEX]["finished"]:
                break


class ResponseTreat:
    HTTP_CREATED = 201
    HTTP_SUCESS = 200
    HTTP_ERROR = 500

    def treatment(self, response, pretty_response: bool = True):
        if response.status_code >= self.HTTP_ERROR:
            return response.text
        elif response.status_code not in (self.HTTP_SUCESS, self.HTTP_CREATED):
            raise Exception(response.json()["result"])
        elif pretty_response:
            return json.dumps(response.json(), indent=2)
        else:
            return response.json()


class DatabaseApi:
    DATABASE_API_PORT = "5000"

    def __init__(self):
        global cluster_url
        self.url_base = cluster_url + ":" + self.DATABASE_API_PORT + "/files"
        self.asyncronous_wait = AsyncronousWait()

    def read_resume_files(self, pretty_response: bool = True):
        if pretty_response:
            print("\n----------" + " READ RESUME FILES " + "----------")
        return ResponseTreat().treatment(requests.get(self.url_base), pretty_response)

    def read_file(
        self, filename, skip=0, limit=10, query={}, pretty_response: bool = True
    ):
        if pretty_response:
            print("\n----------" + " READ FILE " + filename + " ----------")
        request_params = {"skip": str(skip), "limit": str(limit), "query": str(query)}
        response = requests.get(
            url=self.url_base + "/" + filename, params=request_params
        )
        return ResponseTreat().treatment(response, pretty_response)

    def create_file(self, filename, url, pretty_response: bool = True):
        if pretty_response:
            print("\n----------" + " CREATE FILE " + filename + " ----------")
        response = requests.post(
            url=self.url_base, json={"filename": filename, "url": url}
        )
        return ResponseTreat().treatment(response, pretty_response)

    def delete_file(self, filename, pretty_response: bool = True):
        if pretty_response:
            print("\n----------" + " DELETE FILE " + filename + " ----------")
        self.asyncronous_wait.wait(filename, pretty_response)
        response = requests.delete(url=self.url_base + "/" + filename)
        return ResponseTreat().treatment(response, pretty_response)


class Projection:
    PROJECTION_PORT = "5001"

    def __init__(self):
        global cluster_url
        self.url_base = cluster_url + ":" + self.PROJECTION_PORT + "/projections"
        self.asyncronous_wait = AsyncronousWait()

    def create_projection(
        self, filename, projection_filename, fields, pretty_response: bool = True
    ):
        if pretty_response:
            print(
                "\n----------"
                + " CREATE PROJECTION FROM "
                + filename
                + " TO "
                + projection_filename
                + " ----------"
            )
        self.asyncronous_wait.wait(filename, pretty_response)
        response = requests.post(
            url=self.url_base + "/" + filename,
            json={"projection_filename": projection_filename, "fields": fields},
        )
        return ResponseTreat().treatment(response, pretty_response)


class Histogram:
    HISTOGRAM_PORT = "5004"

    def __init__(self):
        global cluster_url
        self.url_base = cluster_url + ":" + self.HISTOGRAM_PORT + "/histograms"
        self.asyncronous_wait = AsyncronousWait()

    def create_histogram(
        self, filename, histogram_filename, fields, pretty_response: bool = True
    ):
        if pretty_response:
            print(
                "\n----------"
                + " CREATE HISTOGRAM FROM "
                + filename
                + " TO "
                + histogram_filename
                + " ----------"
            )
        self.asyncronous_wait.wait(filename, pretty_response)
        response = requests.post(
            url=self.url_base + "/" + filename,
            json={"histogram_filename": histogram_filename, "fields": fields},
        )
        return ResponseTreat().treatment(response, pretty_response)


class Tsne:
    TSNE_PORT = "5005"

    def __init__(self):
        global cluster_url
        self.url_base = cluster_url + ":" + self.TSNE_PORT + "/images"
        self.asyncronous_wait = AsyncronousWait()

    def create_image_plot(
        self, tsne_filename, parent_filename, label_name=None, pretty_response=True
    ):
        if pretty_response:
            print(
                "\n----------"
                + " CREATE t-SNE IMAGE PLOT FROM "
                + parent_filename
                + " TO "
                + tsne_filename
                + " ----------"
            )
        self.asyncronous_wait.wait(parent_filename, pretty_response)
        response = requests.post(
            url=self.url_base + "/" + parent_filename,
            json={"tsne_filename": tsne_filename, "label_name": label_name},
        )
        return ResponseTreat().treatment(response, pretty_response)

    def delete_image_plot(self, tsne_filename, pretty_response=True):
        if pretty_response:
            print(
                "\n----------"
                + " DELETE "
                + tsne_filename
                + "  t-SNE IMAGE PLOT "
                + "----------"
            )
        response = requests.delete(url=self.url_base + "/" + tsne_filename)
        return ResponseTreat().treatment(response, pretty_response)

    def read_image_plot_filenames(self, pretty_response=True):
        if pretty_response:
            print("\n---------- READE IMAGE PLOT FILENAMES " + " ----------")
        return ResponseTreat().treatment(requests.get(self.url_base), pretty_response)

    def read_image_plot(self, tsne_filename, pretty_response=True):
        if pretty_response:
            print(
                "\n----------"
                + " READ "
                + tsne_filename
                + " t-SNE IMAGE PLOT "
                + "----------"
            )
        return self.url_base + "/" + tsne_filename


class Pca:
    PCA_PORT = "5006"

    def __init__(self):
        global cluster_url
        self.url_base = cluster_url + ":" + self.PCA_PORT + "/images"
        self.asyncronous_wait = AsyncronousWait()

    def create_image_plot(
        self, pca_filename, parent_filename, label_name=None, pretty_response=True
    ):
        if pretty_response:
            print(
                "\n----------"
                + " CREATE PCA IMAGE PLOT FROM "
                + parent_filename
                + " TO "
                + pca_filename
                + " ----------"
            )
        self.asyncronous_wait.wait(parent_filename, pretty_response)
        response = requests.post(
            url=self.url_base + "/" + parent_filename,
            json={"pca_filename": pca_filename, "label_name": label_name},
        )
        return ResponseTreat().treatment(response, pretty_response)

    def delete_image_plot(self, pca_filename, pretty_response=True):
        if pretty_response:
            print(
                "\n----------"
                + " DELETE "
                + pca_filename
                + " PCA IMAGE PLOT "
                + "----------"
            )
        response = requests.delete(url=self.url_base + "/" + pca_filename)
        return ResponseTreat().treatment(response, pretty_response)

    def read_image_plot_filenames(self, pretty_response=True):
        if pretty_response:
            print("\n---------- READE IMAGE PLOT FILENAMES " + " ----------")
        return ResponseTreat().treatment(requests.get(self.url_base), pretty_response)

    def read_image_plot(self, pca_filename, pretty_response=True):
        if pretty_response:
            print(
                "\n----------"
                + " READ "
                + pca_filename
                + " PCA IMAGE PLOT "
                + "----------"
            )
        return self.url_base + "/" + pca_filename


class DataTypeHandler:
    DATA_TYPE_HANDLER_PORT = "5003"

    def __init__(self):
        global cluster_url
        self.url_base = (
            cluster_url + ":" + self.DATA_TYPE_HANDLER_PORT + "/fieldtypes"
        )
        self.asyncronous_wait = AsyncronousWait()

    def change_file_type(self, filename, fields_dict, pretty_response: bool = True):
        if pretty_response:
            print(
                "\n----------" + " CHANGE " + filename + " FILE TYPE " + "----------"
            )
        self.asyncronous_wait.wait(filename, pretty_response)
        response = requests.patch(
            url=self.url_base + "/" + filename, json=fields_dict
        )
        return ResponseTreat().treatment(response, pretty_response)


class Model:
    MODEL_BUILDER_PORT = "5002"

    def __init__(self):
        global cluster_url
        self.url_base = cluster_url + ":" + self.MODEL_BUILDER_PORT + "/models"
        self.asyncronous_wait = AsyncronousWait()

    def create_model(
        self,
        training_filename,
        test_filename,
        preprocessor_code,
        model_classificator,
        pretty_response: bool = True,
    ):
        if pretty_response:
            print(
                "\n----------"
                + " CREATE MODEL WITH "
                + training_filename
                + " AND "
                + test_filename
                + " ----------"
            )
        self.asyncronous_wait.wait(training_filename, pretty_response)
        self.asyncronous_wait.wait(test_filename, pretty_response)
        response = requests.post(
            url=self.url_base,
            json={
                "training_filename": training_filename,
                "test_filename": test_filename,
                "preprocessor_code": preprocessor_code,
                "classificators_list": model_classificator,
            },
        )
        return ResponseTreat().treatment(response, pretty_response)
