"""Python client — API-compatible with ``learning_orchestra_client`` 1.0.1.

Drop-in compatibility contract (reference:
learning_orchestra_client/learning_orchestra_client/__init__.py:1-370):
the class names (including the reference's ``AsyncronousWait`` spelling),
method signatures, hard-coded service ports, poll-until-``finished``
synchronization (now push-first: ``AsyncronousWait`` prefers the
server's ``GET /jobs/<name>/wait`` long-poll when ``/health``
advertises it, falling back to jittered metadata polling — docs/web.md),
``ResponseTreat`` semantics (pretty JSON string by
default, raise on 4xx, raw text on 5xx), **and the printed banner lines**
— output parity is intended, so the banner texts below reproduce the
reference's exact strings, typos included (``READE``, ``HTTP_SUCESS``).
A user script written against the reference client runs against this
framework by changing only the import.

The implementation is original: one ``_RestClient`` base owns the HTTP
plumbing and banner printing that the reference repeats per class.
"""

from __future__ import annotations

import json
import time
import urllib.parse
import uuid

import requests

from learningorchestra_tpu.sched import policy as _policy

cluster_url = None

# One correlation ID per pipeline run (per Context), sent as
# X-Correlation-Id on EVERY request so the whole client-driven pipeline
# — ingest, projection, build, predict — lands under one ID the server
# threads through jobs and SPMD spans, and GET /traces/<cid> stitches
# into one cross-process Chrome trace (docs/observability.md). Same
# format the server mints for headerless callers
# (telemetry/tracing.mint_correlation_id).
CORRELATION_HEADER = "X-Correlation-Id"
correlation_id = None


def _correlation_headers() -> dict:
    return {CORRELATION_HEADER: correlation_id} if correlation_id else {}


class Context:
    def __init__(self, ip_from_cluster: str):
        global cluster_url, correlation_id
        cluster_url = "http://" + ip_from_cluster
        correlation_id = uuid.uuid4().hex[:16]
        self.correlation_id = correlation_id


def _banner(body: str) -> None:
    """The reference's section separator: ``\\n----------<body>----------``."""
    print("\n----------" + body + "----------")


class ResponseTreat:
    HTTP_CREATED = 201
    HTTP_SUCESS = 200  # reference constant name, typo intended
    HTTP_ERROR = 500

    def treatment(self, response, pretty_response: bool = True):
        ok_codes = (self.HTTP_SUCESS, self.HTTP_CREATED)
        if response.status_code >= self.HTTP_ERROR:
            return response.text
        if response.status_code not in ok_codes:
            raise Exception(response.json()["result"])
        if pretty_response:
            return json.dumps(response.json(), indent=2)
        return response.json()


class AsyncronousWait:
    """Reference-parity synchronization, now push-first.

    The reference polls the dataset's ``finished`` flag every 3 seconds.
    This client keeps that contract as the FALLBACK but prefers the
    server's push route when available:

    1. probe ``GET :5000/health`` once per cluster (cached) — a server
       answering ``job_wait: true`` serves ``GET /jobs/<name>/wait``;
    2. long-poll ``/wait``: one parked request per job, notified within
       milliseconds of the job's done event instead of up to one poll
       period late (404 → the job isn't tracked there, fall back);
    3. metadata polling fallback: the fixed 3 s sleep becomes
       exponential backoff with deterministic seeded jitter
       (sched/policy.backoff_delay) so a restarting fleet doesn't poll
       in lockstep, and ``Retry-After`` on 429/503 is honored.
    """

    WAIT_TIME = 3
    METADATA_INDEX = 0
    # poll-backoff ceiling: 4x the reference's pace, reached by the
    # third fallback poll
    MAX_WAIT_TIME = 12
    # one probe per cluster base URL per process, not per wait() call
    _push_probe_cache: dict = {}

    def wait(self, filename: str, pretty_response: bool = True) -> None:
        if pretty_response:
            _banner(" WAITING " + filename + " FINISH ")
        reader = DatabaseApi()
        if self._push_supported(reader) and self._wait_push(reader, filename):
            return
        self._wait_poll(reader, filename)

    def _service_base(self, reader) -> str:
        # ".../files" → the service root serving /health and /jobs
        return reader.url_base.rsplit("/", 1)[0]

    def _push_supported(self, reader) -> bool:
        base = self._service_base(reader)
        cached = self._push_probe_cache.get(base)
        if cached is not None:
            return cached
        try:
            response = requests.get(
                base + "/health",
                headers=_correlation_headers(),
                timeout=2,
            )
            supported = bool(
                response.status_code == 200
                and response.json().get("job_wait")
            )
        except (requests.RequestException, ValueError):
            supported = False
        self._push_probe_cache[base] = supported
        return supported

    def _wait_push(self, reader, filename: str) -> bool:
        """Long-poll ``GET /jobs/<filename>/wait`` until the tracking
        job goes terminal. Returns False to fall back to metadata
        polling (job unknown here, or the push route went away).

        A connection error mid-poll is NOT an answer — it is a server
        restart (the exact event crash resume exists for): back off
        with the seeded jitter, re-probe the capability once, and
        re-park. The restarted server resolves the wait when the
        resumed job finishes; one that no longer advertises push (or
        stays unreachable) sends the wait to the polling fallback."""
        base = self._service_base(reader)
        url = f"{base}/jobs/{urllib.parse.quote(filename, safe='')}/wait"
        attempt = 0
        while True:
            try:
                response = requests.get(
                    url,
                    params={"timeout": "25"},
                    headers=_correlation_headers(),
                    timeout=40,
                )
            except requests.RequestException:
                attempt += 1
                time.sleep(
                    _policy.backoff_delay(
                        filename,
                        attempt,
                        base_s=self.WAIT_TIME,
                        cap_s=self.MAX_WAIT_TIME,
                    )
                )
                self._push_probe_cache.pop(base, None)
                if not self._push_supported(reader):
                    return False
                continue
            attempt = 0
            if response.status_code in (429, 503):
                self._sleep_retry_after(response)
                continue
            if response.status_code != 200:
                return False  # 404: not tracked here — poll metadata
            try:
                result = response.json().get("result")
            except ValueError:
                return False
            if isinstance(result, dict) and result.get("state") in (
                "finished",
                "failed",
                "cancelled",
            ):
                # terminal states flip the dataset's finished flag
                # before the done event fires (core/jobs._finalize), so
                # returning here preserves the reference contract
                return True
            # {"result": "timeout"}: the job is alive — ask again

    def _wait_poll(self, reader, filename: str) -> None:
        """Metadata polling with seeded-jitter backoff — the hardened
        version of the reference's fixed 3 s loop."""
        attempt = 0
        while True:
            attempt += 1
            time.sleep(
                _policy.backoff_delay(
                    filename,
                    attempt,
                    base_s=self.WAIT_TIME,
                    cap_s=self.MAX_WAIT_TIME,
                )
            )
            response = requests.get(
                url=reader._url(filename),
                params={"skip": "0", "limit": "1", "query": "{}"},
                headers=_correlation_headers(),
                timeout=40,
            )
            if response.status_code in (429, 503):
                self._sleep_retry_after(response)
                continue
            listing = ResponseTreat().treatment(response, False)
            rows = listing["result"] if isinstance(listing, dict) else None
            if rows and rows[self.METADATA_INDEX]["finished"]:
                return

    def _sleep_retry_after(self, response) -> None:
        try:
            delay = float(
                response.headers.get("Retry-After", "") or self.WAIT_TIME
            )
        except ValueError:
            delay = float(self.WAIT_TIME)
        time.sleep(min(max(delay, 0.1), 60.0))


class _RestClient:
    """Shared plumbing for every service wrapper: URL construction from
    the per-class port constant, banner printing, request dispatch, and
    the poll-before-submit idiom (mutating calls first wait for their
    input dataset's ``finished`` flag)."""

    _RESOURCE = ""
    # Every request carries a timeout (analysis LO206: an untimed
    # socket hangs forever on a half-open connection). Generous on
    # purpose: mutating calls can run a synchronous model build on the
    # server, so the ceiling bounds a dead peer, not a slow one.
    _TIMEOUT_S = 3600

    def __init__(self, port: str):
        global cluster_url
        self.url_base = f"{cluster_url}:{port}/{self._RESOURCE}"
        self.asyncronous_wait = AsyncronousWait()

    # --- request helpers ------------------------------------------------------
    def _url(self, suffix: str = "") -> str:
        return self.url_base + ("/" + suffix if suffix else "")

    def _treat(self, response, pretty_response: bool):
        return ResponseTreat().treatment(response, pretty_response)

    def _get(self, suffix: str = "", params=None, pretty_response: bool = True):
        return self._treat(
            requests.get(
                url=self._url(suffix),
                params=params,
                headers=_correlation_headers(),
                timeout=self._TIMEOUT_S,
            ),
            pretty_response,
        )

    def _post(self, suffix: str = "", body=None, pretty_response: bool = True):
        return self._treat(
            requests.post(
                url=self._url(suffix),
                json=body,
                headers=_correlation_headers(),
                timeout=self._TIMEOUT_S,
            ),
            pretty_response,
        )

    def _patch(self, suffix: str = "", body=None, pretty_response: bool = True):
        return self._treat(
            requests.patch(
                url=self._url(suffix),
                json=body,
                headers=_correlation_headers(),
                timeout=self._TIMEOUT_S,
            ),
            pretty_response,
        )

    def _delete(self, suffix: str = "", pretty_response: bool = True):
        return self._treat(
            requests.delete(
                url=self._url(suffix),
                headers=_correlation_headers(),
                timeout=self._TIMEOUT_S,
            ),
            pretty_response,
        )

    def _wait_finished(self, filename: str, pretty_response: bool) -> None:
        self.asyncronous_wait.wait(filename, pretty_response)


class DatabaseApi(_RestClient):
    DATABASE_API_PORT = "5000"
    _RESOURCE = "files"

    def __init__(self):
        super().__init__(self.DATABASE_API_PORT)

    def read_resume_files(self, pretty_response: bool = True):
        if pretty_response:
            _banner(" READ RESUME FILES ")
        return self._get(pretty_response=pretty_response)

    def read_file(
        self, filename, skip=0, limit=10, query={}, pretty_response: bool = True
    ):
        if pretty_response:
            _banner(" READ FILE " + filename + " ")
        params = {"skip": str(skip), "limit": str(limit), "query": str(query)}
        return self._get(filename, params=params, pretty_response=pretty_response)

    def create_file(self, filename, url, pretty_response: bool = True):
        if pretty_response:
            _banner(" CREATE FILE " + filename + " ")
        body = {"filename": filename, "url": url}
        return self._post(body=body, pretty_response=pretty_response)

    def delete_file(self, filename, pretty_response: bool = True):
        if pretty_response:
            _banner(" DELETE FILE " + filename + " ")
        self._wait_finished(filename, pretty_response)
        return self._delete(filename, pretty_response=pretty_response)


class Projection(_RestClient):
    PROJECTION_PORT = "5001"
    _RESOURCE = "projections"

    def __init__(self):
        super().__init__(self.PROJECTION_PORT)

    def create_projection(
        self, filename, projection_filename, fields, pretty_response: bool = True
    ):
        if pretty_response:
            _banner(
                " CREATE PROJECTION FROM "
                + filename
                + " TO "
                + projection_filename
                + " "
            )
        self._wait_finished(filename, pretty_response)
        body = {"projection_filename": projection_filename, "fields": fields}
        return self._post(filename, body=body, pretty_response=pretty_response)


class Histogram(_RestClient):
    HISTOGRAM_PORT = "5004"
    _RESOURCE = "histograms"

    def __init__(self):
        super().__init__(self.HISTOGRAM_PORT)

    def create_histogram(
        self, filename, histogram_filename, fields, pretty_response: bool = True
    ):
        if pretty_response:
            _banner(
                " CREATE HISTOGRAM FROM "
                + filename
                + " TO "
                + histogram_filename
                + " "
            )
        self._wait_finished(filename, pretty_response)
        body = {"histogram_filename": histogram_filename, "fields": fields}
        return self._post(filename, body=body, pretty_response=pretty_response)


class _ImagePlots(_RestClient):
    """Common body of the reference's near-identical ``Tsne``/``Pca``
    classes; ``_METHOD_LABEL`` and ``_FILENAME_KEY`` carry the two
    differences (banner wording and request key)."""

    _RESOURCE = "images"
    _METHOD_LABEL = ""
    _FILENAME_KEY = ""

    def _create_image_plot(
        self, output_filename, parent_filename, label_name, pretty_response
    ):
        if pretty_response:
            _banner(
                " CREATE "
                + self._METHOD_LABEL
                + " IMAGE PLOT FROM "
                + parent_filename
                + " TO "
                + output_filename
                + " "
            )
        self._wait_finished(parent_filename, pretty_response)
        body = {self._FILENAME_KEY: output_filename, "label_name": label_name}
        return self._post(parent_filename, body=body, pretty_response=pretty_response)

    def _delete_image_plot(self, output_filename, pretty_response, trailing: str):
        if pretty_response:
            _banner(" DELETE " + output_filename + trailing)
        return self._delete(output_filename, pretty_response=pretty_response)

    def read_image_plot_filenames(self, pretty_response=True):
        if pretty_response:
            _banner(" READE IMAGE PLOT FILENAMES  ")  # reference typo
        return self._get(pretty_response=pretty_response)

    def _read_image_plot(self, output_filename, pretty_response):
        if pretty_response:
            _banner(
                " READ " + output_filename + " " + self._METHOD_LABEL + " IMAGE PLOT "
            )
        return self._url(output_filename)


class Tsne(_ImagePlots):
    TSNE_PORT = "5005"
    _METHOD_LABEL = "t-SNE"
    _FILENAME_KEY = "tsne_filename"

    def __init__(self):
        super().__init__(self.TSNE_PORT)

    def create_image_plot(
        self, tsne_filename, parent_filename, label_name=None, pretty_response=True
    ):
        return self._create_image_plot(
            tsne_filename, parent_filename, label_name, pretty_response
        )

    def delete_image_plot(self, tsne_filename, pretty_response=True):
        # reference banner has two spaces before "t-SNE" here
        return self._delete_image_plot(
            tsne_filename, pretty_response, "  t-SNE IMAGE PLOT "
        )

    def read_image_plot(self, tsne_filename, pretty_response=True):
        return self._read_image_plot(tsne_filename, pretty_response)


class Pca(_ImagePlots):
    PCA_PORT = "5006"
    _METHOD_LABEL = "PCA"
    _FILENAME_KEY = "pca_filename"

    def __init__(self):
        super().__init__(self.PCA_PORT)

    def create_image_plot(
        self, pca_filename, parent_filename, label_name=None, pretty_response=True
    ):
        return self._create_image_plot(
            pca_filename, parent_filename, label_name, pretty_response
        )

    def delete_image_plot(self, pca_filename, pretty_response=True):
        return self._delete_image_plot(
            pca_filename, pretty_response, " PCA IMAGE PLOT "
        )

    def read_image_plot(self, pca_filename, pretty_response=True):
        return self._read_image_plot(pca_filename, pretty_response)


class DataTypeHandler(_RestClient):
    DATA_TYPE_HANDLER_PORT = "5003"
    _RESOURCE = "fieldtypes"

    def __init__(self):
        super().__init__(self.DATA_TYPE_HANDLER_PORT)

    def change_file_type(self, filename, fields_dict, pretty_response: bool = True):
        if pretty_response:
            _banner(" CHANGE " + filename + " FILE TYPE ")
        self._wait_finished(filename, pretty_response)
        return self._patch(filename, body=fields_dict, pretty_response=pretty_response)


class Model(_RestClient):
    MODEL_BUILDER_PORT = "5002"
    _RESOURCE = "models"
    # one probe per cluster base URL per process (the AsyncronousWait
    # push-probe idiom): does the base URL front a fleet router?
    _router_probe_cache: dict = {}

    def __init__(self):
        super().__init__(self.MODEL_BUILDER_PORT)

    def _router_base(self):
        """The fleet router's base URL, or ``None`` for the classic
        direct-to-model_builder topology.

        A fleet deployment (docs/serving.md "Fleet") fronts predicts
        with ONE router URL instead of the per-service port table:
        ``Context("host:5007")`` points the client at it, and this
        probe — one ``GET /health`` per base URL per process, cached —
        detects the ``"fleet_router"`` feature field the router
        advertises (serve/router.py). Everything else about the client
        is unchanged: batch calls still go to the head's service ports,
        so fleet users give the data-plane classes a separate
        ``Context`` at the head."""
        base = cluster_url
        if not base:
            return None
        cached = self._router_probe_cache.get(base)
        if cached is None:
            try:
                response = requests.get(
                    base + "/health",
                    headers=_correlation_headers(),
                    timeout=2,
                )
                cached = bool(
                    response.status_code == 200
                    and response.json().get("fleet_router")
                )
            except (requests.RequestException, ValueError):
                cached = False
            self._router_probe_cache[base] = cached
        return base if cached else None

    def create_model(
        self,
        training_filename,
        test_filename,
        preprocessor_code,
        model_classificator,
        pretty_response: bool = True,
    ):
        if pretty_response:
            _banner(
                " CREATE MODEL WITH "
                + training_filename
                + " AND "
                + test_filename
                + " "
            )
        self._wait_finished(training_filename, pretty_response)
        self._wait_finished(test_filename, pretty_response)
        body = {
            "training_filename": training_filename,
            "test_filename": test_filename,
            "preprocessor_code": preprocessor_code,
            "classificators_list": model_classificator,
        }
        return self._post(body=body, pretty_response=pretty_response)

    # --- online serving (beyond the reference surface; docs/serving.md) ---
    def predict(self, model_name, rows, pretty_response: bool = True):
        """Synchronous predictions from a built model: ``rows`` (a list
        of numeric feature rows) in, labels + probabilities out — no job
        to poll. The 429/Retry-After and 404 cases surface through the
        standard ``ResponseTreat`` semantics.

        Transparently rides a fleet router when the ``Context`` URL
        fronts one (:meth:`_router_base`): the request goes to the
        router's ``/models/<name>/predict`` and a per-model-quota 429
        is honored by sleeping out its ``Retry-After`` (the
        AsyncronousWait backoff clamp) and retrying, so a burst over
        ``LO_FLEET_MODEL_QPS`` smooths out instead of raising."""
        if pretty_response:
            _banner(" PREDICT WITH " + model_name + " ")
        router = self._router_base()
        if router is None:
            return self._post(
                model_name + "/predict",
                body={"rows": rows},
                pretty_response=pretty_response,
            )
        url = f"{router}/models/{urllib.parse.quote(model_name, safe='')}/predict"
        while True:
            response = requests.post(
                url,
                json={"rows": rows},
                headers=_correlation_headers(),
                timeout=self._TIMEOUT_S,
            )
            if response.status_code == 429:
                self.asyncronous_wait._sleep_retry_after(response)
                continue
            return self._treat(response, pretty_response)

    def list_models(self, pretty_response: bool = True):
        """Built model names plus serving-registry occupancy."""
        if pretty_response:
            _banner(" LIST MODELS ")
        return self._get(pretty_response=pretty_response)

    def sweep(
        self,
        training_filename,
        test_filename,
        preprocessor_code,
        classificator,
        grid,
        sweep_name,
        max_iter=None,
        pretty_response: bool = True,
    ):
        """Hyperparameter sweep in ONE device dispatch (``POST
        /models/sweep``): ``grid`` is a list of points — ``[{"reg_param":
        0.1}, ...]`` for ``classificator="lr"``, ``[{"max_depth": 3},
        ...]`` for ``"dt"``. Returns per-point metrics, the argmax
        index, and the checkpoint name ``sweep_name`` — immediately
        servable via :meth:`predict`."""
        if pretty_response:
            _banner(" SWEEP " + classificator + " AS " + sweep_name + " ")
        self._wait_finished(training_filename, pretty_response)
        self._wait_finished(test_filename, pretty_response)
        body = {
            "training_filename": training_filename,
            "test_filename": test_filename,
            "preprocessor_code": preprocessor_code,
            "classificator": classificator,
            "grid": grid,
            "sweep_name": sweep_name,
        }
        if max_iter is not None:
            body["max_iter"] = max_iter
        return self._post("sweep", body=body, pretty_response=pretty_response)
