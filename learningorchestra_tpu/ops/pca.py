"""PCA on device: covariance + eigendecomposition, all matmuls.

Replaces the reference's driver-side ``sklearn.decomposition.PCA(
n_components=2).fit_transform`` (reference: microservices/pca_image/
pca.py:87-88), which first collapses the whole dataset to one host via
``toPandas()`` (pca.py:80) — the scalability cliff called out in
SURVEY.md §3.4.

TPU shape: center, form the ``(features, features)`` Gram matrix with one
``Xᵀ @ X`` matmul — on row-sharded data that contraction IS the
cross-chip reduction — then ``eigh`` the tiny covariance and project with
a second matmul. No host round-trip of the data, ever.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from learningorchestra_tpu.ml.base import prepare_xy, resolve_mesh
from learningorchestra_tpu.parallel.multihost import fetch


@partial(jax.jit, static_argnames=("n_components",))
def _pca(X, mask, n_components: int):
    weights = mask.astype(X.dtype)
    n = weights.sum()
    mean = (X * weights[:, None]).sum(axis=0) / n
    centered = (X - mean) * weights[:, None]
    # full-f32 passes: the TPU's default bf16 matmul perturbs the tiny
    # covariance enough to visibly rotate the eigh components
    covariance = (
        jnp.dot(centered.T, centered, precision=jax.lax.Precision.HIGHEST)
        / (n - 1)
    )
    eigenvalues, eigenvectors = jnp.linalg.eigh(covariance)
    # eigh is ascending; take the top components, largest first.
    components = eigenvectors[:, ::-1][:, :n_components]
    explained = eigenvalues[::-1][:n_components]
    return centered @ components, components, explained


def pca_embedding(
    X, n_components: int = 2, mesh: Optional[Mesh] = None
) -> np.ndarray:
    """Project rows onto the top principal components. Returns
    ``(rows, n_components)``.

    ``X`` may be a host array or an already-sharded
    :class:`~learningorchestra_tpu.ml.base.DeviceMatrix` (the device
    cache's currency, core/devcache.py): a cached matrix enters with
    ZERO host↔device input traffic — ``prepare_xy`` passes its buffers
    straight through and only the ``(rows, n_components)`` embedding
    crosses back (the ``d2h`` span below is the whole transfer bill)."""
    from learningorchestra_tpu.telemetry import profile, span

    mesh = resolve_mesh(mesh)
    # prepare = H2D (when X is a host array) + the async fit dispatch;
    # the device compute itself is awaited inside the d2h span below,
    # which is where its wall-clock lands on the timeline.
    with span("pca:prepare", rows=len(X)):
        X_dev, _, mask = prepare_xy(X, None, mesh)
        embedded, _, _ = _pca(X_dev, mask, n_components)
    with span("d2h:pca", rows=len(X), components=n_components):
        out = fetch(embedded)[: len(X)]
        profile.account_d2h(int(np.asarray(out).nbytes))
        return out
