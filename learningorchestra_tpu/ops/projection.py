"""Column projection: parent dataset → new dataset with a field subset.

Reference behaviour (microservices/projection_image/projection.py:71-125):
a Spark job reads the parent collection, filters out the metadata row,
``select``s the requested fields (plus ``_id``), appends the rows into the
output collection, and writes a metadata document whose ``finished`` flag
flips when the job completes.

Here projection is a single bulk columnar move: one
``read_column_arrays`` scan (fields + ``_id`` together, so values and
row ids can never mis-pair) and one column-major write under the
``finished`` contract — typed buffers in, typed buffers out, no per-row
dicts and no per-cell conversion anywhere. Row ``_id``s are preserved,
matching the reference's appending of ``_id`` to the projection fields
(projection_image/server.py:104-106). Values are copied raw — projection
never coerces types; that is the fieldtypes service's job.
"""

from __future__ import annotations

import numpy as np

from learningorchestra_tpu.core.ingest import timestamp
from learningorchestra_tpu.core.store import ROW_ID, DocumentStore
from learningorchestra_tpu.core.table import write_columns


def project(
    store: DocumentStore,
    parent_filename: str,
    projection_filename: str,
    fields: list[str],
) -> int:
    """Project ``fields`` of ``parent_filename`` into ``projection_filename``.

    Returns the row count.
    """
    field_names = [field for field in fields if field != ROW_ID]
    metadata = store.metadata(parent_filename)
    known = metadata.get("fields") if metadata else None
    if isinstance(known, list):
        missing = [field for field in field_names if field not in known]
        if missing:
            raise KeyError(
                f"fields {missing} not in dataset {parent_filename!r}"
            )
    columns = store.read_column_arrays(
        parent_filename, fields=field_names + [ROW_ID]
    )
    ids_column = columns.pop(ROW_ID)
    num_rows = len(ids_column)
    if ids_column.kind == "i8":
        ids = ids_column.data[:num_rows]
    else:
        ids = np.asarray(ids_column.tolist())

    write_columns(
        store,
        projection_filename,
        columns,
        {
            "filename": projection_filename,
            "finished": True,
            "time_created": timestamp(),
            "parent_filename": parent_filename,
            "fields": field_names,
        },
        ids=ids,
    )
    return num_rows
