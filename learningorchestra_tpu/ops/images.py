"""Embedding → scatter-plot PNG pipeline shared by the PCA and t-SNE
services.

Reference behaviour (microservices/pca_image/pca.py:74-98 and
tsne_image/tsne.py:74-102): load the dataset, ``dropna()``, LabelEncode
string columns, embed to 2-D, seaborn scatter (hue = label column when
given), save ``<name>.png`` into the images volume.

Here the load is one bulk columnar read **through the device cache**
(core/devcache.py): the decoded table, its encoded form (same
sorted-vocabulary order as sklearn's LabelEncoder) and the sharded
device matrix are all keyed by the collection's store rev, so a
histogram→pca→tsne pipeline over one dataset reads and uploads it once
— the second embedding request starts from buffers already resident in
HBM and only the ``(rows, 2)`` output crosses back. The embedding runs
on device (ops/pca.py, ops/tsne.py) instead of single-host sklearn.
Only the final PNG rasterization stays on host — plot rendering is not
TPU work (SURVEY.md §2).
"""

from __future__ import annotations

import os
from typing import Callable, Optional

import numpy as np

from learningorchestra_tpu.core.devcache import dataset_embedding_inputs
from learningorchestra_tpu.core.store import DocumentStore
from learningorchestra_tpu.ops.pca import pca_embedding
from learningorchestra_tpu.ops.tsne import tsne_embedding
from learningorchestra_tpu.utils.paths import safe_filename

IMAGE_FORMAT = ".png"

EMBEDDINGS: dict[str, Callable] = {
    "pca": pca_embedding,
    "tsne": tsne_embedding,
}


def _scatter_png(
    embedded: np.ndarray, hue: Optional[np.ndarray], image_path: str
) -> None:
    import matplotlib

    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt
    import seaborn as sns

    figure, axes = plt.subplots()
    try:
        if hue is not None:
            sns.scatterplot(
                x=embedded[:, 0], y=embedded[:, 1], hue=hue, ax=axes
            )
        else:
            sns.scatterplot(x=embedded[:, 0], y=embedded[:, 1], ax=axes)
        figure.savefig(image_path)
    finally:
        plt.close(figure)


def create_embedding_image(
    store: DocumentStore,
    parent_filename: str,
    label_name: Optional[str],
    output_filename: str,
    images_path: str,
    method: str,
    render: bool = True,
) -> str:
    """Embed ``parent_filename`` with ``method`` ("pca"/"tsne") and write
    ``<images_path>/<output_filename>.png``. Returns the image path.

    ``render=False`` runs the device embedding (whose collectives every
    process of a multi-host mesh must enter) but skips the host-side PNG
    rasterization — SPMD worker processes pass False so only the
    coordinator touches the images volume (parallel/spmd.py)."""
    if not safe_filename(output_filename):
        raise ValueError(f"unsafe image filename {output_filename!r}")
    embed = EMBEDDINGS[method]
    # Rev-keyed read: table decode, dropna+encode and the H2D all hit
    # cache when this dataset revision was embedded before. One cache
    # entry carries the encoded table AND its device matrix, so the hue
    # labels below always match the embedded rows even if a write lands
    # mid-request.
    encoded, _, X = dataset_embedding_inputs(store, parent_filename)
    embedded = embed(X)
    image_path = os.path.join(images_path, output_filename + IMAGE_FORMAT)
    if render:
        hue = None
        if label_name is not None:
            hue = np.asarray(encoded.columns[label_name])
        os.makedirs(images_path, exist_ok=True)
        _scatter_png(embedded, hue, image_path)
    return image_path
