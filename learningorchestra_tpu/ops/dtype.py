"""Field type conversion: string ↔ number, whole columns at a time.

Reference behaviour (microservices/data_type_handler_image/
data_type_handler.py:47-82): for each requested field, iterate every
document and issue one ``update_one`` RPC per row — 2 RPCs per row per
field. Conversion rules preserved here:

- → string: ``None`` becomes ``""``, everything else ``str(value)``.
- → number: ``""`` becomes ``None`` (missing), everything else
  ``float(value)``, collapsed to ``int`` when integral (so ``"28"``
  round-trips as ``28`` not ``28.0``).

This implementation is columnar: one bulk read, one vectorized convert,
one bulk :meth:`~learningorchestra_tpu.core.store.DocumentStore.
set_field_values` write per field.
"""

from __future__ import annotations

from learningorchestra_tpu.core.store import ROW_ID, DocumentStore

STRING_TYPE = "string"
NUMBER_TYPE = "number"


def _to_string(value):
    if value is None:
        return ""
    if isinstance(value, float) and value.is_integer():
        return str(int(value))
    return str(value)


def _to_number(value):
    if value is None or value == "":
        return None
    number = float(value)
    return int(number) if number.is_integer() else number


def convert_field_types(
    store: DocumentStore, filename: str, field_types: dict[str, str]
) -> None:
    """Convert each ``field`` of ``filename`` to ``field_types[field]``.

    Raises ``ValueError`` on an unparseable numeric string (the reference
    lets the same error surface as an HTTP 500).
    """
    converters = {STRING_TYPE: _to_string, NUMBER_TYPE: _to_number}
    for field, field_type in field_types.items():
        if field_type not in converters:
            raise ValueError(f"invalid field type {field_type!r}")

    columns = store.read_columns(
        filename, fields=[ROW_ID] + list(field_types)
    )
    ids = columns[ROW_ID]
    num_rows = len(ids)
    contiguous = num_rows == 0 or all(
        ids[i] == ids[0] + i for i in range(num_rows)
    )
    for field, field_type in field_types.items():
        convert = converters[field_type]
        converted = [convert(value) for value in columns[field]]
        if contiguous:
            # one bulk column write (block-replace fast path in the store)
            store.set_column(
                filename, field, converted, start_id=ids[0] if num_rows else 1
            )
        else:
            store.set_field_values(
                filename, field, dict(zip(ids, converted))
            )
