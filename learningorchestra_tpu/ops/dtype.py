"""Field type conversion: string ↔ number, whole columns at a time.

Reference behaviour (microservices/data_type_handler_image/
data_type_handler.py:47-82): for each requested field, iterate every
document and issue one ``update_one`` RPC per row — 2 RPCs per row per
field. Conversion rules preserved here:

- → string: ``None`` becomes ``""``, everything else ``str(value)``
  (integral floats collapse: ``28.0`` → ``"28"``).
- → number: ``""`` becomes ``None`` (missing), everything else
  ``float(value)``, collapsed to ``int`` when integral (so ``"28"``
  round-trips as ``28`` not ``28.0``).

This implementation is columnar AND typed: one bulk
``read_column_arrays``, a vectorized numpy convert (numpy's C string
parser with a Python-``float()`` fallback for its grammar gaps), one
bulk ``set_column`` write per field — the converted column lands in the
store as a typed block, never a boxed list.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from learningorchestra_tpu.core.columns import Column
from learningorchestra_tpu.core.store import ROW_ID, DocumentStore

STRING_TYPE = "string"
NUMBER_TYPE = "number"

# str→number casts convert this many rows per boxed-list transient.
_CAST_BLOCK_ROWS = 2_000_000


def _to_string(value):
    if value is None:
        return ""
    if isinstance(value, float) and value.is_integer():
        return str(int(value))
    return str(value)


def _to_number(value):
    if value is None or value == "":
        return None
    number = float(value)
    return int(number) if number.is_integer() else number


def _num_column(data: np.ndarray, none: np.ndarray) -> Column:
    """float64 values + null mask → a ``num`` column with the
    int-collapse mask set for integral values (the ``"28"`` → ``28``
    contract)."""
    column = Column("num")
    column.size = len(data)
    column.data = data
    finite = np.isfinite(data)
    column.intm = finite & (data == np.floor(np.where(finite, data, 0.0)))
    column.intm[none] = False
    if none.any():
        column.none = none
        column.data = column.data.copy()
        column.data[none] = np.nan
    # NaN parsed from a literal "nan" cell also reads back as null —
    # including when a None/"" mask already exists (data[none] is NaN
    # by the assignment above, so the isnan mask is a superset)
    nan = np.isnan(column.data)
    if nan.any():
        column.none = nan
    return column


def _strings_to_number(
    values: list, empty_mask: Optional[np.ndarray] = None
) -> Column:
    """Vectorized ``float()`` over raw string cells: numpy's
    list-of-str → float64 construction parses with Python ``float``
    semantics (``"1_0"`` included) in one C loop. ``empty_mask`` (from
    the Arrow offsets: zero-length cells) skips the per-value None/""
    scan when the caller already knows it."""
    n = len(values)
    if empty_mask is not None:  # caller-complete None/"" mask: no scan
        none = empty_mask.copy()
    else:
        none = np.zeros(n, dtype=bool)
        for i, v in enumerate(values):
            if v is None or v == "":
                none[i] = True
    filled = (
        ["nan" if none[i] else v for i, v in enumerate(values)]
        if none.any()
        else values
    )
    try:
        data = np.asarray(filled, dtype=np.float64)
    except (ValueError, TypeError):
        # exact per-value fallback, same error surface as float(value)
        data = np.empty(n, dtype=np.float64)
        for i, v in enumerate(filled):
            data[i] = np.nan if none[i] else float(v)
    return _num_column(data, none)


def _numeric_to_string(column: Column) -> Column:
    """Typed numeric column → string column, vectorized: integral
    values render via int64 (no trailing ``.0``), the rest via numpy's
    float repr (identical to ``str(float)``)."""
    data = column.to_float64()
    absent = np.isnan(data)
    safe = np.where(absent, 0.0, data)
    integral = np.isfinite(safe) & (safe == np.floor(safe))
    # int64 only renders magnitudes below 2^63; bigger integral floats
    # go through Python's arbitrary-precision int below
    small = np.abs(safe) < 2**63
    out = np.where(
        integral & small,
        np.where(small, safe, 0.0).astype(np.int64).astype("U21"),
        safe.astype("U32"),
    )
    values = out.tolist()
    for i in np.flatnonzero(integral & ~small):
        values[i] = str(int(data[i]))
    if absent.any():
        for i in np.flatnonzero(absent):
            values[i] = ""
    return Column.from_strings(values)


def _convert_column(column: Column, field_type: str) -> Optional[Column]:
    """Typed fast path; ``None`` means "use the per-value loop"."""
    if field_type == NUMBER_TYPE:
        if column.kind in ("f8", "i8", "num"):
            return _num_column(
                column.data[: len(column)].astype(np.float64, copy=True),
                (
                    column._absent_mask().copy()
                    if column._absent_mask() is not None
                    else np.zeros(len(column), dtype=bool)
                ),
            )
        if column.kind == "str":
            # complete None/"" mask from the Arrow offsets (zero-length
            # cells) + the null/missing masks — skips the Python scan.
            # Converted in blocks: a 100M-row cast must never hold the
            # whole column as a boxed Python list (the out-of-core
            # story caps the anonymous working set at block size).
            source = column._materialized()
            n = len(source)
            absent = source._absent_mask()
            out: Optional[Column] = None
            for start in range(0, max(n, 1), _CAST_BLOCK_ROWS):
                stop = min(start + _CAST_BLOCK_ROWS, n)
                empty = np.diff(source.offsets[start : stop + 1]) == 0
                if absent is not None:
                    empty = empty | absent[start:stop]
                part = _strings_to_number(
                    source.tolist(start, stop), empty_mask=empty
                )
                out = part if out is None else out.append_column(part)
                if stop >= n:
                    break
            return out
        return None  # obj/bool/empty: exact per-value loop
    if field_type == STRING_TYPE:
        if column.kind in ("f8", "i8", "num"):
            return _numeric_to_string(column)
        if column.kind == "str":
            absent = column._absent_mask()
            if absent is None or not absent.any():
                return column  # already strings, no nulls: unchanged
            values = column.tolist()
            for i in np.flatnonzero(absent):
                values[i] = ""
            return Column.from_strings(values)
        return None
    return None


def convert_field_types(
    store: DocumentStore, filename: str, field_types: dict[str, str]
) -> None:
    """Convert each ``field`` of ``filename`` to ``field_types[field]``.

    Raises ``ValueError`` on an unparseable numeric string (the reference
    lets the same error surface as an HTTP 500).
    """
    converters = {STRING_TYPE: _to_string, NUMBER_TYPE: _to_number}
    for field, field_type in field_types.items():
        if field_type not in converters:
            raise ValueError(f"invalid field type {field_type!r}")

    columns = store.read_column_arrays(
        filename, fields=[ROW_ID] + list(field_types)
    )
    ids_column = columns[ROW_ID]
    num_rows = len(ids_column)
    if ids_column.kind == "i8":
        arr = ids_column.data[:num_rows]
        contiguous = num_rows == 0 or bool(
            np.array_equal(arr, np.arange(arr[0], arr[0] + num_rows))
        )
        ids = arr.tolist() if not contiguous else ([int(arr[0])] if num_rows else [])
        del arr  # a live view would pin the full id buffer below
    else:
        ids = ids_column.tolist()
        contiguous = num_rows == 0 or all(
            ids[i] == ids[0] + i for i in range(num_rows)
        )
    del ids_column, columns[ROW_ID]  # 100M ids: don't hold for the pass
    for field, field_type in field_types.items():
        source = columns.pop(field)  # release each snapshot as it casts
        converted = _convert_column(source, field_type)
        if converted is None:
            convert = converters[field_type]
            converted = Column.from_values(
                [convert(value) for value in source.tolist()]
            )
        del source
        if contiguous:
            # one bulk column write (block-replace fast path in the store)
            store.set_column(
                filename, field, converted, start_id=ids[0] if num_rows else 1
            )
        else:
            store.set_field_values(
                filename, field, dict(zip(ids, converted.tolist()))
            )
