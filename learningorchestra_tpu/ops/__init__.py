"""Dataset operations: projection, type conversion, histogram, PCA, t-SNE.

Each op is the TPU-native analogue of one reference microservice's logic
module. Ops consume/produce collections in a
:class:`~learningorchestra_tpu.core.store.DocumentStore` through bulk
columnar reads and writes; the compute itself is numpy/JAX, not
row-at-a-time RPCs.
"""

from learningorchestra_tpu.ops.projection import project
from learningorchestra_tpu.ops.dtype import convert_field_types
from learningorchestra_tpu.ops.histogram import create_histogram, value_counts

__all__ = [
    "project",
    "convert_field_types",
    "create_histogram",
    "value_counts",
]
