"""Value-count histograms.

Reference behaviour (microservices/histogram_image/histogram.py:49-74):
per requested field, push a ``$group``/``$sum: 1`` aggregation down to
MongoDB and store the resulting ``[{_id: value, count: n}, ...]`` list as
one document of a new histogram collection, plus a metadata document
``{filename_parent, fields, filename, _id: 0}``.

Two counting paths:

- :func:`value_counts` — for raw store columns (host-resident Python
  values). Exact float64 counting via ``np.unique``; putting arbitrary
  float64 store values through a float32 device would silently perturb
  the histogram keys.
- :func:`device_value_counts` (and the jitted kernel
  :func:`_sorted_unique_counts`) — for columns already living on device
  as ``jax.Array``: one XLA sort + two scatters with a static output
  shape. This is the path table-level compute (e.g. tree binning in
  ``ml/``) uses, where the data is device-resident and device-width
  anyway.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from learningorchestra_tpu.core.store import METADATA_ID, ROW_ID, DocumentStore


@jax.jit
def _sorted_unique_counts(x: jax.Array):
    """Unique values of ``x`` and their counts, compacted to the front.

    Returns ``(values, counts, n_unique)`` where only the first
    ``n_unique`` entries are meaningful; the tail is padding so the shape
    stays static under jit. One device sort + two scatters — the on-device
    analogue of the reference's server-side ``$group`` pushdown.
    """
    s = jnp.sort(x)
    is_new = jnp.concatenate([jnp.ones(1, dtype=bool), s[1:] != s[:-1]])
    group = jnp.cumsum(is_new) - 1
    counts = jnp.zeros(x.shape, dtype=jnp.int32).at[group].add(1)
    values = jnp.zeros(x.shape, dtype=x.dtype).at[group].set(s)
    return values, counts, is_new.sum()


def device_value_counts(x: jax.Array) -> tuple[np.ndarray, np.ndarray]:
    """``(values, counts)`` of a device-resident numeric column."""
    values, counts, n = _sorted_unique_counts(x)
    n = int(n)
    return np.asarray(values)[:n], np.asarray(counts)[:n]


def value_counts(raw_values: list) -> list[tuple[object, int]]:
    """``(value, count)`` pairs for one raw store column, sorted by value.

    ``None``/NaN values form their own group (like Mongo's null group);
    integral floats collapse to int so counts round-trip the dtype
    converter (ops/dtype.py).
    """
    nulls = 0
    numbers: list[float] = []
    others: list = []
    for value in raw_values:
        if value is None or (isinstance(value, float) and np.isnan(value)):
            nulls += 1
        elif isinstance(value, (int, float, np.integer, np.floating)) and not isinstance(
            value, bool
        ):
            numbers.append(float(value))
        else:
            others.append(value)

    pairs: list[tuple[object, int]] = []
    if numbers:
        host_values, host_counts = np.unique(
            np.asarray(numbers, dtype=np.float64), return_counts=True
        )
        for value, count in zip(host_values, host_counts):
            value = float(value)
            pairs.append((int(value) if value.is_integer() else value, int(count)))
    if others:
        host_values, host_counts = np.unique(
            np.asarray(others, dtype=object), return_counts=True
        )
        for value, count in zip(host_values, host_counts):
            pairs.append((value, int(count)))
    if nulls:
        pairs.append((None, nulls))
    return pairs


def create_histogram(
    store: DocumentStore,
    parent_filename: str,
    histogram_filename: str,
    fields: list[str],
) -> None:
    """Build the histogram collection with the reference's document shape
    (histogram.py:50-74): metadata at ``_id: 0`` then one document per
    field containing its ``[{_id: value, count: n}, ...]`` list."""
    store.insert_one(
        histogram_filename,
        {
            "filename_parent": parent_filename,
            "fields": fields,
            "filename": histogram_filename,
            ROW_ID: METADATA_ID,
        },
    )
    columns = store.read_columns(parent_filename, fields=fields)
    for document_id, field in enumerate(fields, start=1):
        store.insert_one(
            histogram_filename,
            {
                field: [
                    {"_id": value, "count": count}
                    for value, count in value_counts(columns[field])
                ],
                ROW_ID: document_id,
            },
        )
