"""Value-count histograms.

Reference behaviour (microservices/histogram_image/histogram.py:49-74):
per requested field, push a ``$group``/``$sum: 1`` aggregation down to
MongoDB and store the resulting ``[{_id: value, count: n}, ...]`` list as
one document of a new histogram collection, plus a metadata document
``{filename_parent, fields, filename, _id: 0}``.

Counting is host-side and exact: the raw store column holds arbitrary
Python values (float64, strings, whatever ``update_one`` wrote), and
pushing floats through a float32 device would silently perturb the
histogram keys. Device-side histogramming of already-binned device data
lives where it is actually hot: the tree-split histograms in
``ml/trees.py``.
"""

from __future__ import annotations

import numpy as np

from learningorchestra_tpu.core.store import METADATA_ID, ROW_ID, DocumentStore


def value_counts(raw_values: list) -> list[tuple[object, int]]:
    """``(value, count)`` pairs for one raw store column, sorted by value.

    ``None``/NaN values form their own group (like Mongo's null group);
    integral floats collapse to int so counts round-trip the dtype
    converter (ops/dtype.py).
    """
    nulls = 0
    numbers: list[float] = []
    others: list = []
    for value in raw_values:
        if value is None or (isinstance(value, float) and np.isnan(value)):
            nulls += 1
        elif isinstance(value, (int, float, np.integer, np.floating)) and not isinstance(
            value, bool
        ):
            numbers.append(float(value))
        else:
            others.append(value)

    pairs: list[tuple[object, int]] = []
    if numbers:
        host_values, host_counts = np.unique(
            np.asarray(numbers, dtype=np.float64), return_counts=True
        )
        for value, count in zip(host_values, host_counts):
            value = float(value)
            pairs.append((int(value) if value.is_integer() else value, int(count)))
    if others:
        # Dict-based: a mixed-type column (e.g. strings + booleans) has
        # no total order, so no sorting-based unique.
        counts: dict = {}
        for value in others:
            counts[value] = counts.get(value, 0) + 1
        for value in sorted(counts, key=str):
            pairs.append((value, counts[value]))
    if nulls:
        pairs.append((None, nulls))
    return pairs


def create_histogram(
    store: DocumentStore,
    parent_filename: str,
    histogram_filename: str,
    fields: list[str],
) -> None:
    """Build the histogram collection with the reference's document shape
    (histogram.py:50-74): metadata at ``_id: 0`` then one document per
    field containing its ``[{_id: value, count: n}, ...]`` list."""
    store.insert_one(
        histogram_filename,
        {
            "filename_parent": parent_filename,
            "fields": fields,
            "filename": histogram_filename,
            ROW_ID: METADATA_ID,
        },
    )
    columns = store.read_columns(parent_filename, fields=fields)
    for document_id, field in enumerate(fields, start=1):
        store.insert_one(
            histogram_filename,
            {
                field: [
                    {"_id": value, "count": count}
                    for value, count in value_counts(columns[field])
                ],
                ROW_ID: document_id,
            },
        )
