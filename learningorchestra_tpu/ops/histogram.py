"""Value-count histograms.

Reference behaviour (microservices/histogram_image/histogram.py:49-74):
per requested field, push a ``$group``/``$sum: 1`` aggregation down to
MongoDB and store the resulting ``[{_id: value, count: n}, ...]`` list as
one document of a new histogram collection, plus a metadata document
``{filename_parent, fields, filename, _id: 0}``.

Counting happens IN the store via the same ``$group`` pushdown
(``store.aggregate``): the columnar engine counts block columns without
synthesizing rows, and over the wire only ``(value, count)`` pairs
travel — never the raw column. Counts stay exact: the store column
holds arbitrary Python values (float64, strings, whatever
``update_one`` wrote), and pushing floats through a float32 device
would silently perturb the histogram keys. Device-side histogramming of
already-binned device data lives where it is actually hot: the
tree-split histograms in ``ml/trees.py``.
"""

from __future__ import annotations

import numpy as np

from learningorchestra_tpu.core.store import METADATA_ID, ROW_ID, DocumentStore


def normalize_group_counts(groups: list[dict]) -> list[tuple[object, int]]:
    """Normalize ``$group`` results (``[{_id, count}]``) into the stored
    histogram order: numbers ascending (integral floats collapsed to int
    so counts round-trip the dtype converter, ops/dtype.py), then other
    values by string, then the merged ``None``/NaN null group (like
    Mongo's null group)."""
    nulls = 0
    numbers: dict[object, int] = {}
    others: dict = {}
    for group in groups:
        value, count = group["_id"], group["count"]
        if value is None or (isinstance(value, float) and np.isnan(value)):
            nulls += count
        elif isinstance(
            value, (int, float, np.integer, np.floating)
        ) and not isinstance(value, bool):
            value = float(value)
            key = int(value) if value.is_integer() else value
            numbers[key] = numbers.get(key, 0) + count
        else:
            others[value] = others.get(value, 0) + count

    pairs: list[tuple[object, int]] = []
    pairs.extend((key, numbers[key]) for key in sorted(numbers))
    pairs.extend((key, others[key]) for key in sorted(others, key=str))
    if nulls:
        pairs.append((None, nulls))
    return pairs


def value_counts(raw_values: list) -> list[tuple[object, int]]:
    """``(value, count)`` pairs for one raw column, same contract as
    :func:`normalize_group_counts` over an ad-hoc value list.

    Keys carry a bool tag because ``True`` hashes equal to ``1``: a
    plain dict would merge them, silently dropping the boolean group."""
    counts: dict = {}
    for value in raw_values:
        key = (isinstance(value, bool), value)
        counts[key] = counts.get(key, 0) + 1
    return normalize_group_counts(
        [{"_id": key[1], "count": count} for key, count in counts.items()]
    )


def create_histogram(
    store: DocumentStore,
    parent_filename: str,
    histogram_filename: str,
    fields: list[str],
) -> None:
    """Build the histogram collection with the reference's document shape
    (histogram.py:50-74): metadata at ``_id: 0`` then one document per
    field containing its ``[{_id: value, count: n}, ...]`` list."""
    store.insert_one(
        histogram_filename,
        {
            "filename_parent": parent_filename,
            "fields": fields,
            "filename": histogram_filename,
            ROW_ID: METADATA_ID,
        },
    )
    for document_id, field in enumerate(fields, start=1):
        # $group pushdown, exactly the reference's Mongo aggregation
        # (histogram.py:63-69): the store counts — its columnar fast
        # path skips row synthesis entirely — and only (value, count)
        # pairs ride the wire, never the raw column.
        groups = store.aggregate(
            parent_filename,
            [{"$group": {"_id": f"${field}", "count": {"$sum": 1}}}],
        )
        store.insert_one(
            histogram_filename,
            {
                field: [
                    {"_id": value, "count": count}
                    for value, count in normalize_group_counts(groups)
                ],
                ROW_ID: document_id,
            },
        )
