"""t-SNE on device: exact, data-parallel over the mesh, with a landmark
path for datasets past the O(n²) wall.

Replaces the reference's driver-side ``sklearn.manifold.TSNE()
.fit_transform`` (reference: microservices/tsne_image/tsne.py:87-88) —
single-host, O(n²), the headline scalability cliff (SURVEY.md §3.4,
BASELINE.json north-star metric).

TPU shape — every stage is matmul/elementwise:

- pairwise squared distances via ``‖x‖² + ‖y‖² − 2 X Xᵀ`` (MXU);
- per-row bandwidth calibration to the target perplexity as a
  vectorized 32-step bisection (no data-dependent Python control flow);
- the gradient ``4 (diag(W·1) − W) Y`` as two matmuls per iteration
  inside ``lax.fori_loop`` with momentum + adaptive gains, early
  exaggeration folded in by phase.

Parallelism: both the affinity build and the gradient loop run under
``jax.shard_map`` with rows split over the mesh's ``data`` axis — each
chip owns an ``(n/D, n)`` slab of P and of the repulsion matrix, the
single global scalar (the Q normalizer) is a ``psum`` over ICI, and the
``(n, 2)`` gradient is an ``all_gather`` (tiny) so the embedding state
stays replicated. Rows are zero-padded to the mesh size with a validity
mask; padded rows have zero affinity and zero repulsion weight, so they
never influence real points. Per-chip memory is O(n²/D), the exact
algorithm's floor.

Past ``EXACT_ROWS_LIMIT`` rows the ``landmark`` method runs exact t-SNE
on a random subsample and places every remaining row by
perplexity-calibrated kernel regression onto the landmark embedding —
an ``(n, m)`` matmul pipeline that is row-sharded and chunked, so 1M+
rows fit comfortably on one chip and scale linearly with the data axis.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as PSpec

from learningorchestra_tpu.ml.base import resolve_mesh
from learningorchestra_tpu.parallel.mesh import DATA_AXIS, data_size
from learningorchestra_tpu.parallel.multihost import fetch

PERPLEXITY = 30.0
ITERATIONS = 1000
EARLY_EXAGGERATION = 12.0
EARLY_PHASE = 250
LEARNING_RATE = 200.0
CHUNK = 1024
# Exact t-SNE holds O(n²/D) per chip; past this the landmark path wins.
EXACT_ROWS_LIMIT = 20_000
LANDMARKS = 5_000
INTERP_CHUNK = 8_192
# Rows per _interpolate dispatch: keeps one interpolation program well
# under remote-execution watchdogs at any n (see ml/base.segment_steps).
_INTERP_ROWS_PER_PROGRAM = 4_000_000


def _squared_distances(A, B):
    # precision=HIGHEST: the TPU's default bf16 matmul makes
    # ‖a‖²+‖b‖²−2ab come out slightly NEGATIVE for near neighbors once
    # coordinates grow; 1/(1+d) then blows past zero and the whole
    # optimization NaNs. Full-f32 passes on the MXU cost ~3× on this one
    # contraction and keep the identity non-negative to rounding.
    return jnp.maximum(
        jnp.sum(A**2, axis=1)[:, None]
        + jnp.sum(B**2, axis=1)[None, :]
        - 2.0 * jnp.dot(A, B.T, precision=jax.lax.Precision.HIGHEST),
        0.0,
    )


def _calibrate_row_block(block_distances, excluded, perplexity):
    """Per-row Gaussian bandwidths matching ``log(perplexity)`` entropy,
    by bisection on beta = 1/(2σ²). Fully vectorized over the block.
    ``excluded`` masks columns that must get zero affinity (each row's
    own column, padding) — self-affinity is excluded by INDEX, so
    duplicate rows keep their (maximal) mutual affinity like sklearn's
    TSNE."""
    target = jnp.log(perplexity)

    def entropy_and_p(beta):
        # numerically stable: distances are shifted per-row
        logits = -block_distances * beta[:, None]
        logits = logits - logits.max(axis=1, keepdims=True)
        p = jnp.exp(logits)
        p = p * ~excluded
        total = jnp.maximum(p.sum(axis=1, keepdims=True), 1e-12)
        p = p / total
        entropy = -jnp.sum(jnp.where(p > 0, p * jnp.log(p), 0.0), axis=1)
        return entropy, p

    def bisect(state, _):
        low, high, beta = state
        entropy, _ = entropy_and_p(beta)
        too_high = entropy > target  # entropy too high → increase beta
        low = jnp.where(too_high, beta, low)
        high = jnp.where(too_high, high, beta)
        beta = jnp.where(
            jnp.isinf(high), beta * 2.0, (low + high) / 2.0
        )
        return (low, high, beta), None

    m = block_distances.shape[0]
    init = (
        jnp.zeros(m),
        jnp.full(m, jnp.inf),
        jnp.ones(m),
    )
    (_, _, beta), _ = jax.lax.scan(bisect, init, length=32)
    _, p = entropy_and_p(beta)
    return p


@partial(jax.jit, static_argnames=("mesh", "chunk"))
def _affinities(mesh: Mesh, X, valid, perplexity, chunk: int):
    """Symmetrized conditional affinities P, row-sharded over ``data``.

    ``X``/``valid`` are replicated ``(n_pad, …)``; each chip builds its
    own ``(n_pad/D, n_pad)`` slab, chunked block-of-rows at a time so
    the distance transient is ``(chunk, n_pad)``, not the full square.
    Padded rows/columns get exactly zero affinity.
    """
    n_pad = X.shape[0]
    shards = data_size(mesh)
    local = n_pad // shards
    pad_local = -(-local // chunk) * chunk

    def local_slab(X_full, valid_full):
        row0 = jax.lax.axis_index(DATA_AXIS) * local
        X_local = jax.lax.dynamic_slice_in_dim(X_full, row0, local, 0)
        X_local = jnp.pad(X_local, ((0, pad_local - local), (0, 0)))
        blocks = X_local.reshape(-1, chunk, X_full.shape[1])
        offsets = row0 + jnp.arange(blocks.shape[0]) * chunk

        def one_block(args):
            block, offset = args
            distances = _squared_distances(block, X_full)
            rows = offset + jnp.arange(chunk)
            excluded = (rows[:, None] == jnp.arange(n_pad)[None, :]) | (
                ~valid_full[None, :]
            )
            p = _calibrate_row_block(distances, excluded, perplexity)
            # zero out padded rows (clamped indexing is fine: overhang
            # rows are sliced off below)
            return p * valid_full[jnp.minimum(rows, n_pad - 1), None]

        slab = jax.lax.map(one_block, (blocks, offsets))
        return slab.reshape(pad_local, n_pad)[:local]

    P = jax.shard_map(
        local_slab,
        mesh=mesh,
        in_specs=(PSpec(), PSpec()),
        out_specs=PSpec(DATA_AXIS),
        check_vma=False,
    )(X, valid)
    n_valid = valid.sum().astype(P.dtype)
    P = (P + P.T) / (2.0 * n_valid)
    return jnp.maximum(P, 1e-12)


@partial(jax.jit, static_argnames=("mesh", "iterations", "early_phase"))
def _optimize(
    mesh: Mesh, P, Y0, valid, iterations: int, early_phase: int,
    learning_rate, exaggeration,
):
    """Gradient descent with momentum + adaptive gains, sharded like P:
    each chip computes its row slab of the attraction/repulsion matrix,
    the Q normalizer is one psum, and the (n, 2) gradient is
    all_gathered so Y/velocity/gains stay replicated (tiny state)."""
    n_pad = Y0.shape[0]
    shards = data_size(mesh)
    local = n_pad // shards

    def run(P_local, Y0_full, valid_full):
        row0 = jax.lax.axis_index(DATA_AXIS) * local
        valid_local = jax.lax.dynamic_slice_in_dim(valid_full, row0, local, 0)
        rows = row0 + jnp.arange(local)
        pair_mask = (
            valid_local[:, None]
            & valid_full[None, :]
            & (rows[:, None] != jnp.arange(n_pad)[None, :])
        )

        def gradient(Y, P_eff):
            Y_local = jax.lax.dynamic_slice_in_dim(Y, row0, local, 0)
            distances = _squared_distances(Y_local, Y)
            inv = (1.0 / (1.0 + distances)) * pair_mask
            total = jax.lax.psum(inv.sum(), DATA_AXIS)
            Q = inv / jnp.maximum(total, 1e-12)
            W = (P_eff - jnp.maximum(Q, 1e-12)) * inv
            grad_local = 4.0 * (
                W.sum(axis=1)[:, None] * Y_local
                - jnp.dot(W, Y, precision=jax.lax.Precision.HIGHEST)
            )
            return jax.lax.all_gather(
                grad_local, DATA_AXIS, axis=0, tiled=True
            )

        def step(i, state):
            Y, velocity, gains = state
            P_eff = jnp.where(i < early_phase, P_local * exaggeration, P_local)
            grad = gradient(Y, P_eff).astype(Y.dtype)
            momentum = jnp.where(i < early_phase, 0.5, 0.8).astype(Y.dtype)
            same_sign = jnp.sign(grad) == jnp.sign(velocity)
            gains = jnp.maximum(
                jnp.where(same_sign, gains * 0.8, gains + 0.2), 0.01
            )
            velocity = momentum * velocity - learning_rate * gains * grad
            return Y + velocity, velocity, gains

        Y, _, _ = jax.lax.fori_loop(
            0,
            iterations,
            step,
            (Y0_full, jnp.zeros_like(Y0_full), jnp.ones_like(Y0_full)),
        )
        return Y

    return jax.shard_map(
        run,
        mesh=mesh,
        in_specs=(PSpec(DATA_AXIS), PSpec(), PSpec()),
        out_specs=PSpec(),
        check_vma=False,
    )(P, Y0, valid)


def _pad_for_mesh(X: np.ndarray, mesh: Mesh, chunk: int) -> tuple:
    """Zero-pad rows to the bucketed shape grid (sharding.bucket_rows —
    nearby sizes reuse one compiled affinity/optimize program), build
    the validity mask, and pick the per-chip chunk size."""
    from learningorchestra_tpu.parallel.sharding import padded_row_count

    shards = data_size(mesh)
    n = len(X)
    n_pad = padded_row_count(n, shards)
    valid = np.zeros(n_pad, dtype=bool)
    valid[:n] = True
    X_pad = np.pad(X, ((0, n_pad - n), (0, 0)))
    chunk = max(1, min(chunk, n_pad // shards))
    return X_pad, valid, chunk


def _tsne_exact(
    X: np.ndarray,
    mesh: Mesh,
    perplexity: float,
    iterations: int,
    learning_rate: float,
    seed: int,
) -> np.ndarray:
    n = len(X)
    X_pad, valid, chunk = _pad_for_mesh(X, mesh, CHUNK)
    replicated = NamedSharding(mesh, PSpec())
    X_dev = jax.device_put(jnp.asarray(X_pad), replicated)
    valid_dev = jax.device_put(jnp.asarray(valid), replicated)
    return _tsne_exact_on_device(
        X_dev, valid_dev, n, mesh, perplexity, iterations, learning_rate,
        seed, chunk,
    )


def _tsne_exact_on_device(
    X_dev,
    valid_dev,
    n: int,
    mesh: Mesh,
    perplexity: float,
    iterations: int,
    learning_rate: float,
    seed: int,
    chunk: int,
) -> np.ndarray:
    """Exact t-SNE over already-replicated device buffers — the shared
    tail of the host-array path and the cached-DeviceMatrix path (which
    reshards the cached row-sharded buffers on device instead of
    re-crossing the PCIe boundary)."""
    perplexity = min(perplexity, max((n - 1) / 3.0, 1.0))
    replicated = NamedSharding(mesh, PSpec())
    P = _affinities(mesh, X_dev, valid_dev, jnp.float32(perplexity), chunk)
    Y0 = (
        jax.random.normal(
            jax.random.key(seed), (X_dev.shape[0], 2), jnp.float32
        )
        * 1e-4
    )
    Y0 = jax.device_put(Y0, replicated)
    Y = _optimize(
        mesh,
        P,
        Y0,
        valid_dev,
        iterations,
        min(EARLY_PHASE, iterations // 2),
        jnp.float32(learning_rate),
        jnp.float32(EARLY_EXAGGERATION),
    )
    from learningorchestra_tpu.telemetry import profile, span

    with span("d2h:tsne", rows=n):
        out = fetch(Y)[:n]
        profile.account_d2h(int(np.asarray(out).nbytes))
        return out


@partial(jax.jit, static_argnames=("mesh", "chunk"))
def _interpolate(mesh: Mesh, X, landmarks, Y_landmarks, perplexity, chunk: int):
    """Out-of-sample placement: perplexity-calibrated Gaussian affinities
    from each row to the landmark set, then one ``P @ Y_L`` matmul. Rows
    are sharded over ``data`` and processed in chunks, so the transient
    is ``(chunk, m)`` per chip — linear scaling in n."""
    n_pad = X.shape[0]
    local = n_pad // data_size(mesh)

    def run(X_local, L_full, Y_full):
        blocks = X_local.reshape(-1, chunk, X_local.shape[1])

        def one_block(block):
            distances = _squared_distances(block, L_full)
            excluded = jnp.zeros(distances.shape, bool)
            p = _calibrate_row_block(distances, excluded, perplexity)
            return jnp.dot(p, Y_full, precision=jax.lax.Precision.HIGHEST)

        return jax.lax.map(one_block, blocks).reshape(local, 2)

    return jax.shard_map(
        run,
        mesh=mesh,
        in_specs=(PSpec(DATA_AXIS), PSpec(), PSpec()),
        out_specs=PSpec(DATA_AXIS),
        check_vma=False,
    )(X, landmarks, Y_landmarks)


def _tsne_landmark(
    X: np.ndarray,
    mesh: Mesh,
    perplexity: float,
    iterations: int,
    learning_rate: float,
    seed: int,
    landmarks: int,
) -> np.ndarray:
    from learningorchestra_tpu.telemetry import span

    n = len(X)
    rng = np.random.default_rng(seed)
    m = min(landmarks, n)
    chosen = rng.choice(n, size=m, replace=False)
    L = X[chosen]
    # Phase spans: the landmark path is (exact fit on m rows) +
    # (interpolate n rows); each phase ends in a blocking fetch, so
    # these wall-clocks are honest — they are the attribution that
    # localizes a landmark-path regression to the phase that moved
    # (bench.py reports them per run; --compare diffs them).
    with span("tsne:landmark_fit", rows=m):
        Y_L = _tsne_exact(L, mesh, perplexity, iterations, learning_rate, seed)
    if m == n:
        # Every row IS a landmark: the exact embedding is already the
        # answer — undo the sampling permutation instead of blurring it
        # through interpolation.
        out = np.empty((n, 2), np.float32)
        out[chosen] = Y_L
        return out

    shards = data_size(mesh)
    chunk = min(INTERP_CHUNK, -(-n // shards))
    multiple = shards * chunk
    replicated = NamedSharding(mesh, PSpec())
    row_sharded = NamedSharding(mesh, PSpec(DATA_AXIS))
    L_dev = jax.device_put(jnp.asarray(L), replicated)
    Y_L_dev = jax.device_put(jnp.asarray(Y_L, np.float32), replicated)
    interp_perplexity = min(perplexity, max((m - 1) / 3.0, 1.0))

    # Macro-batch the interpolation: one _interpolate call is ONE XLA
    # program sequentially mapping its blocks, and at 100M rows that is
    # a ~20-minute single execution — execution watchdogs on
    # remotely-attached chips kill it (same constraint as
    # ml/base.segment_steps). Below the per-program row budget the
    # macro shape follows the BUCKETED dataset size (a 100k dataset
    # must not ride a 4M-row padded program — that 40x compute waste
    # was round 4's 1.1s -> 21.5s landmark regression at 100k); above
    # it, fixed-size slices keep every program short and identically
    # shaped (one compile), the tail slice padded and cropped.
    from learningorchestra_tpu.parallel.sharding import padded_row_count

    if n <= _INTERP_ROWS_PER_PROGRAM:
        macro = padded_row_count(n, multiple)
    else:
        macro = max(
            multiple, (_INTERP_ROWS_PER_PROGRAM // multiple) * multiple
        )
    with span(
        "tsne:interpolate", rows=n, landmarks=m, macro_rows=macro
    ):
        outs = []
        for start in range(0, n, macro):
            stop = min(start + macro, n)
            block = X[start:stop]
            padded = np.pad(block, ((0, macro - len(block)), (0, 0)))
            X_dev = jax.device_put(jnp.asarray(padded), row_sharded)
            Y = _interpolate(
                mesh, X_dev, L_dev, Y_L_dev, jnp.float32(interp_perplexity),
                chunk,
            )
            outs.append(np.asarray(fetch(Y))[: len(block)])
    return np.concatenate(outs) if len(outs) > 1 else outs[0]


def tsne_embedding(
    X,
    perplexity: float = PERPLEXITY,
    iterations: int = ITERATIONS,
    learning_rate: float = LEARNING_RATE,
    seed: int = 0,
    mesh: Optional[Mesh] = None,
    method: str = "auto",
    exact_rows_limit: int = EXACT_ROWS_LIMIT,
    landmarks: int = LANDMARKS,
) -> np.ndarray:
    """2-D t-SNE embedding of ``X``. Returns ``(rows, 2)``.

    ``method``: ``"exact"`` (O(n²/chip), sharded over the data axis),
    ``"landmark"`` (exact on a subsample + calibrated kernel regression
    for the rest — linear in n), or ``"auto"`` (exact up to
    ``exact_rows_limit`` rows).

    ``X`` may be an already-sharded :class:`~learningorchestra_tpu.ml.
    base.DeviceMatrix` (the device cache's currency, core/devcache.py).
    The exact path reshards the cached buffers on device — the dataset
    never re-crosses the PCIe boundary and only the ``(rows, 2)``
    embedding comes back. The landmark path needs host rows for
    subsampling and macro-batching, so a cached matrix pays one D2H
    there — still strictly cheaper than re-reading the store over the
    wire. (Same padded-shape rule both ways: ``shard_rows`` and
    ``_pad_for_mesh`` share ``padded_row_count``.)
    """
    from learningorchestra_tpu.ml.base import DeviceMatrix

    mesh = resolve_mesh(mesh)
    if isinstance(X, DeviceMatrix):
        n = len(X)
        if method == "auto":
            method = "exact" if n <= exact_rows_limit else "landmark"
        if (
            method == "exact"
            and X.mesh is mesh
            and jax.process_count() == 1
        ):
            shards = data_size(mesh)
            chunk = max(1, min(CHUNK, X.data.shape[0] // shards))
            replicated_sharding = NamedSharding(mesh, PSpec())
            return _tsne_exact_on_device(
                jax.device_put(X.data.astype(jnp.float32), replicated_sharding),
                jax.device_put(X.mask, replicated_sharding),
                n,
                mesh,
                perplexity,
                iterations,
                learning_rate,
                seed,
                chunk,
            )
        # landmark (or mesh/process mismatch): one D2H of the cached
        # buffer replaces the wire read (fetch gathers across hosts —
        # every process enters tsne_embedding, so the collective lines
        # up)
        X = np.asarray(fetch(X.data))[:n]
    X = np.asarray(X, np.float32)
    if method == "auto":
        method = "exact" if len(X) <= exact_rows_limit else "landmark"
    if method == "exact":
        return _tsne_exact(X, mesh, perplexity, iterations, learning_rate, seed)
    if method == "landmark":
        return _tsne_landmark(
            X, mesh, perplexity, iterations, learning_rate, seed, landmarks
        )
    raise ValueError(f"unknown t-SNE method {method!r}")
