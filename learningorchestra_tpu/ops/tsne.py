"""Exact t-SNE as one jitted device program.

Replaces the reference's driver-side ``sklearn.manifold.TSNE()
.fit_transform`` (reference: microservices/tsne_image/tsne.py:87-88) —
single-host, O(n²), the headline scalability cliff (SURVEY.md §3.4,
BASELINE.json north-star metric).

TPU shape: every stage is matmul/elementwise —

- pairwise squared distances via ``‖x‖² + ‖y‖² − 2 X Xᵀ`` (MXU);
- per-row bandwidth calibration to the target perplexity as a
  vectorized 32-step bisection (no data-dependent Python control flow);
- the gradient ``4 (diag(W·1) − W) Y`` as two matmuls per iteration
  inside ``lax.fori_loop`` with momentum + adaptive gains, early
  exaggeration folded in by phase.

Memory is O(n²) on device, like exact t-SNE everywhere; the affinity
build is chunked over row blocks (``lax.map``) so the transient
distance tensor stays bounded. Defaults match the reference's sklearn
0.23: perplexity 30, 1000 iterations, early exaggeration 12 for the
first 250.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from learningorchestra_tpu.ml.base import resolve_mesh

PERPLEXITY = 30.0
ITERATIONS = 1000
EARLY_EXAGGERATION = 12.0
EARLY_PHASE = 250
LEARNING_RATE = 200.0
CHUNK = 1024


def _squared_distances(A, B):
    return (
        jnp.sum(A**2, axis=1)[:, None]
        + jnp.sum(B**2, axis=1)[None, :]
        - 2.0 * A @ B.T
    )


def _calibrate_row_block(block_distances, self_mask, perplexity):
    """Per-row Gaussian bandwidths matching ``log(perplexity)`` entropy,
    by bisection on beta = 1/(2σ²). Fully vectorized over the block.
    ``self_mask`` marks each row's own column — self-affinity is excluded
    by INDEX, so duplicate rows keep their (maximal) mutual affinity like
    sklearn's TSNE."""
    target = jnp.log(perplexity)

    def entropy_and_p(beta):
        # numerically stable: distances are shifted per-row
        logits = -block_distances * beta[:, None]
        logits = logits - logits.max(axis=1, keepdims=True)
        p = jnp.exp(logits)
        p = p * ~self_mask
        total = jnp.maximum(p.sum(axis=1, keepdims=True), 1e-12)
        p = p / total
        entropy = -jnp.sum(jnp.where(p > 0, p * jnp.log(p), 0.0), axis=1)
        return entropy, p

    def bisect(state, _):
        low, high, beta = state
        entropy, _ = entropy_and_p(beta)
        too_high = entropy > target  # entropy too high → increase beta
        low = jnp.where(too_high, beta, low)
        high = jnp.where(too_high, high, beta)
        beta = jnp.where(
            jnp.isinf(high), beta * 2.0, (low + high) / 2.0
        )
        return (low, high, beta), None

    m = block_distances.shape[0]
    init = (
        jnp.zeros(m),
        jnp.full(m, jnp.inf),
        jnp.ones(m),
    )
    (_, _, beta), _ = jax.lax.scan(bisect, init, length=32)
    _, p = entropy_and_p(beta)
    return p


@partial(jax.jit, static_argnames=("chunk",))
def _affinities(X, perplexity, chunk: int):
    """Symmetrized conditional affinities P, built block-of-rows at a
    time so the distance transient is (chunk, n), not (n, n) twice."""
    n = X.shape[0]
    pad = (-n) % chunk
    X_padded = jnp.pad(X, ((0, pad), (0, 0)))
    blocks = X_padded.reshape(-1, chunk, X.shape[1])
    offsets = jnp.arange(blocks.shape[0]) * chunk

    def one_block(args):
        block, offset = args
        distances = _squared_distances(block, X)
        rows = offset + jnp.arange(chunk)
        self_mask = rows[:, None] == jnp.arange(n)[None, :]
        return _calibrate_row_block(distances, self_mask, perplexity)

    P = jax.lax.map(one_block, (blocks, offsets)).reshape(-1, n)[:n]
    P = (P + P.T) / (2.0 * n)
    return jnp.maximum(P, 1e-12)


@partial(jax.jit, static_argnames=("iterations", "early_phase"))
def _optimize(P, Y0, iterations: int, early_phase: int, learning_rate, exaggeration):
    n = Y0.shape[0]

    def gradient(Y, P_eff):
        distances = _squared_distances(Y, Y)
        inv = 1.0 / (1.0 + distances)
        inv = inv * (1.0 - jnp.eye(n, dtype=Y.dtype))
        Q = inv / jnp.maximum(inv.sum(), 1e-12)
        W = (P_eff - jnp.maximum(Q, 1e-12)) * inv
        return 4.0 * (W.sum(axis=1)[:, None] * Y - W @ Y)

    def step(i, state):
        Y, velocity, gains = state
        P_eff = jnp.where(i < early_phase, P * exaggeration, P)
        grad = gradient(Y, P_eff).astype(Y.dtype)
        momentum = jnp.where(i < early_phase, 0.5, 0.8).astype(Y.dtype)
        same_sign = jnp.sign(grad) == jnp.sign(velocity)
        gains = jnp.maximum(
            jnp.where(same_sign, gains * 0.8, gains + 0.2), 0.01
        )
        velocity = momentum * velocity - learning_rate * gains * grad
        return Y + velocity, velocity, gains

    Y, _, _ = jax.lax.fori_loop(
        0,
        iterations,
        step,
        (Y0, jnp.zeros_like(Y0), jnp.ones_like(Y0)),
    )
    return Y


def tsne_embedding(
    X: np.ndarray,
    perplexity: float = PERPLEXITY,
    iterations: int = ITERATIONS,
    learning_rate: float = LEARNING_RATE,
    seed: int = 0,
    mesh: Optional[Mesh] = None,
) -> np.ndarray:
    """2-D t-SNE embedding of ``X``. Returns ``(rows, 2)``."""
    resolve_mesh(mesh)  # device presence check; single program, no sharding yet
    X = np.asarray(X, np.float32)
    n = len(X)
    perplexity = min(perplexity, max((n - 1) / 3.0, 1.0))
    P = _affinities(jnp.asarray(X), jnp.float32(perplexity), min(CHUNK, n))
    Y0 = (
        jax.random.normal(jax.random.key(seed), (n, 2), jnp.float32) * 1e-4
    )
    Y = _optimize(
        P,
        Y0,
        iterations,
        min(EARLY_PHASE, iterations // 2),
        jnp.float32(learning_rate),
        jnp.float32(EARLY_EXAGGERATION),
    )
    return np.asarray(Y)
