"""Typed columnar blocks: the store's cell engine.

The reference delegates typed storage to MongoDB's BSON (reference:
microservices/database_api_image/database.py:94-130 stores documents;
Mongo owns the bytes). Round 3 kept dataset bodies as Python lists of
boxed objects — ~60-100 bytes of interpreter overhead per cell — which
capped the store at ~1M rows. This module is the fix: a :class:`Column`
holds one field of a dataset block as a typed numpy buffer:

- ``f8``  — float64 values
- ``i8``  — int64 values
- ``num`` — mixed int/float: float64 data + an int-mask so ``28``
  round-trips as ``28`` and ``2.5`` as ``2.5`` (the dtype converter's
  int-collapse contract, ops/dtype.py)
- ``bool`` — bools (kept distinct from ``1``: Mongo's ``$group``
  separates them, reference histogram.py:63-69)
- ``str`` — Arrow-style UTF-8 byte buffer + int64 offsets (dataset
  bodies arrive as raw strings at ingest — reference database.py:156-169
  — so string cells must be unboxed too, not just numbers)
- ``vec`` — fixed-width float64 vectors as one ``(rows, width)`` matrix;
  cells materialize as per-row plain lists only at document reads. The
  probability column the model builder persists for every test row
  (reference model_builder.py:232-247 converts Spark's probability
  vector per row) would otherwise box millions of Python lists.
- ``obj`` — Python-list fallback for mixed/irregular cells (document
  overlays, ragged vectors)

``None`` (explicit null) and *missing* (a row that predates a
later-added field — Mongo's absent-field state) are tracked in packed
side masks, never as boxed sentinels in the data.

Concurrency: columns are copy-on-write. ``snapshot()`` marks buffers
shared; readers work outside the store lock while writers copy before
the first in-place mutation. Appends never copy — they land beyond any
snapshot's recorded ``size``.

The same buffers serialize three ways with zero per-cell work: the
binary HTTP wire (core/wire.py), base64 WAL records (crash recovery /
replication), and numpy hand-off to the compute layer (core/table.py).
"""

from __future__ import annotations

import base64
import os
from collections import Counter
from typing import Any, Iterable, Optional

import numpy as np

__all__ = ["Column", "FrameOwner", "MISSING", "merge_kind"]


class FrameOwner:
    """Ownership token for a zero-copy decoded wire frame: every column
    view of one frame references the SAME aligned backing buffer
    through this token, so pinning any decoded column (the device
    cache's host tier) keeps exactly one allocation alive — and
    ``nbytes`` tells the pinning cache what that costs. The buffer is
    read-only; a column view marked ``_shared`` copies before any
    in-place mutation, so a caller can never corrupt a pinned frame."""

    __slots__ = ("base",)

    def __init__(self, base):
        self.base = base

    @property
    def nbytes(self) -> int:
        return int(self.base.nbytes)


class _Missing:
    """Pad value for block rows that genuinely lack a field. Distinct
    from ``None`` (an explicit null) so synthesized documents keep
    Mongo's missing-field semantics ($exists, $ne on absent fields).
    Never escapes the store: columnar reads map pads to ``None``."""

    __slots__ = ()

    def __repr__(self):
        return "<missing>"


MISSING = _Missing()

EMPTY = "empty"  # only pads so far; adopts the kind of the first data
F8 = "f8"
I8 = "i8"
NUM = "num"
BOOL = "bool"
STR = "str"
VEC = "vec"
OBJ = "obj"

_NUMERIC_KINDS = frozenset((F8, I8, NUM))
_DTYPES = {F8: np.float64, I8: np.int64, NUM: np.float64, BOOL: np.bool_,
           VEC: np.float64}


def merge_kind(a: str, b: str) -> str:
    """Width-blind kind merge; ``vec``+``vec`` of differing widths is
    resolved to ``obj`` in ``append_column`` (widths live on the data
    buffers, not the kind tags)."""
    if a == b:
        return a
    if a == EMPTY:
        return b
    if b == EMPTY:
        return a
    if a in _NUMERIC_KINDS and b in _NUMERIC_KINDS:
        return NUM
    return OBJ


def _classify(values: Iterable) -> tuple[str, bool, bool]:
    """(kind, has_none, has_missing) for raw Python values. The type-set
    scan is a single C loop; per-value Python dispatch happens only for
    genuinely mixed columns (→ obj, where it is unavoidable)."""
    types = {type(v) for v in values}
    has_none = type(None) in types
    has_missing = _Missing in types
    types.discard(type(None))
    types.discard(_Missing)
    kind = EMPTY
    for t in types:
        if t is bool or issubclass(t, np.bool_):
            k = BOOL
        elif issubclass(t, (int, np.integer)):
            k = I8
        elif issubclass(t, (float, np.floating)):
            k = F8
        elif issubclass(t, str):
            k = STR
        else:
            k = OBJ
        kind = merge_kind(kind, k)
    return kind, has_none, has_missing


def _object_array(values: list) -> np.ndarray:
    out = np.empty(len(values), dtype=object)
    out[:] = values
    return out


def _group_key(value):
    """Canonical hashable grouping key: bools tagged apart from their
    numeric equals PER ELEMENT (so [True] and [1] stay distinct groups,
    like the scalar path); unhashable nested cells (dicts, lists inside
    lists come back from tolist as lists) fall back to repr."""
    if isinstance(value, list):
        return tuple(_group_key(element) for element in value)
    try:
        hash(value)
    except TypeError:
        return ("__unhashable__", repr(value))
    return (isinstance(value, bool), value)


def _pack(mask: Optional[np.ndarray], size: int) -> Optional[np.ndarray]:
    """Packed-bit buffer for the wire — handed over as the packbits
    array itself (a fresh allocation already), never a second
    ``tobytes`` copy (LO106)."""
    if mask is None:
        return None
    return np.packbits(mask[:size])


def _unpack(raw, size: int) -> Optional[np.ndarray]:
    if raw is None:
        return None
    bits = (
        raw
        if isinstance(raw, np.ndarray)
        else np.frombuffer(raw, dtype=np.uint8)
    )
    if size > 8 * len(bits):
        # np.unpackbits with count past the buffer reads OUT OF BOUNDS
        # silently (observed: garbage bytes, no error) — a short mask
        # buffer must raise like any other truncated wire payload
        raise ValueError("packed mask shorter than the row count")
    return np.unpackbits(bits, count=size).astype(bool)


def _b64(raw) -> Optional[str]:
    """Base64 of any bytes-like buffer (bytes or a contiguous numpy
    view — wire_parts hands over views, never tobytes copies)."""
    return None if raw is None else base64.b64encode(raw).decode("ascii")


def _unb64(text: Optional[str]) -> Optional[bytes]:
    return None if text is None else base64.b64decode(text)


def _encode_strings(values: list) -> tuple[np.ndarray, np.ndarray]:
    """Python strings → (uint8 byte buffer, int64 offsets). One joined
    encode; char offsets are reused as byte offsets when the payload is
    pure ASCII (the overwhelmingly common case)."""
    n = len(values)
    joined = "".join(values)
    encoded = joined.encode("utf-8")
    if len(encoded) == len(joined):  # ASCII: char lengths == byte lengths
        lengths = np.fromiter(map(len, values), dtype=np.int64, count=n)
    else:
        lengths = np.fromiter(
            (len(v.encode("utf-8")) for v in values), dtype=np.int64, count=n
        )
    offsets = np.empty(n + 1, dtype=np.int64)
    offsets[0] = 0
    np.cumsum(lengths, out=offsets[1:])
    # the ingest path builds an OWNED, appendable byte buffer from the
    # transient encode — this copy IS the allocation, not a redundancy
    # lo: allow[LO106]
    return np.frombuffer(encoded, dtype=np.uint8).copy(), offsets


class Column:
    """One field of a dataset block. See module docstring for kinds.

    Internal geometry: ``data``/masks are capacity buffers; ``size`` is
    the live prefix. For ``str``, ``data`` is the byte buffer (live
    prefix ``offsets[size]``) and ``offsets`` has ``size + 1`` live
    entries. ``edits`` (str only) overlays single-cell updates so a
    point write into an Arrow layout is O(1), not an O(n) rebuild.
    """

    __slots__ = (
        "kind",
        "size",
        "data",
        "offsets",
        "none",
        "miss",
        "intm",
        "edits",
        "_shared",
        "spill",
        "owner",
    )

    def __init__(self, kind: str = EMPTY):
        self.kind = kind
        self.size = 0
        # Zero-copy wire decode: the FrameOwner whose aligned buffer
        # this column's data/offsets view into (None = owned buffers).
        self.owner: Optional["FrameOwner"] = None
        # Out-of-core state: {"dir", "prefix"} once the payload lives in
        # disk-backed mappings (spill_to); None = all-RAM buffers.
        self.spill: Optional[dict] = None
        if kind == OBJ:
            self.data: Any = []
        elif kind == VEC:
            self.data = np.empty((0, 0), dtype=np.float64)
        else:
            self.data = np.empty(0, dtype=_DTYPES.get(kind, np.uint8))
        self.offsets: Optional[np.ndarray] = (
            np.zeros(1, dtype=np.int64) if kind == STR else None
        )
        self.none: Optional[np.ndarray] = None
        self.miss: Optional[np.ndarray] = None
        self.intm: Optional[np.ndarray] = None
        self.edits: Optional[dict[int, Any]] = None
        self._shared = False

    # --- constructors ---------------------------------------------------------
    @classmethod
    def from_values(cls, values) -> "Column":
        """Build from raw Python values (the JSON-wire / document path)."""
        if isinstance(values, np.ndarray) and values.dtype != object:
            return cls.from_numpy(values)
        values = list(values)
        kind, has_none, has_missing = _classify(values)
        column = cls._build(values, kind, has_none, has_missing)
        return column

    @classmethod
    def _build(
        cls, values: list, kind: str, has_none: bool, has_missing: bool
    ) -> "Column":
        n = len(values)
        column = cls(OBJ if kind == OBJ else kind)
        column.size = n
        if kind == OBJ:
            column.data = list(values)
            if has_missing:
                column.miss = np.fromiter(
                    (v is MISSING for v in values), dtype=bool, count=n
                )
                column.data = [None if v is MISSING else v for v in values]
            return column
        absent = None
        if has_none or has_missing:
            obj = _object_array(values)
            if has_none:
                column.none = np.fromiter(
                    (v is None for v in values), dtype=bool, count=n
                )
            if has_missing:
                column.miss = np.fromiter(
                    (v is MISSING for v in values), dtype=bool, count=n
                )
            absent = (
                column.none
                if column.miss is None
                else (
                    column.miss
                    if column.none is None
                    else column.none | column.miss
                )
            )
        if kind == EMPTY:
            # only None/MISSING cells: keep an empty-kind column; data
            # buffer is a placeholder until real values merge in
            column.data = np.zeros(n, dtype=np.uint8)
            return column
        if kind == STR:
            if absent is not None:
                values = [
                    "" if (v is None or v is MISSING) else v for v in values
                ]
            column.data, column.offsets = _encode_strings(values)
            return column
        try:
            if absent is not None:
                obj[absent] = False if kind == BOOL else 0
                column.data = obj.astype(_DTYPES[kind])
            else:
                column.data = np.asarray(values, dtype=_DTYPES[kind])
        except OverflowError:
            # e.g. a Python int beyond int64 — keep the boxed fallback
            return cls._build(values, OBJ, has_none, has_missing)
        if kind == NUM:
            column.intm = np.fromiter(
                (type(v) is not float and not isinstance(v, np.floating)
                 for v in values),
                dtype=bool,
                count=n,
            )
            if absent is not None:
                column.intm[absent] = False
        if kind == F8 and column.none is None:
            nan = np.isnan(column.data)
            if nan.any():
                # NaN cells behave as nulls end to end (JSON has no NaN)
                column.none = nan
        elif kind == F8 and column.none is not None:
            column.data[column.none] = np.nan
        return column

    @classmethod
    def from_numpy(
        cls, array: np.ndarray, none: Optional[np.ndarray] = None
    ) -> "Column":
        """Zero-conversion constructor from a typed numpy array — the
        compute-layer hand-off. float64 NaNs read back as ``None``."""
        array = np.ascontiguousarray(array)
        if array.ndim == 2:
            if not np.issubdtype(array.dtype, np.number):
                return cls.from_values(array.tolist())
            column = cls(VEC)
            column.data = array.astype(np.float64, copy=False)
            column.size = len(array)
            if none is None:
                # NaN-as-null contract (same as the f8 branch): a cell
                # is the whole row vector, so any NaN nulls the cell —
                # JSON has no NaN to ship the partial vector in
                nan = np.isnan(column.data).any(axis=1)
                if nan.any():
                    none = nan
            if none is not None and none.any():
                column.none = none.astype(bool).copy()
            if not column.data.flags.writeable:
                column._shared = True  # read-only source: copy-on-write
            return column
        if array.dtype == np.bool_:
            column = cls(BOOL)
        elif np.issubdtype(array.dtype, np.integer):
            column = cls(I8)
            array = array.astype(np.int64, copy=False)
        elif np.issubdtype(array.dtype, np.floating):
            column = cls(F8)
            array = array.astype(np.float64, copy=False)
            if none is None:
                nan = np.isnan(array)
                if nan.any():
                    none = nan
        elif array.dtype.kind == "U":
            return cls.from_strings(array.tolist())
        else:
            return cls.from_values(array.tolist())
        column.data = array
        column.size = len(array)
        if none is not None and none.any():
            column.none = none.astype(bool).copy()
            if column.kind == F8:
                column.data = column.data.copy()
                column.data[column.none] = np.nan
        if not column.data.flags.writeable:
            column._shared = True  # read-only source: copy-on-write
        return column

    @classmethod
    def from_strings(
        cls, values: list, none: Optional[np.ndarray] = None
    ) -> "Column":
        """All-string values (``none`` marks nulls) → Arrow layout."""
        column = cls(STR)
        column.size = len(values)
        if none is not None and none.any():
            column.none = none.astype(bool).copy()
            values = [
                "" if m else v for v, m in zip(values, column.none)
            ]
        column.data, column.offsets = _encode_strings(values)
        return column

    @classmethod
    def from_nul_joined(cls, buffer: bytes, count: int) -> "Column":
        """NUL-separated concatenation of ``count`` cells (the native CSV
        parser's bulk export, native/csv_loader.cpp) → Arrow layout with
        no intermediate Python strings."""
        raw = np.frombuffer(buffer, dtype=np.uint8)
        stops = np.flatnonzero(raw == 0)
        if len(stops) != count:
            # short buffer, or a cell containing a literal NUL — the
            # separator protocol can't represent it; caller falls back
            raise ValueError("NUL-joined buffer does not split into count cells")
        column = cls(STR)
        column.size = count
        keep = np.ones(len(raw), dtype=bool)
        keep[stops] = False
        # offsets into the NUL-stripped buffer: each stop shifts later
        # cells left by one
        offsets = np.empty(count + 1, dtype=np.int64)
        offsets[0] = 0
        offsets[1:] = stops - np.arange(count)
        column.data = raw[keep][: offsets[-1]].copy()
        column.offsets = offsets
        return column

    @classmethod
    def pads(cls, count: int) -> "Column":
        column = cls(EMPTY)
        column.size = count
        column.data = np.zeros(count, dtype=np.uint8)
        if count:
            column.miss = np.ones(count, dtype=bool)
        return column

    # --- geometry / flags -----------------------------------------------------
    def __len__(self) -> int:
        return self.size

    @property
    def has_missing(self) -> bool:
        return self.miss is not None and bool(self.miss[: self.size].any())

    def is_missing(self, i: int) -> bool:
        return self.miss is not None and bool(self.miss[i])

    def _absent_mask(self) -> Optional[np.ndarray]:
        if self.none is None and self.miss is None:
            return None
        if self.none is None:
            return self.miss[: self.size]
        if self.miss is None:
            return self.none[: self.size]
        return self.none[: self.size] | self.miss[: self.size]

    # --- copy-on-write --------------------------------------------------------
    def snapshot(self) -> "Column":
        """A consistent read view sharing buffers; both sides copy
        before their next in-place write. Appends by the live column
        never disturb the snapshot (they land beyond its ``size``).
        Must be called under the store lock."""
        clone = Column.__new__(Column)
        clone.kind = self.kind
        clone.size = self.size
        clone.data = self.data
        clone.offsets = self.offsets
        clone.none = self.none
        clone.miss = self.miss
        clone.intm = self.intm
        clone.edits = dict(self.edits) if self.edits else None
        clone.owner = self.owner
        # The clone READS the shared mapping but must never take the
        # append-into-file path — only one column may own the file tail.
        clone.spill = None
        clone._shared = True
        self._shared = True
        return clone

    def _own(self) -> None:
        """Copy shared buffers before an in-place mutation.

        ``np.array`` (not ``.copy()``) for mapped buffers: a memmap's
        ``.copy()`` preserves the subclass, which would leave a RAM
        column still claiming to be spilled."""
        if not self._shared:
            return
        if self.kind == OBJ:
            self.data = list(self.data)
        else:
            self.data = np.array(self.data)
        if self.offsets is not None:
            self.offsets = np.array(self.offsets)
        self.spill = None  # buffers are anonymous RAM again
        self.owner = None  # owned copies no longer pin a wire frame
        for slot in ("none", "miss", "intm"):
            mask = getattr(self, slot)
            if mask is not None:
                setattr(self, slot, mask.copy())
        self._shared = False

    # --- mask helpers ---------------------------------------------------------
    def _row_capacity(self) -> int:
        if self.kind == OBJ:
            return self.size
        if self.kind == STR:
            return max(len(self.offsets) - 1, self.size)
        return max(len(self.data), self.size)

    def _mask(self, slot: str) -> np.ndarray:
        mask = getattr(self, slot)
        capacity = self._row_capacity()
        if mask is None:
            mask = np.zeros(capacity, dtype=bool)
            setattr(self, slot, mask)
        elif len(mask) < capacity:
            grown = np.zeros(capacity, dtype=bool)
            grown[: len(mask)] = mask
            mask = grown
            setattr(self, slot, mask)
        return mask

    # --- appends (never copy shared buffers) ----------------------------------
    def _reserve(self, extra: int) -> None:
        """Grow ``data`` (non-str kinds) so ``size + extra`` fits."""
        need = self.size + extra
        if self.kind == OBJ:
            return
        if len(self.data) >= need:
            return
        capacity = max(need, 2 * len(self.data), 1024)
        if self.kind == VEC:
            grown = np.empty((capacity, self.data.shape[1]), dtype=np.float64)
        else:
            grown = np.empty(capacity, dtype=self.data.dtype)
        grown[: self.size] = self.data[: self.size]
        # NOTE: _shared stays set — masks/offsets may still be shared
        # with a snapshot; _own() decides per-buffer at mutation time.
        self.data = grown

    def _append_masks(self, other: "Column", offset: int) -> None:
        for slot in ("none", "miss"):
            theirs = getattr(other, slot)
            if theirs is not None and theirs[: other.size].any():
                mask = self._mask(slot)
                mask[offset : offset + other.size] = theirs[: other.size]
            elif getattr(self, slot) is not None:
                self._mask(slot)[offset : offset + other.size] = False

    def append_column(self, other: "Column") -> "Column":
        """Append ``other``'s cells; returns the (possibly re-kinded)
        column — callers must re-assign. The store's one append path."""
        if other.size == 0 and merge_kind(self.kind, other.kind) in (
            self.kind,
            EMPTY,
        ):
            # nothing to add and no kind change: return unchanged. This
            # also keeps zero-length slice-assignments away from
            # read-only zero-copy wire views (the paged read loop
            # appends the terminal empty chunk through here).
            return self
        if other.kind == EMPTY and self.kind not in (EMPTY, NUM):
            other = other._as_kind(self.kind, width=self._vec_width())
        merged = merge_kind(self.kind, other.kind)
        if (
            merged == VEC
            and self.kind == VEC
            and other.kind == VEC
            and self.data.shape[1] != other.data.shape[1]
        ):
            if other.size == 0:  # zero rows carry no width information
                return self
            if self.size == 0:  # adopt the first real width
                self.data = np.empty((0, other.data.shape[1]), np.float64)
            else:  # widths differ: vectors become ragged → boxed fallback
                merged = OBJ
        if merged != self.kind or (merged == NUM and other.kind != NUM):
            return self._append_promoted(other, merged)
        offset = self.size
        if self.is_spilled() and merged not in (OBJ, EMPTY):
            return self._append_spilled(other, merged)
        if merged == OBJ:
            if self._shared:
                self.data = list(self.data[: self.size])
                self._shared = False
            self.data.extend(other.tolist(pad_as_none=True))
            self.size += other.size
            if other.miss is not None and other.miss[: other.size].any():
                mask = self._mask("miss")
                mask[offset : offset + other.size] = other.miss[: other.size]
            return self
        if merged == STR:
            other = other._materialized()
            my_bytes = int(self.offsets[self.size])
            their_bytes = int(other.offsets[other.size])
            if their_bytes:
                # guarded: a chunk of all-empty/null strings carries
                # rows but ZERO bytes — the no-growth path would then
                # slice-assign zero length into a possibly read-only
                # zero-copy wire view, which numpy rejects
                if len(self.data) < my_bytes + their_bytes:
                    capacity = max(
                        my_bytes + their_bytes, 2 * len(self.data), 4096
                    )
                    grown = np.empty(capacity, dtype=np.uint8)
                    grown[:my_bytes] = self.data[:my_bytes]
                    self.data = grown
                self.data[my_bytes : my_bytes + their_bytes] = other.data[
                    :their_bytes
                ]
            if len(self.offsets) < self.size + other.size + 1:
                capacity = max(
                    self.size + other.size + 1, 2 * len(self.offsets)
                )
                grown = np.empty(capacity, dtype=np.int64)
                grown[: self.size + 1] = self.offsets[: self.size + 1]
                self.offsets = grown
            self.offsets[self.size + 1 : self.size + other.size + 1] = (
                other.offsets[1 : other.size + 1] + my_bytes
            )
            self.size += other.size
            self._append_masks(other, offset)
            return self
        if merged == EMPTY:
            self._reserve(other.size)
            self.size += other.size
            self._append_masks(other, offset)
            return self
        self._reserve(other.size)
        self.data[offset : offset + other.size] = other.data[: other.size]
        self.size += other.size
        self._append_masks(other, offset)
        if merged == NUM:
            intm = self._mask("intm")
            if other.intm is not None:
                intm[offset : offset + other.size] = other.intm[: other.size]
            else:
                intm[offset : offset + other.size] = False
        return self

    def _append_promoted(self, other: "Column", merged: str) -> "Column":
        """Kind changes: rebuild self at the merged kind, then append."""
        if merged == other.kind and self.kind == EMPTY:
            # adopt the incoming kind, keeping the pad prefix
            fresh = Column(other.kind if other.kind != EMPTY else EMPTY)
            width = other._vec_width()
            if other.kind == STR:
                fresh.data = np.empty(0, dtype=np.uint8)
                fresh.offsets = np.zeros(1, dtype=np.int64)
            elif other.kind == OBJ:
                fresh.data = []
            elif other.kind == VEC:
                fresh.data = np.empty((0, width), dtype=np.float64)
            else:
                fresh.data = np.empty(0, dtype=_DTYPES.get(other.kind, np.uint8))
            fresh = fresh.append_column(self._as_kind(other.kind, width=width))
            return fresh.append_column(other)
        if merged == NUM and self.kind in _NUMERIC_KINDS:
            promoted = self._as_kind(NUM)
            return promoted.append_column(other._as_kind(NUM))
        if merged == OBJ:
            promoted = self._as_kind(OBJ)
            return promoted.append_column(other)
        # e.g. empty incoming into typed self at same merged kind
        return self.append_column(other._as_kind(self.kind))

    def _vec_width(self) -> int:
        return self.data.shape[1] if self.kind == VEC else 0

    def _as_kind(self, kind: str, width: int = 0) -> "Column":
        if kind == self.kind:
            return self
        if kind == NUM and self.kind in (I8, F8, EMPTY):
            out = Column(NUM)
            out.size = self.size
            out.data = self.data[: self.size].astype(np.float64)
            out.none = None if self.none is None else self.none[: self.size].copy()
            out.miss = None if self.miss is None else self.miss[: self.size].copy()
            intm = np.zeros(self.size, dtype=bool)
            if self.kind == I8:
                intm[:] = True
                absent = out._absent_mask()
                if absent is not None:
                    intm[absent] = False
            out.intm = intm
            if out.none is not None:
                out.data[out.none[: self.size]] = np.nan
            return out
        if kind == OBJ:
            out = Column(OBJ)
            out.size = self.size
            out.data = self.tolist(pad_as_none=True)
            out.miss = (
                None if self.miss is None else self.miss[: self.size].copy()
            )
            return out
        if self.kind == EMPTY:
            out = Column(kind)
            if kind == STR:
                pads = [""] * self.size
                out.size = self.size
                out.data, out.offsets = _encode_strings(pads)
            elif kind == OBJ:
                out.size = self.size
                out.data = [None] * self.size
            elif kind == VEC:
                out.size = self.size
                out.data = np.zeros((self.size, width), dtype=np.float64)
            else:
                out.size = self.size
                out.data = np.zeros(self.size, dtype=_DTYPES[kind])
                if kind == NUM:
                    out.intm = np.zeros(self.size, dtype=bool)
            out.none = None if self.none is None else self.none[: self.size].copy()
            out.miss = None if self.miss is None else self.miss[: self.size].copy()
            return out
        raise TypeError(f"cannot view {self.kind} column as {kind}")

    def append_pads(self, count: int) -> "Column":
        return self.append_column(Column.pads(count))

    # --- point access ---------------------------------------------------------
    def get(self, i: int):
        """Python value at ``i`` (``MISSING`` for pads, ``None`` for
        nulls)."""
        if self.miss is not None and self.miss[i]:
            return MISSING
        if self.none is not None and self.none[i]:
            return None
        if self.edits is not None and i in self.edits:
            return self.edits[i]
        if self.kind == OBJ:
            return self.data[i]
        if self.kind == EMPTY:
            return MISSING
        if self.kind == STR:
            start, stop = int(self.offsets[i]), int(self.offsets[i + 1])
            return bytes(self.data[start:stop]).decode("utf-8")
        if self.kind == VEC:
            return self.data[i].tolist()
        value = self.data[i]
        if self.kind == NUM:
            return int(value) if self.intm is not None and self.intm[i] else float(value)
        if self.kind == F8 and np.isnan(value):
            return None
        return value.item()

    def set(self, i: int, value) -> "Column":
        """Point write; returns the (possibly re-kinded) column."""
        self._own()
        if isinstance(value, float) and value != value:
            value = None  # NaN behaves as null end to end (no JSON NaN)
        kind, _, _ = _classify((value,))
        if value is None or value is MISSING:
            slot = "none" if value is None else "miss"
            self._mask(slot)[i] = True
            other = "miss" if value is None else "none"
            if getattr(self, other) is not None:
                self._mask(other)[i] = False
            if self.kind == F8:
                self.data[i] = np.nan
            if self.edits is not None:
                self.edits.pop(i, None)
            return self
        merged = merge_kind(self.kind, kind)
        if merged != self.kind:
            if merged == NUM and self.kind in _NUMERIC_KINDS:
                promoted = self._as_kind(NUM)
                return promoted.set(i, value)
            if self.kind == EMPTY:
                promoted = self._as_kind(kind)
                return promoted.set(i, value)
            promoted = self._as_kind(OBJ)
            return promoted.set(i, value)
        if self.none is not None:
            self.none[i] = False
        if self.miss is not None:
            self.miss[i] = False
        if self.kind == OBJ:
            self.data[i] = value
        elif self.kind == STR:
            if self.edits is None:
                self.edits = {}
            self.edits[i] = value
            if len(self.edits) > max(1024, self.size // 8):
                rebuilt = Column.from_values(self.tolist(pad_as_none=False))
                rebuilt.miss = self.miss
                return rebuilt
        else:
            self.data[i] = value
            if self.kind == NUM:
                self._mask("intm")[i] = type(value) is not float and not isinstance(
                    value, np.floating
                )
        return self

    # --- bulk reads -----------------------------------------------------------
    def _materialized(self) -> "Column":
        """str kind with edits → a fresh edit-free Arrow column."""
        if self.kind != STR or not self.edits:
            return self
        values = self._decode_all()
        for i, value in self.edits.items():
            values[i] = value
        none = self.none[: self.size] if self.none is not None else None
        fresh = Column.from_strings(values, none)
        fresh.miss = self.miss[: self.size].copy() if self.miss is not None else None
        return fresh

    # --- out-of-core spill ----------------------------------------------------
    def is_spilled(self) -> bool:
        # both conditions: snapshots/slices share the mapping (memmap
        # instance) without owning the file (spill is None), and an
        # _own() copy drops both
        return self.spill is not None and isinstance(self.data, np.memmap)

    def resident_nbytes(self) -> int:
        """Anonymous-RAM bytes held by this column's buffers — memmapped
        payloads excluded (their pages are file-backed and evictable).
        The store's spill policy budgets against this, not nbytes()."""
        if self.kind == OBJ:
            return self.size * 64  # boxed estimate, never spillable
        total = 0
        if not isinstance(self.data, np.memmap):
            total += self.data.nbytes
        if self.offsets is not None and not isinstance(
            self.offsets, np.memmap
        ):
            total += self.offsets.nbytes
        for slot in ("none", "miss", "intm"):
            mask = getattr(self, slot)
            if mask is not None:
                total += mask.nbytes
        return total

    def advise_cold(self) -> None:
        """Drop this column's RESIDENT mapped pages (madvise DONTNEED on
        the read-only shared mapping): the data stays in the page cache,
        so a later read refaults cheaply, but the pages stop counting
        against the process's RSS — keeping a spilled store's footprint
        near the LO_SPILL_BYTES budget even while scans page through
        tens of GB."""
        import mmap as mmap_module

        for buffer in (self.data, self.offsets):
            if isinstance(buffer, np.memmap):
                try:
                    buffer._mmap.madvise(mmap_module.MADV_DONTNEED)
                except (AttributeError, OSError, ValueError):
                    pass  # platform without madvise: purely advisory

    def _spill_paths(self) -> tuple[str, str]:
        base = os.path.join(self.spill["dir"], self.spill["prefix"])
        return base + ".data", base + ".offsets"

    def spill_to(self, directory: str, prefix: str) -> int:
        """Move the live payload into files under ``directory`` and
        remap it read-only (``np.memmap``): stored bytes leave anonymous
        RAM and ride the page cache instead — the store's disk-ownership
        story (the reference leans on Mongo's data volumes for this,
        docker-compose.yml:335-340). Returns RAM bytes released; 0 when
        not spillable (obj/empty/zero-size or already spilled).

        Afterwards: bulk appends stream straight to the backing file
        (:meth:`_append_spilled`) — ingestion never pulls the column
        back; point mutations copy-on-write back into RAM (``_own``),
        leaving the stale file for collection drop to reclaim."""
        if self.kind in (OBJ, EMPTY) or self.size == 0 or self.is_spilled():
            return 0
        folded = self._materialized()  # str edits overlay → flat layout
        if folded is not self:
            self.data, self.offsets = folded.data, folded.offsets
            self.none, self.miss = folded.none, folded.miss
            self.edits = None
            self._shared = False
        os.makedirs(directory, exist_ok=True)
        data_path = os.path.join(directory, prefix + ".data")
        offsets_path = os.path.join(directory, prefix + ".offsets")
        live = int(self.offsets[self.size]) if self.kind == STR else self.size
        payload = np.ascontiguousarray(self.data[:live])
        released = payload.nbytes
        # ALL file writes before any state change: a mid-spill OSError
        # (disk full) must leave the column untouched — only orphan
        # partial files, reclaimed with the collection/process
        payload.tofile(data_path)
        live_offsets = None
        if self.kind == STR:
            live_offsets = np.ascontiguousarray(self.offsets[: self.size + 1])
            released += live_offsets.nbytes
            live_offsets.tofile(offsets_path)
        self.data = np.memmap(
            data_path, dtype=payload.dtype, mode="r", shape=payload.shape
        )
        if live_offsets is not None:
            self.offsets = np.memmap(
                offsets_path, dtype=np.int64, mode="r", shape=(self.size + 1,)
            )
        self.spill = {"dir": directory, "prefix": prefix}
        # future in-place mutations must copy out of the read-only map
        self._shared = True
        return released

    def _unspill(self) -> None:
        """Materialize the payload back into anonymous RAM (a failed
        file append); the stale spill files are reclaimed at drop."""
        self.data = np.array(self.data)
        if self.offsets is not None:
            self.offsets = np.array(self.offsets)
        self.spill = None

    def _append_spilled(self, other: "Column", merged: str) -> "Column":
        """Append to a spilled column by growing its backing file and
        remapping — bulk ingestion keeps streaming to disk instead of
        materializing the column back into RAM. Snapshot isolation
        holds: an existing snapshot's memmap covers only its own prefix
        of the (append-only) file. Failure-safe: a partial file write
        (disk full) truncates back to the previous length and the
        append retries through the in-RAM path — the backing file is
        never left with an orphan tail that would shift later records.
        """
        offset = self.size
        other = other._materialized()
        new_size = self.size + other.size
        if other.size == 0:
            return self
        data_path, offsets_path = self._spill_paths()
        prev_data_bytes = os.path.getsize(data_path)
        prev_offsets_bytes = (
            os.path.getsize(offsets_path) if self.kind == STR else 0
        )
        try:
            if merged == STR:
                my_bytes = int(self.offsets[self.size])
                their_bytes = int(other.offsets[other.size])
                with open(data_path, "ab") as handle:
                    np.ascontiguousarray(other.data[:their_bytes]).tofile(
                        handle
                    )
                shifted = np.ascontiguousarray(
                    other.offsets[1 : other.size + 1] + my_bytes,
                    dtype=np.int64,
                )
                with open(offsets_path, "ab") as handle:
                    shifted.tofile(handle)
                self.data = np.memmap(
                    data_path,
                    dtype=np.uint8,
                    mode="r",
                    shape=(my_bytes + their_bytes,),
                )
                self.offsets = np.memmap(
                    offsets_path,
                    dtype=np.int64,
                    mode="r",
                    shape=(new_size + 1,),
                )
            else:
                dtype = self.data.dtype
                payload = np.ascontiguousarray(
                    other.data[: other.size], dtype=dtype
                )
                with open(data_path, "ab") as handle:
                    payload.tofile(handle)
                shape = (
                    (new_size, self.data.shape[1])
                    if self.kind == VEC
                    else (new_size,)
                )
                self.data = np.memmap(
                    data_path, dtype=dtype, mode="r", shape=shape
                )
        except OSError:
            for path, prev in (
                (data_path, prev_data_bytes),
                (offsets_path, prev_offsets_bytes),
            ):
                try:
                    with open(path, "r+b") as handle:
                        handle.truncate(prev)
                except OSError:
                    pass
            self._unspill()
            return self.append_column(other)
        self.size = new_size
        self._append_masks(other, offset)
        if merged == NUM:
            intm = self._mask("intm")
            if other.intm is not None:
                intm[offset:new_size] = other.intm[: other.size]
            else:
                intm[offset:new_size] = False
        return self

    def _decode_all(self) -> list:
        nbytes = int(self.offsets[self.size])
        raw = bytes(self.data[:nbytes])
        text = raw.decode("utf-8")
        offsets = self.offsets
        if len(text) == nbytes:  # ASCII: byte offsets index the str directly
            return [
                text[offsets[i] : offsets[i + 1]] for i in range(self.size)
            ]
        return [
            raw[offsets[i] : offsets[i + 1]].decode("utf-8")
            for i in range(self.size)
        ]

    def tolist(
        self, start: int = 0, stop: Optional[int] = None, pad_as_none: bool = True
    ) -> list:
        """Python values in ``[start, stop)``; pads become ``None``
        (default) or ``MISSING``."""
        stop = self.size if stop is None else min(stop, self.size)
        n = stop - start
        if n <= 0:
            return []
        if self.kind == OBJ:
            out = list(self.data[start:stop])
        elif self.kind == EMPTY:
            out = [None] * n
        elif self.kind == STR:
            if start == 0 and stop == self.size:
                out = self._decode_all()
            else:
                base = int(self.offsets[start])
                nbytes = int(self.offsets[stop]) - base
                raw = bytes(self.data[base : base + nbytes])
                text = raw.decode("utf-8")
                offsets = self.offsets
                if len(text) == nbytes:
                    out = [
                        text[offsets[i] - base : offsets[i + 1] - base]
                        for i in range(start, stop)
                    ]
                else:
                    out = [
                        raw[offsets[i] - base : offsets[i + 1] - base].decode(
                            "utf-8"
                        )
                        for i in range(start, stop)
                    ]
            if self.edits:
                for i, value in self.edits.items():
                    if start <= i < stop:
                        out[i - start] = value
        elif self.kind == NUM:
            floats = self.data[start:stop].tolist()
            if self.intm is None:
                out = floats
            else:
                ints = self.intm[start:stop]
                out = [
                    int(v) if ints[i] else v for i, v in enumerate(floats)
                ]
        elif self.kind == F8:
            out = self.data[start:stop].tolist()
            if self.none is None:
                nan = np.isnan(self.data[start:stop])
                if nan.any():
                    for i in np.flatnonzero(nan):
                        out[i] = None
        else:
            out = self.data[start:stop].tolist()
        if self.none is not None:
            for i in np.flatnonzero(self.none[start:stop]):
                out[i] = None
        if self.miss is not None:
            pad = None if pad_as_none else MISSING
            for i in np.flatnonzero(self.miss[start:stop]):
                out[i] = pad
        return out

    def slice(self, start: int, stop: int) -> "Column":
        """Shared-buffer view of ``[start, stop)`` — O(1) for numeric
        kinds. Used by the wire read path."""
        stop = min(stop, self.size)
        start = min(start, stop)
        if self.kind == STR:
            source = self._materialized()
            out = Column(STR)
            out.size = stop - start
            base = int(source.offsets[start])
            out.data = source.data[base : int(source.offsets[stop])]
            # base == 0 (full-prefix reads — the projection/cast scans):
            # keep the VIEW; subtracting would copy the whole offsets
            # buffer (800 MB at 100M rows, per column, per read)
            out.offsets = (
                source.offsets[start : stop + 1]
                if base == 0
                else source.offsets[start : stop + 1] - base
            )
        elif self.kind == OBJ:
            out = Column(OBJ)
            out.size = stop - start
            out.data = self.data[start:stop]
        else:
            out = Column(self.kind)
            out.size = stop - start
            out.data = self.data[start:stop]
        for slot in ("none", "miss", "intm"):
            mask = getattr(self, slot)
            if mask is not None:
                setattr(out, slot, mask[start:stop])
        out.owner = self.owner
        out._shared = True
        self._shared = True
        return out

    def to_float64(self, start: int = 0, stop: Optional[int] = None) -> np.ndarray:
        """float64 view (nulls/pads → NaN) — the design-matrix hand-off.
        Raises TypeError for non-numeric kinds. Mask-free f8/num
        columns hand back a READ-ONLY view of the buffer itself (zero
        copy on the store→matrix path; the copy only happens when NaN
        masking must write) and flip the column copy-on-write — so a
        later column mutation can never rewrite an already-assembled
        matrix, and a matrix writer can never corrupt the store (the
        isolation the old always-copy gave, kept without the copy)."""
        stop = self.size if stop is None else min(stop, self.size)
        absent = self._absent_mask()
        if self.kind in (F8, NUM):
            if absent is None:
                view = self.data[start:stop].astype(np.float64, copy=False)
                if view.flags.writeable:
                    view = view[:]  # fresh view object; base untouched
                    view.flags.writeable = False
                self._shared = True  # next in-place write copies first
                return view
            out = self.data[start:stop].astype(np.float64, copy=True)
        elif self.kind == I8:
            out = self.data[start:stop].astype(np.float64)
        elif self.kind == EMPTY:
            return np.full(stop - start, np.nan)
        else:
            raise TypeError(f"{self.kind} column is not numeric")
        if absent is not None:
            out[absent[start:stop]] = np.nan
        return out

    def to_object(self, start: int = 0, stop: Optional[int] = None) -> np.ndarray:
        """Object-array view with ``None`` for nulls AND pads — the
        ColumnTable string-column hand-off."""
        return _object_array(self.tolist(start, stop, pad_as_none=True))

    # --- histogram ($group) fast path -----------------------------------------
    def unique_counts(self) -> list[dict]:
        """``[{_id, count}]`` groups over the live prefix — np.unique for
        typed kinds, tagged Counter for obj (bool-vs-1 parity with the
        row path, store._group_count)."""
        absent = self._absent_mask()
        null_count = int(absent.sum()) if absent is not None else 0
        out: list[dict] = []
        n = self.size
        if self.kind == OBJ:
            counts: dict = {}
            first: dict = {}
            for value in self.data[:n]:
                key = _group_key(value)
                counts[key] = counts.get(key, 0) + 1
                if key not in first:
                    first[key] = value
            # nulls already appear as None entries in data; pads were
            # stored as None too — counts are consistent already
            return [
                {"_id": first[key], "count": count}
                for key, count in counts.items()
            ]
        if self.kind == EMPTY:
            return [{"_id": None, "count": n}] if n else []
        if self.kind == VEC:
            data = self.data[:n]
            if absent is not None:
                data = data[~absent]
            nan = np.isnan(data).any(axis=1)
            if nan.any():  # NaN cells group as null (f8 parity)
                null_count += int(nan.sum())
                data = data[~nan]
            values, counts = np.unique(data, axis=0, return_counts=True)
            out = [
                {"_id": row.tolist(), "count": int(count)}
                for row, count in zip(values, counts)
            ]
            if null_count:
                out.append({"_id": None, "count": null_count})
            return out
        if self.kind == STR:
            source = self._materialized()
            values = source._decode_all()
            if absent is not None:
                keep = ~absent
                values = [v for v, k in zip(values, keep) if k]
            counts = Counter(values)
            out = [
                {"_id": value, "count": count}
                for value, count in counts.items()
            ]
        else:
            data = self.data[:n]
            if absent is not None:
                data = data[~absent]
            if self.kind == NUM:
                intm = (
                    self.intm[:n]
                    if self.intm is not None
                    else np.zeros(n, dtype=bool)
                )
                if absent is not None:
                    intm = intm[~absent]
                # ONE group per numeric value (2 and 2.0 merge, exactly
                # like the dict/Counter row path and Mongo's $group);
                # the key's int/float type follows the value's FIRST
                # occurrence, matching Counter's first-seen-key rule
                values, first, counts = np.unique(
                    data, return_index=True, return_counts=True
                )
                for value, index, count in zip(values, first, counts):
                    key = int(value) if intm[index] else float(value)
                    out.append({"_id": key, "count": int(count)})
            else:
                if self.kind == F8:
                    nan = np.isnan(data)
                    nan_count = int(nan.sum())
                    if nan_count:
                        data = data[~nan]
                        null_count += nan_count
                values, counts = np.unique(data, return_counts=True)
                out = [
                    {"_id": value.item(), "count": int(count)}
                    for value, count in zip(values, counts)
                ]
        if null_count:
            out.append({"_id": None, "count": null_count})
        return out

    # --- serialization --------------------------------------------------------
    def wire_parts(self) -> tuple[dict, list]:
        """(meta, buffers) for the binary HTTP frame (core/wire.py).
        Buffer order: data, offsets, none, miss, intm — present iff the
        corresponding meta flag says so. Buffers are handed over as
        numpy views of the live payload (``ascontiguousarray`` on an
        already-contiguous slice is free) — the frame assembly writes
        them into the output exactly once, with no intermediate
        ``tobytes`` copies (LO106)."""
        source = self._materialized()
        n = source.size
        meta: dict = {"kind": source.kind, "n": n}
        buffers: list = []
        if source.kind == OBJ:
            meta["values"] = source.tolist(pad_as_none=True)
        elif source.kind == STR:
            nbytes = int(source.offsets[n])
            buffers.append(np.ascontiguousarray(source.data[:nbytes]))
            buffers.append(np.ascontiguousarray(source.offsets[: n + 1]))
            meta["data"] = True
            meta["offsets"] = True
        elif source.kind == VEC:
            meta["w"] = source.data.shape[1]
            buffers.append(np.ascontiguousarray(source.data[:n]))
            meta["data"] = True
        elif source.kind != EMPTY:
            buffers.append(np.ascontiguousarray(source.data[:n]))
            meta["data"] = True
        for slot in ("none", "miss", "intm"):
            mask = getattr(source, slot)
            # intm ships even when all-False: a NUM column without its
            # int mask would deserialize structurally incomplete
            if mask is not None and (
                slot == "intm" or mask[:n].any()
            ):
                buffers.append(_pack(mask, n))
                meta[slot] = True
        return meta, buffers

    @classmethod
    def from_wire_parts(
        cls,
        meta: dict,
        buffers: list,
        copy: bool = True,
        owner: Optional["FrameOwner"] = None,
    ) -> "Column":
        """Rebuild a column from its wire buffers.

        ``copy=True`` (v1 frames, WAL base64 records) produces a column
        that OWNS its buffers. ``copy=False`` (aligned v2 frames,
        core/wire.py) produces read-only numpy *views* over the frame's
        one backing buffer — zero per-column copies; ``owner`` is the
        frame's :class:`FrameOwner` token, recorded on the column so a
        pinning consumer (the device cache) holds exactly one
        allocation. Zero-copy columns are marked ``_shared``: any
        in-place mutation copies first (copy-on-write), so a caller
        writing through a view can never corrupt the pinned frame."""
        kind = meta["kind"]
        n = meta["n"]
        column = cls(kind)
        column.size = n
        index = 0

        def take():
            nonlocal index
            raw = buffers[index]
            index += 1
            return raw

        def typed(raw, dtype):
            if not copy:
                # raw is an aligned uint8 view (core/wire.py): a dtype
                # reinterpretation of it is the zero-copy hand-off
                return raw.view(dtype)
            # v1/WAL decode contract: the column must own its buffers
            # (the source bytes are transient) — this copy is that
            # ownership, not a removable redundancy
            # lo: allow[LO106]
            return np.frombuffer(raw, dtype=dtype).copy()

        if kind == OBJ:
            column.data = list(meta["values"])
        elif kind == STR:
            column.data = typed(take(), np.uint8)
            column.offsets = typed(take(), np.int64)
        elif kind == VEC:
            width = int(meta["w"])
            # ALWAYS consume the data buffer — wire_parts emits one for
            # width-0 vec columns too (empty), and skipping it would
            # shift every following mask buffer onto the wrong slot
            raw = take()
            if width:
                # reshape the flat VIEW first, then (only under v1)
                # copy once — never allocate flat and reshape after
                flat = (
                    raw.view(np.float64)
                    if not copy
                    else np.frombuffer(raw, dtype=np.float64)
                )
                shaped = flat.reshape(-1, width)
                column.data = shaped if not copy else shaped.copy()
            else:
                column.data = np.empty((n, 0), dtype=np.float64)
        elif kind == EMPTY:
            column.data = np.zeros(n, dtype=np.uint8)
        else:
            column.data = typed(take(), _DTYPES[kind])
        for slot in ("none", "miss", "intm"):
            if meta.get(slot):
                setattr(column, slot, _unpack(take(), n))
        if kind == NUM and column.intm is None:
            # defensive: a NUM column always carries its int mask
            column.intm = np.zeros(n, dtype=bool)
        if not copy:
            column.owner = owner
            column._shared = True  # copy-on-write before any mutation
        return column

    def to_json_record(self) -> dict:
        """Base64 form for WAL lines (crash recovery + replication)."""
        meta, buffers = self.wire_parts()
        record = {"k": meta["kind"], "n": meta["n"]}
        if "values" in meta:
            record["v"] = meta["values"]
        if "w" in meta:
            record["w"] = meta["w"]
        index = 0
        for key, flag in (
            ("d", "data"),
            ("o", "offsets"),
            ("nm", "none"),
            ("mm", "miss"),
            ("im", "intm"),
        ):
            if meta.get(flag):
                record[key] = _b64(buffers[index])
                index += 1
        return record

    @classmethod
    def from_json_record(cls, record: dict) -> "Column":
        meta = {"kind": record["k"], "n": record["n"]}
        if "v" in record:
            meta["values"] = record["v"]
        if "w" in record:
            meta["w"] = record["w"]
        buffers: list[bytes] = []
        for key, flag in (
            ("d", "data"),
            ("o", "offsets"),
            ("nm", "none"),
            ("mm", "miss"),
            ("im", "intm"),
        ):
            if record.get(key) is not None:
                meta[flag] = True
                buffers.append(_unb64(record[key]))
        return cls.from_wire_parts(meta, buffers)

    def nbytes(self) -> int:
        """Approximate live payload bytes (capacity excluded)."""
        if self.kind == OBJ:
            return self.size * 64  # boxed estimate
        if self.kind == STR:
            return int(self.offsets[self.size]) + 8 * (self.size + 1)
        return int(self.data[: self.size].nbytes)
