"""Shared-memory ring transport for the binary columnar wire.

When the store server and its client are co-located (the runner's
single-process/LO_STACK topology hosts all seven services and the store
in one process tree — the common case), the HTTP body is pure overhead:
the frame is serialized into a socket, copied through the kernel, and
reassembled by the client just to land in the same machine's RAM. The
ring removes that hop:

- the **client** owns one ``multiprocessing.shared_memory`` segment of
  ``LO_SHM_BYTES`` (0 disables; ``1e9`` notation accepted like
  ``LO_DEVCACHE_BYTES``) and advertises its name + size on every binary
  read request (``X-Lo-Shm-Segment`` / ``X-Lo-Shm-Bytes``);
- the **server** attaches the segment (cached per name), writes the
  encoded frame into the next ring slot, and answers with three tiny
  headers (offset / length / generation) instead of the frame body;
- the client copies the frame out of the slot into ONE aligned private
  buffer (a single memcpy at memory bandwidth — no sockets, no
  chunked-transfer reassembly, no inflate) and decodes it with the v2
  zero-copy path (core/wire.py): per-column decode work is zero.

The ring is **lease-free**: slots carry a monotonically increasing
generation in a 64-byte header, the client re-reads the header after
its copy, and a mismatch (the server lapped the ring while the client
was copying — only possible when outstanding frames exceed the segment)
surfaces as :class:`ShmTornError`, upon which the caller simply
re-fetches that chunk over the plain HTTP body. Falling back is also
what happens transparently when the server cannot attach the segment
(different machine or container, segment unlinked, feature disabled
server-side): it just answers with the body, and the client never
notices beyond the bytes taking the slower road.
"""

from __future__ import annotations

import os
import re
import struct
import threading
import weakref
from collections import OrderedDict
from typing import Optional

import numpy as np

from learningorchestra_tpu.core.wire import ALIGN as _ALIGN

SEGMENT_HEADER = "X-Lo-Shm-Segment"
BYTES_HEADER = "X-Lo-Shm-Bytes"
OFFSET_HEADER = "X-Lo-Shm-Offset"
LENGTH_HEADER = "X-Lo-Shm-Length"
GENERATION_HEADER = "X-Lo-Shm-Generation"

# Slot header: u32 magic, u32 pad, u64 generation, u64 payload length;
# padded to wire.ALIGN bytes so the payload starts frame-aligned (the
# mmap base is page-aligned and slot offsets are ALIGN multiples) —
# which is what lets the v2 decode treat a slot copy as an aligned
# frame. The header size is DERIVED from the wire alignment, not an
# independent constant: raising ALIGN (wider SIMD) automatically grows
# the header pad, and slot-offset rounding below uses ALIGN directly.
SLOT_MAGIC = 0x4C4F5348  # "LOSH"
_SLOT = struct.Struct("<IIQQ")
SLOT_HEADER_BYTES = _ALIGN
assert SLOT_HEADER_BYTES >= _SLOT.size


class ShmTornError(RuntimeError):
    """The server lapped the ring slot while the client was copying it
    out — re-fetch this chunk over the HTTP body."""


# A segment name is a flat shm identifier (shared_memory mints psm_*).
# The server maps it under /dev/shm, so anything path-like — separators,
# dot-relatives, empties — is rejected before any filesystem call: a
# request header must never be able to point the mmap at an arbitrary
# server-writable file.
_NAME_RE = re.compile(r"[A-Za-z0-9][A-Za-z0-9_.-]*\Z")


def valid_segment_name(name: str) -> bool:
    return bool(
        name
        and ".." not in name
        and _NAME_RE.fullmatch(name) is not None
    )


def shm_bytes() -> int:
    """``LO_SHM_BYTES`` validated: ring segment size in bytes, ``1e9``
    notation accepted (like ``LO_DEVCACHE_BYTES``); ``0`` (the default)
    disables the shared-memory transport entirely."""
    # lo: allow[LO305] this IS the validated accessor preflight calls
    raw = os.environ.get("LO_SHM_BYTES", "").strip()
    if not raw:
        return 0
    try:
        value = int(float(raw))
    except ValueError:
        raise ValueError(
            f"LO_SHM_BYTES must be a number of bytes, got {raw!r}"
        ) from None
    if value < 0:
        raise ValueError(f"LO_SHM_BYTES must be >= 0, got {value}")
    return value


class _Attachment:
    """A server-side mapping of a client-owned segment.

    On Linux this maps ``/dev/shm/<name>`` directly — deliberately NOT
    ``multiprocessing.shared_memory`` attach, which on 3.10 registers
    the segment with the attaching process's resource tracker
    (bpo-38119) and would try to unlink the client's segment at server
    exit. Elsewhere it falls back to a SharedMemory attach."""

    __slots__ = ("buf", "size", "_mmap", "_shm")

    def __init__(self, name: str):
        import mmap

        if not valid_segment_name(name):  # defense in depth: no paths
            raise ValueError(f"invalid shm segment name {name!r}")
        self._shm = None
        path = os.path.join("/dev/shm", name)
        if os.path.exists(path):
            fd = os.open(path, os.O_RDWR)
            try:
                self.size = os.fstat(fd).st_size
                self._mmap = mmap.mmap(fd, self.size)
            finally:
                os.close(fd)
            self.buf = memoryview(self._mmap)
            return
        from multiprocessing import shared_memory

        self._mmap = None
        self._shm = shared_memory.SharedMemory(name=name)
        self.size = self._shm.size
        self.buf = self._shm.buf

    def close(self) -> None:
        try:
            if self._mmap is not None:
                self.buf.release()
                self._mmap.close()
            elif self._shm is not None:
                self._shm.close()
        except Exception:  # noqa: BLE001 — best-effort unmap
            pass


def _release(shm) -> None:
    try:
        shm.close()
        shm.unlink()
    except Exception:  # noqa: BLE001 — already gone is fine
        pass


class ClientRing:
    """The client-owned segment: created once per RemoteStore, read by
    slot coordinates the server's response names, unlinked at close /
    garbage collection (``weakref.finalize``)."""

    def __init__(self, nbytes: int):
        from multiprocessing import shared_memory

        self.shm = shared_memory.SharedMemory(create=True, size=nbytes)
        self.name = self.shm.name.lstrip("/")
        self.nbytes = nbytes
        self.frames = 0
        self.bytes = 0
        self._lock = threading.Lock()
        self._finalizer = weakref.finalize(self, _release, self.shm)

    def read(self, offset: int, length: int, generation: int) -> np.ndarray:
        """Copy one frame out of the ring into an aligned private
        buffer, verifying the slot header before AND after the copy —
        a generation mismatch means the server lapped the ring."""
        from learningorchestra_tpu.core.wire import aligned_frame

        view = self.shm.buf

        def check() -> None:
            magic, _, gen, nbytes = _SLOT.unpack_from(view, offset)
            if magic != SLOT_MAGIC or gen != generation or nbytes != length:
                raise ShmTornError(
                    f"ring slot at {offset} overwritten (generation "
                    f"{gen} != {generation})"
                )

        start = offset + SLOT_HEADER_BYTES
        if start + length > self.nbytes:
            raise ShmTornError("ring slot exceeds the segment")
        check()
        frame = aligned_frame(view[start : start + length])
        check()
        with self._lock:
            self.frames += 1
            self.bytes += length
        return frame

    def stats(self) -> dict:
        with self._lock:
            return {"frames": self.frames, "bytes": self.bytes}

    def close(self) -> None:
        self._finalizer()


class _Segment:
    __slots__ = (
        "attachment", "nbytes", "lock", "offset", "generation", "closed"
    )

    def __init__(self, attachment: _Attachment, nbytes: int):
        self.attachment = attachment
        self.nbytes = nbytes
        self.lock = threading.Lock()
        self.offset = 0
        self.generation = 0
        self.closed = False


def _close_segment(segment: _Segment) -> None:
    """Release an evicted segment under ITS lock: a concurrent
    ``place`` holding the lock finishes its write first, and any later
    ``place`` sees ``closed`` and falls back to the HTTP body instead
    of writing into a released mapping."""
    with segment.lock:
        segment.closed = True
        segment.attachment.close()


class ServerRings:
    """Server-side attach cache + per-segment rolling slot allocator.

    One instance per store app. Attachments are LRU-bounded (a client
    churn of segments must not pin mmaps forever; access moves a
    segment to the back, the true-oldest evicts); a failed attach is
    negative-cached briefly by simply answering None — the route then
    falls back to the HTTP body."""

    MAX_SEGMENTS = 8

    def __init__(self):
        self._lock = threading.Lock()
        self._segments: "OrderedDict[str, _Segment]" = OrderedDict()

    def _segment(self, name: str, nbytes: int) -> Optional[_Segment]:
        with self._lock:
            segment = self._segments.get(name)
            if segment is not None:
                self._segments.move_to_end(name)  # LRU touch
                return segment
        try:
            attachment = _Attachment(name)
        except Exception:  # noqa: BLE001 — not co-located / gone: fallback
            return None
        if attachment.size < nbytes:
            # the client lied about (or resized) its segment — refuse
            attachment.close()
            return None
        segment = _Segment(attachment, nbytes)
        evicted: list[_Segment] = []
        with self._lock:
            if name in self._segments:
                self._segments.move_to_end(name)
                existing = self._segments[name]
            else:
                existing = None
                while len(self._segments) >= self.MAX_SEGMENTS:
                    _, oldest = self._segments.popitem(last=False)
                    evicted.append(oldest)
                self._segments[name] = segment
        # closes run OUTSIDE the cache lock (each takes its segment's
        # own lock; no handler path holds a segment lock while taking
        # the cache lock, so the order cannot invert)
        if existing is not None:
            attachment.close()
        for oldest in evicted:
            _close_segment(oldest)
        return existing if existing is not None else segment

    def place(
        self, name: str, nbytes: int, frame: bytes
    ) -> Optional[tuple[int, int, int]]:
        """Write ``frame`` into the next ring slot of segment ``name``;
        returns ``(offset, length, generation)`` or None when the frame
        cannot ride the ring (attach failed or evicted mid-flight,
        frame too large, path-shaped segment name)."""
        need = SLOT_HEADER_BYTES + len(frame)
        if nbytes <= 0 or need > nbytes or not valid_segment_name(name):
            return None
        segment = self._segment(name, nbytes)
        if segment is None:
            return None
        with segment.lock:
            if segment.closed:  # evicted between lookup and write
                return None
            offset = segment.offset
            if offset + need > segment.nbytes:
                offset = 0  # wrap: the remainder can't hold the slot
            segment.generation += 1
            generation = segment.generation
            view = segment.attachment.buf
            _SLOT.pack_into(
                view, offset, SLOT_MAGIC, 0, generation, len(frame)
            )
            view[
                offset + SLOT_HEADER_BYTES : offset
                + SLOT_HEADER_BYTES
                + len(frame)
            ] = frame
            # advance to the next ALIGN boundary past this slot (the
            # alignment the v2 zero-copy decode relies on)
            segment.offset = (
                (offset + need + _ALIGN - 1) // _ALIGN * _ALIGN
            )
        return offset, len(frame), generation

    def close(self) -> None:
        with self._lock:
            segments = list(self._segments.values())
            self._segments.clear()
        for segment in segments:
            _close_segment(segment)
