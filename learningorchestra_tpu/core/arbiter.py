"""Quorum arbiter: the vote-only replica-set member.

The reference's Mongo replica set deploys a dedicated arbiter container
precisely so a two-data-node set has a majority to elect with
(reference docker-compose.yml:49-91: ``mongodbarbiter`` joins the set
with ``--replSet`` and holds a vote but no data). This module is that
role for the framework's store pair: a tiny stdlib+werkzeug HTTP
process that holds ONE vote and no data, so

- a follower whose primary vanished can assemble a 2-of-3 majority
  (itself + the arbiter) and promote *with quorum* instead of on a
  blind timer, and
- the partitioned minority side can *see* that it lost quorum and
  suspend writes (503 + Retry-After) instead of opening a second
  primary.

Vote semantics (the slice of Raft's election rules this topology
needs, shared with the store servers via :func:`grant_vote`):

- a candidate campaigns for an explicit ``term``;
- a voter grants at most one vote per term (first candidate wins the
  term; re-asking with the same term and candidate is idempotent —
  retried requests must not burn the vote);
- stale candidacies (``term`` ≤ the highest term the voter has
  observed) are denied.

Vote state is in-memory: an arbiter restart inside one election window
could in principle double-vote, the same trade Mongo documents for
priority-0 members — the window is seconds and the term fence
(store_service fencing probe) still converges on one writer.

Run it: ``python -m learningorchestra_tpu.core.arbiter`` (knobs:
``LO_HOST``, ``LO_ARBITER_PORT``). Point the store servers at it with
``LO_ARBITERS=http://host:port``.
"""

from __future__ import annotations

import os
import secrets
import threading
from typing import Optional

from learningorchestra_tpu.utils.web import ServerThread, WebApp

DEFAULT_ARBITER_PORT = 27029


def grant_vote(state: dict, term: int, candidate: str) -> dict:
    """Apply one vote request against ``state`` (mutated in place;
    caller holds the node's lock). ``state`` carries ``term`` (highest
    observed), ``voted_term``/``voted_for`` (the one-vote-per-term
    ledger). Returns the wire payload."""
    voted_term = state.get("voted_term", 0)
    voted_for = state.get("voted_for")
    if term == voted_term:
        # idempotent re-ask FIRST: a candidate whose grant response was
        # lost to a timeout retries the identical request, and the
        # arbiter's observed term has meanwhile been bumped to the
        # granted term — the staleness check below must not burn the
        # vote the retry is trying to read back
        granted = candidate == voted_for
    elif term <= state.get("term", 0) or term < voted_term:
        granted = False
    else:
        granted = True
        state["voted_term"] = term
        state["voted_for"] = candidate
    return {
        "granted": granted,
        "term": state.get("term", 0),
        "voted_term": state.get("voted_term", 0),
        "voted_for": state.get("voted_for"),
    }


def create_arbiter_app(state: Optional[dict] = None) -> WebApp:
    """``state`` (mutable, shared with the caller/tests) mirrors the
    store server's role dict shape where it matters: ``term`` is the
    highest term this arbiter has observed, ``boot`` identifies the
    incarnation."""
    app = WebApp("arbiter")
    state = state if state is not None else {}
    state.setdefault("term", 0)
    state.setdefault("voted_term", 0)
    state.setdefault("voted_for", None)
    state.setdefault("boot", secrets.token_hex(8))
    state.setdefault("lock", threading.Lock())

    @app.route("/health", methods=("GET",))
    def health(request):
        with state["lock"]:
            return {
                "ok": True,
                "arbiter": True,
                "writable": False,  # never holds data, never promotes
                "term": state["term"],
                "voted_term": state["voted_term"],
                "boot": state["boot"],
            }, 200

    @app.route("/vote", methods=("POST",))
    def vote(request):
        body = request.get_json()
        try:
            term = int(body["term"])
            candidate = str(body["candidate"])
        except (KeyError, TypeError, ValueError):
            return {"error": "vote needs integer term + candidate"}, 400
        with state["lock"]:
            payload = grant_vote(state, term, candidate)
            # an election in flight moves the observed term forward even
            # when this vote is denied — later stale candidacies at the
            # same term must also be denied
            state["term"] = max(state["term"], payload["voted_term"])
        return payload, 200

    return app


def serve(host: str = "127.0.0.1", port: int = DEFAULT_ARBITER_PORT) -> ServerThread:
    state: dict = {}
    server = ServerThread(create_arbiter_app(state), host, port).start()
    server.arbiter_state = state
    return server


def main() -> None:
    from learningorchestra_tpu.testing import faults

    try:
        faults.validate_env()  # refuse bring-up on a typo'd chaos knob
    except ValueError as error:
        raise SystemExit(f"LO_FAULT_* validation failed: {error}")
    # lo: allow[LO305] boot main(): the arbiter's own launcher wiring
    host = os.environ.get("LO_HOST", "127.0.0.1")
    # lo: allow[LO305] boot main(): the arbiter's own launcher wiring
    port = int(os.environ.get("LO_ARBITER_PORT", DEFAULT_ARBITER_PORT))
    server = serve(host, port)
    print(f"store arbiter on {host}:{server.port}", flush=True)
    server._thread.join()


if __name__ == "__main__":
    main()
