"""Binary columnar wire framing for the store service.

The reference's data plane is BSON over the Mongo wire protocol
(reference: microservices/database_api_image/database.py:94-130 via
pymongo) — typed bytes, not text. Round 3 shipped dataset bodies as
JSON, which costs ~10× the bytes and a float-repr per cell. This frame
is the typed replacement for the three bulk columnar verbs
(``read_columns`` / ``insert_columns`` / ``set_column``).

Two frame versions share one header schema:

**v1** (``LOCB1``) — the original layout, kept for old peers::

    LOCB1\\n | u32 header_len | header JSON | buffer bytes...

**v2** (``LOCB2``) — fixed-width, 64-byte-aligned columnar layout::

    LOCB2\\n | u32 header_len | header JSON | pad | buffer | pad | ...

where every buffer starts on a 64-byte boundary *relative to the frame
start*. Decoding a v2 frame performs ONE allocation (an aligned copy of
the whole frame — or zero when the bytes already sit in an aligned
buffer, e.g. a shared-memory ring slot) and hands each column numpy
**views** into it: no per-column copies, no per-cell work, and every
view is 64-byte aligned (SIMD/DMA friendly). The views are read-only
and carry an ownership token (:class:`FrameOwner`) so a consumer — the
device cache pinning a decoded table — keeps exactly one backing buffer
alive, and a caller writing through a view cannot corrupt it
(copy-on-write via ``Column._shared``).

Version negotiation rides the existing ``X-Lo-Columns-Accept`` header:
a client that understands v2 advertises ``v2`` (alongside ``zlib`` when
it wants compression); a server only emits v2 when asked, so old
clients keep receiving v1 and old servers keep being understood —
:func:`decode_frame` dispatches on the magic either way.

The header describes each column (kind, row count, which buffers
follow, per-buffer lengths); buffers are the columns' live numpy
payloads verbatim (``Column.wire_parts`` — handed over as buffer
views, never ``tobytes`` copies; the LO106 analyzer rule keeps it that
way). Encoding and decoding do zero per-cell work. ``obj``-kind columns
(mixed cells) fall back to JSON values inside the header — they are the
overlay tail, never the dataset body.
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import Optional, Union

import numpy as np

from learningorchestra_tpu.core.columns import Column, FrameOwner

MAGIC = b"LOCB1\n"
MAGIC_V2 = b"LOCB2\n"
CONTENT_TYPE = "application/x-lo-columns"

# Buffer alignment of the v2 layout. 64 bytes covers every dtype the
# columns ship (f8/i8 need 8) with headroom for cache-line/AVX-512-width
# access — and it is what lets decode hand out *views* instead of
# per-column aligned copies.
ALIGN = 64

# Optional whole-frame compression (LO_STORE_COMPRESS), negotiated per
# request: the client advertises ACCEPT_HEADER on binary reads (and
# stamps ENCODING_HEADER on compressed uploads); the server compresses a
# response ONLY when the request advertised, and always stamps
# ENCODING_HEADER on what it compressed. Custom headers — not HTTP
# Content-Encoding — so no WSGI middleware ever transcodes the frame
# behind the framing's back. stdlib zlib at level 1: typed float columns
# compress 2-4x and the deflate cost overlaps the next chunk's fetch in
# the double-buffered read loop (store_service.RemoteStore).
#
# The same comma-separated ACCEPT_HEADER value carries the frame-version
# token: "v2" means "send me aligned LOCB2 frames".
ACCEPT_HEADER = "X-Lo-Columns-Accept"
ENCODING_HEADER = "X-Lo-Columns-Encoding"
WIRE_COMPRESSION = "zlib"
WIRE_V2 = "v2"
COMPRESS_LEVEL = 1
# Frames below this aren't worth a deflate pass (headers dominate).
COMPRESS_MIN_BYTES = 4096

Buffer = Union[bytes, bytearray, memoryview, np.ndarray]


def compress_frame(frame: bytes) -> bytes:
    return zlib.compress(frame, COMPRESS_LEVEL)


def decode_body(data: bytes, encoding: Optional[str]) -> bytes:
    """Undo wire compression per the peer's ENCODING_HEADER value."""
    if not encoding:
        return data
    if encoding != WIRE_COMPRESSION:
        raise ValueError(f"unknown columns wire encoding {encoding!r}")
    return zlib.decompress(data)


def accept_tokens(header_value: Optional[str]) -> set[str]:
    """The comma-separated ``X-Lo-Columns-Accept`` value as tokens."""
    if not header_value:
        return set()
    return {token.strip() for token in header_value.split(",") if token.strip()}


def _byte_view(part: Buffer) -> memoryview:
    """``part`` as a flat byte view — no copy, whatever the dtype.
    Zero-size arrays short-circuit: ``memoryview.cast`` rejects any
    view with a zero in its shape (a (0, w) vec buffer from a
    beyond-the-end paged chunk, a width-0 vec column)."""
    if isinstance(part, np.ndarray):
        if part.size == 0:
            return memoryview(b"")
        return memoryview(np.ascontiguousarray(part)).cast("B")
    return memoryview(part).cast("B")


def _align_up(offset: int) -> int:
    return (offset + ALIGN - 1) // ALIGN * ALIGN


def _build_header(columns: dict[str, Column], extra: Optional[dict]):
    header: dict = {"extra": extra or {}, "columns": []}
    buffers: list[memoryview] = []
    for name, column in columns.items():
        meta, parts = column.wire_parts()
        views = [_byte_view(part) for part in parts]
        meta["name"] = name
        meta["lens"] = [view.nbytes for view in views]
        header["columns"].append(meta)
        buffers.extend(views)
    return json.dumps(header).encode("utf-8"), buffers


def encode_frame(
    columns: dict[str, Column],
    extra: Optional[dict] = None,
    version: int = 1,
) -> bytes:
    """One frame for ``columns`` (+ the header's ``extra`` dict).

    ``version=2`` emits the aligned LOCB2 layout — only send it to a
    peer that advertised ``v2`` (the decode side accepts both)."""
    encoded, buffers = _build_header(columns, extra)
    if version == 2:
        out = bytearray()
        out += MAGIC_V2
        out += struct.pack("<I", len(encoded))
        out += encoded
        for view in buffers:
            pad = _align_up(len(out)) - len(out)
            out += b"\0" * pad
            out += view
        return bytes(out)
    out = bytearray()
    out += MAGIC
    out += struct.pack("<I", len(encoded))
    out += encoded
    for view in buffers:
        out += view
    return bytes(out)


def frame_version(data: Buffer) -> int:
    """1 or 2 per the magic; raises ``ValueError`` on anything else."""
    magic = bytes(_byte_view(data)[: len(MAGIC)])
    if magic == MAGIC:
        return 1
    if magic == MAGIC_V2:
        return 2
    raise ValueError("bad columnar frame magic")


def aligned_frame(data: Buffer) -> np.ndarray:
    """``data`` as a 64-byte-aligned, read-only uint8 array — ONE
    allocation + one memcpy when the source isn't already aligned, zero
    when it is (a shared-memory ring slot). This is the only copy a v2
    decode ever performs."""
    if (
        isinstance(data, np.ndarray)
        and data.dtype == np.uint8
        and data.ndim == 1
        and data.ctypes.data % ALIGN == 0
    ):
        if data.flags.writeable:
            data = data[:]
            data.flags.writeable = False
        return data
    view = _byte_view(data)
    n = view.nbytes
    backing = np.empty(n + ALIGN, dtype=np.uint8)
    shift = (-backing.ctypes.data) % ALIGN
    base = backing[shift : shift + n]
    base[:] = np.frombuffer(view, dtype=np.uint8)
    base.flags.writeable = False
    return base


def _parse_header(view: memoryview) -> tuple[dict, int]:
    (header_len,) = struct.unpack_from("<I", view, len(MAGIC))
    start = len(MAGIC) + 4
    header = json.loads(bytes(view[start : start + header_len]).decode("utf-8"))
    return header, start + header_len


def decode_frame(data: Buffer) -> tuple[dict[str, Column], dict]:
    """Decode either frame version (dispatching on the magic).

    v1 frames decode into columns that OWN their buffers (per-column
    copies — the compatibility contract old peers rely on). v2 frames
    decode zero-copy: one aligned allocation for the whole frame, every
    column a read-only view into it, ownership tracked by a shared
    :class:`FrameOwner` so a pinning consumer (the device cache) keeps
    exactly one buffer alive."""
    if frame_version(data) == 2:
        return decode_frame_v2(data)
    view = _byte_view(data)
    header, offset = _parse_header(view)
    columns: dict[str, Column] = {}
    for meta in header["columns"]:
        parts: list[bytes] = []
        for length in meta["lens"]:
            if offset + length > view.nbytes:
                # a slice would silently come back short — a truncated
                # frame (server dying mid-response) must RAISE so the
                # chunk-retry machinery re-fetches, never return a
                # silently short column
                raise ValueError("truncated columnar frame")
            parts.append(bytes(view[offset : offset + length]))
            offset += length
        columns[meta["name"]] = Column.from_wire_parts(meta, parts)
    return columns, header.get("extra", {})


def decode_frame_v2(data: Buffer) -> tuple[dict[str, Column], dict]:
    base = aligned_frame(data)
    header, offset = _parse_header(memoryview(base))
    owner = FrameOwner(base)
    columns: dict[str, Column] = {}
    for meta in header["columns"]:
        parts: list[np.ndarray] = []
        for length in meta["lens"]:
            offset = _align_up(offset)
            if offset + length > len(base):
                # see decode_frame: short slices must raise, not decode
                raise ValueError("truncated columnar frame")
            parts.append(base[offset : offset + length])
            offset += length
        columns[meta["name"]] = Column.from_wire_parts(
            meta, parts, copy=False, owner=owner
        )
    return columns, header.get("extra", {})
