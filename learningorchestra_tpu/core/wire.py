"""Binary columnar wire framing for the store service.

The reference's data plane is BSON over the Mongo wire protocol
(reference: microservices/database_api_image/database.py:94-130 via
pymongo) — typed bytes, not text. Round 3 shipped dataset bodies as
JSON, which costs ~10× the bytes and a float-repr per cell. This frame
is the typed replacement for the three bulk columnar verbs
(``read_columns`` / ``insert_columns`` / ``set_column``):

    LOCB1\\n | u32 header_len | header JSON | buffer bytes...

The header describes each column (kind, row count, which buffers
follow, per-buffer lengths); buffers are the columns' live numpy
payloads verbatim (``Column.wire_parts``) — float64/int64 data, Arrow
string bytes + offsets, packed null/missing bitmasks. Encoding and
decoding do zero per-cell work. ``obj``-kind columns (mixed cells)
fall back to JSON values inside the header — they are the overlay tail,
never the dataset body.
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import Optional

from learningorchestra_tpu.core.columns import Column

MAGIC = b"LOCB1\n"
CONTENT_TYPE = "application/x-lo-columns"

# Optional whole-frame compression (LO_STORE_COMPRESS), negotiated per
# request: the client advertises ACCEPT_HEADER on binary reads (and
# stamps ENCODING_HEADER on compressed uploads); the server compresses a
# response ONLY when the request advertised, and always stamps
# ENCODING_HEADER on what it compressed. Custom headers — not HTTP
# Content-Encoding — so no WSGI middleware ever transcodes the frame
# behind the framing's back. stdlib zlib at level 1: typed float columns
# compress 2-4x and the deflate cost overlaps the next chunk's fetch in
# the double-buffered read loop (store_service.RemoteStore).
ACCEPT_HEADER = "X-Lo-Columns-Accept"
ENCODING_HEADER = "X-Lo-Columns-Encoding"
WIRE_COMPRESSION = "zlib"
COMPRESS_LEVEL = 1
# Frames below this aren't worth a deflate pass (headers dominate).
COMPRESS_MIN_BYTES = 4096


def compress_frame(frame: bytes) -> bytes:
    return zlib.compress(frame, COMPRESS_LEVEL)


def decode_body(data: bytes, encoding: Optional[str]) -> bytes:
    """Undo wire compression per the peer's ENCODING_HEADER value."""
    if not encoding:
        return data
    if encoding != WIRE_COMPRESSION:
        raise ValueError(f"unknown columns wire encoding {encoding!r}")
    return zlib.decompress(data)


def encode_frame(
    columns: dict[str, Column], extra: Optional[dict] = None
) -> bytes:
    header: dict = {"extra": extra or {}, "columns": []}
    buffers: list[bytes] = []
    for name, column in columns.items():
        meta, parts = column.wire_parts()
        meta["name"] = name
        meta["lens"] = [len(part) for part in parts]
        header["columns"].append(meta)
        buffers.extend(parts)
    encoded = json.dumps(header).encode("utf-8")
    out = bytearray()
    out += MAGIC
    out += struct.pack("<I", len(encoded))
    out += encoded
    for part in buffers:
        out += part
    return bytes(out)


def decode_frame(data: bytes) -> tuple[dict[str, Column], dict]:
    if data[: len(MAGIC)] != MAGIC:
        raise ValueError("bad columnar frame magic")
    offset = len(MAGIC)
    (header_len,) = struct.unpack_from("<I", data, offset)
    offset += 4
    header = json.loads(data[offset : offset + header_len].decode("utf-8"))
    offset += header_len
    columns: dict[str, Column] = {}
    view = memoryview(data)
    for meta in header["columns"]:
        parts: list[bytes] = []
        for length in meta["lens"]:
            parts.append(bytes(view[offset : offset + length]))
            offset += length
        columns[meta["name"]] = Column.from_wire_parts(meta, parts)
    return columns, header.get("extra", {})
