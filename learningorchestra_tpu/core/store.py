"""Document store: the framework's storage contract.

The reference uses a MongoDB replica set as its only data plane; every
dataset is a collection whose row ``_id: 0`` is a metadata document with a
``finished`` flag, and rows are documents ``_id: 1..N`` (reference:
microservices/database_api_image/database.py:14-15,199-216). This module
keeps that contract but makes the store a first-class, pluggable part of
the framework:

- :class:`DocumentStore` — the interface every backend implements. It is
  a superset of the hand-rolled ``DatabaseInterface`` ABCs scattered
  through the reference services (e.g. reference:
  microservices/model_builder_image/model_builder.py:33-43).
- :class:`InMemoryStore` — thread-safe in-process backend with an
  optional JSONL write-ahead log for durability. Used directly by tests
  and by the storage service (``services/storage.py``).
- Columnar reads (:meth:`DocumentStore.read_columns` /
  :meth:`DocumentStore.read_column_arrays`) are the data plane between
  storage and the TPU: compute never does row-at-a-time RPCs the way
  the reference does (reference:
  microservices/model_builder_image/model_builder.py:237-247).

Dataset bodies live in **typed columnar blocks** (core/columns.py):
numpy buffers for numbers/bools, Arrow-style byte buffers for strings —
~8 bytes/cell instead of the ~60-100 bytes a boxed Python object costs,
which is what makes 10M+-row datasets fit where the reference leans on
Mongo owning disk (reference: docker-compose.yml:335-340). A
row-document overlay holds the ``_id: 0`` metadata document and any
out-of-band inserts, preserving full document semantics.

Queries are Mongo-style subset-equality matches, which is the full extent
of what the reference services use.
"""

from __future__ import annotations

import ast
import itertools
import json
import os
import re
import threading
from typing import Any, Iterator, Optional, Union

import numpy as np

from learningorchestra_tpu.core.columns import MISSING as _MISSING
from learningorchestra_tpu.core.columns import Column

ROW_ID = "_id"
METADATA_ID = 0

# Metadata keys a dataset's `_id: 0` document may carry (reference:
# microservices/model_builder_image/model_builder.py:103-111).
METADATA_FIELDS = (
    "_id",
    "fields",
    "filename",
    "finished",
    "time_created",
    "url",
    "parent_filename",
)

ColumnInput = Union[Column, list, np.ndarray]


def parse_query(raw: Optional[str]) -> dict:
    """Parse a query string sent over REST.

    The reference client serialises queries with ``str(dict)`` (reference:
    learning_orchestra_client/__init__.py:75) which produces Python repr,
    while the server parses with ``json.loads`` (reference:
    microservices/database_api_image/database.py:40) — so any non-trivial
    query crashes it. We accept both encodings.
    """
    if not raw:
        return {}
    try:
        query = json.loads(raw)
    except (json.JSONDecodeError, ValueError):
        query = ast.literal_eval(raw)  # ValueError/SyntaxError to caller
    if not isinstance(query, dict):
        raise ValueError(f"query must be a dict, got {type(query).__name__}")
    return query


class UnsupportedQueryError(ValueError):
    """A query uses an operator this engine doesn't implement, or a
    malformed operand. The REST layer maps it to a 400 rather than
    letting it surface as a 500."""


def _membership_list(op: str, operand: Any) -> Any:
    if not isinstance(operand, (list, tuple, set)):
        raise UnsupportedQueryError(f"{op} operand must be a list")
    return operand


def _compare(op: str, value: Any, operand: Any) -> bool:
    if op == "$in":
        operand = _membership_list(op, operand)
    try:
        if op == "$eq":
            return value == operand
        if op == "$gt":
            return value > operand
        if op == "$gte":
            return value >= operand
        if op == "$lt":
            return value < operand
        if op == "$lte":
            return value <= operand
        if op == "$in":
            return value in operand
    except TypeError:  # e.g. None vs number — Mongo treats as no match
        return False
    raise UnsupportedQueryError(f"unsupported query operator {op!r}")


def _match_operators(document: dict, key: str, ops: dict) -> bool:
    """Operator document on one field, with Mongo's missing-field
    semantics: ``$ne``/``$nin`` match documents lacking the field, the
    comparisons don't."""
    present = key in document
    value = document.get(key)
    for op, operand in ops.items():
        if op == "$exists":
            if present != bool(operand):
                return False
        elif op == "$ne":
            if present and value == operand:
                return False
        elif op == "$nin":
            operand = _membership_list(op, operand)  # validate even if absent
            if present and value in operand:
                return False
        elif op == "$regex":
            try:
                pattern = re.compile(operand)
            except (re.error, TypeError) as error:
                raise UnsupportedQueryError(
                    f"invalid $regex operand {operand!r}"
                ) from error
            if not present or not isinstance(value, str) or not pattern.search(value):
                return False
        elif op == "$not":
            if not isinstance(operand, dict):
                raise UnsupportedQueryError("$not operand must be an operator dict")
            if _match_operators(document, key, operand):
                return False
        else:
            if op not in ("$eq", "$gt", "$gte", "$lt", "$lte", "$in"):
                raise UnsupportedQueryError(f"unsupported query operator {op!r}")
            if op == "$in":
                operand = _membership_list(op, operand)  # validate even if absent
            if not present or not _compare(op, value, operand):
                return False
    return True


def matches(document: dict, query: dict) -> bool:
    """Mongo-style match — the operator surface the reference exposes by
    forwarding client queries straight to pymongo ``find`` (reference:
    microservices/database_api_image/database.py:36-44): subset equality,
    ``$eq/$ne/$gt/$gte/$lt/$lte/$in/$nin/$exists/$regex/$not``, and the
    top-level logicals ``$or/$and/$nor``. Anything else raises
    :class:`UnsupportedQueryError` (→ REST 400) instead of silently
    matching nothing."""
    for key, condition in query.items():
        if key in ("$or", "$and", "$nor"):
            if not isinstance(condition, (list, tuple)) or not all(
                isinstance(sub, dict) for sub in condition
            ):
                raise UnsupportedQueryError(f"{key} operand must be a list of dicts")
            branches = [matches(document, sub) for sub in condition]
            if key == "$or" and not any(branches):
                return False
            if key == "$and" and not all(branches):
                return False
            if key == "$nor" and any(branches):
                return False
        elif key.startswith("$"):
            raise UnsupportedQueryError(f"unsupported query operator {key!r}")
        elif isinstance(condition, dict) and any(
            k.startswith("$") for k in condition
        ):
            if not _match_operators(document, key, condition):
                return False
        elif key not in document or document[key] != condition:
            return False
    return True


def as_column(values: ColumnInput) -> Column:
    """Normalize any accepted columnar input to a :class:`Column`."""
    if isinstance(values, Column):
        return values
    if isinstance(values, np.ndarray):
        return Column.from_numpy(values)
    return Column.from_values(values)


class DocumentStore:
    """Interface for collection-of-documents backends."""

    # --- collection lifecycle -------------------------------------------------
    def list_collections(self) -> list[str]:
        raise NotImplementedError

    def create_collection(self, collection: str) -> bool:
        """Atomically claim ``collection``; False if it already exists.

        The duplicate-output-name gate for create routes. The reference
        validates with a check-then-act list scan
        (reference: microservices/projection_image/projection.py:151-155)
        — a race SURVEY §5 flags; this primitive makes the claim atomic
        so concurrent duplicate creates get exactly one winner.
        """
        raise NotImplementedError

    def drop(self, collection: str) -> None:
        raise NotImplementedError

    def trim_collection(self, collection: str, max_docs: int) -> int:
        """Ring-collection cap discipline: drop the OLDEST overlay
        documents (ascending int ``_id``, metadata excluded) until at
        most ``max_docs`` remain; returns how many were removed. The
        bounded-retention primitive behind ``__lo_metrics__``
        (telemetry/tsdb.py) — rev-bumping like every other mutation, so
        paged readers and caches see the eviction. Columnar block rows
        are out of scope: rings are row-document collections."""
        raise NotImplementedError

    # --- writes ---------------------------------------------------------------
    def insert_one(self, collection: str, document: dict) -> None:
        raise NotImplementedError

    def insert_many(self, collection: str, documents: list[dict]) -> None:
        for document in documents:
            self.insert_one(collection, document)

    def insert_columns(
        self,
        collection: str,
        columns: dict[str, ColumnInput],
        start_id: Optional[int] = None,
    ) -> None:
        """Bulk column-major append: rows ``start_id..start_id+n-1`` with
        ``{field: values[i]}``. The storage→compute data plane's write
        half — backends keep this columnar end to end so dataset bodies
        never pay per-row Python dict costs. Values may be plain lists,
        numpy arrays, or :class:`Column` objects. Default implementation
        degrades to ``insert_many`` for row-oriented backends.
        """
        columns = {name: as_column(values) for name, values in columns.items()}
        lengths = {len(values) for values in columns.values()}
        if len(lengths) > 1:
            raise ValueError("ragged columns")
        num_rows = lengths.pop() if lengths else 0
        value_lists = {
            name: column.tolist(pad_as_none=False)
            for name, column in columns.items()
        }
        documents = []
        for i in range(num_rows):
            document = {
                name: values[i]
                for name, values in value_lists.items()
                if values[i] is not _MISSING
            }
            if start_id is not None:
                document[ROW_ID] = start_id + i
            documents.append(document)
        self.insert_many(collection, documents)

    def insert_column_arrays(
        self,
        collection: str,
        columns: dict[str, Column],
        start_id: Optional[int] = None,
    ) -> None:
        """Typed-column append — the zero-conversion write half of the
        data plane. Same semantics as :meth:`insert_columns`."""
        self.insert_columns(collection, columns, start_id=start_id)

    def update_one(self, collection: str, query: dict, new_values: dict) -> None:
        """Set ``new_values`` on the first document matching ``query``
        (Mongo ``update_one(filter, {"$set": ...})`` semantics)."""
        raise NotImplementedError

    def set_field_values(
        self, collection: str, field: str, values_by_id: dict
    ) -> None:
        """Bulk-write one field across many rows: ``{_id: new_value}``.

        The columnar write path. The reference updates converted values one
        ``update_one`` RPC per document (reference:
        microservices/data_type_handler_image/data_type_handler.py:47-77);
        backends implement this as a single batched mutation instead.
        """
        for doc_id, value in values_by_id.items():
            self.update_one(collection, {ROW_ID: doc_id}, {field: value})

    def set_column(
        self,
        collection: str,
        field: str,
        values: ColumnInput,
        start_id: int = 1,
    ) -> None:
        """Replace ``field`` for the contiguous rows ``start_id..`` with
        ``values`` — the column-major write the fieldtypes conversion
        uses (one bulk call per field; the reference issues 2 RPCs per
        row per field, reference data_type_handler.py:47-77). Default
        implementation degrades to ``set_field_values``."""
        values = as_column(values).tolist(pad_as_none=False)
        self.set_field_values(
            collection,
            field,
            {start_id + i: value for i, value in enumerate(values)},
        )

    # --- reads ----------------------------------------------------------------
    def find(
        self,
        collection: str,
        query: Optional[dict] = None,
        skip: int = 0,
        limit: Optional[int] = None,
    ) -> Iterator[dict]:
        """Documents matching ``query``, ordered by ``_id`` ascending."""
        raise NotImplementedError

    def find_one(self, collection: str, query: dict) -> Optional[dict]:
        for document in self.find(collection, query, limit=1):
            return document
        return None

    def count(self, collection: str) -> int:
        return sum(1 for _ in self.find(collection))

    def aggregate(self, collection: str, pipeline: list[dict]) -> list[dict]:
        """The ``$group``/``$sum: 1`` value-count pipeline the histogram
        service pushes down (reference:
        microservices/histogram_image/histogram.py:63-69)."""
        raise NotImplementedError

    # --- columnar data plane --------------------------------------------------
    def read_columns(
        self,
        collection: str,
        fields: Optional[list[str]] = None,
        start: int = 0,
        limit: Optional[int] = None,
    ) -> dict[str, list]:
        """Column-major read of non-metadata rows, ordered by ``_id``.

        Returns ``{field: [values...]}``. This is the storage→device path:
        one bulk call instead of the reference's per-row RPCs.
        ``start``/``limit`` slice the row range (after metadata exclusion)
        so wire backends can page large datasets in bounded chunks;
        field-name discovery under ``fields=None`` always scans every row
        (a chunk must not change the column set).
        """
        rows = [
            document
            for document in self.find(collection)
            if document.get(ROW_ID) != METADATA_ID
        ]
        if fields is None:
            names: list[str] = []
            for row in rows:
                for key in row:
                    if key not in names and key != ROW_ID:
                        names.append(key)
            fields = names
        stop = None if limit is None else start + limit
        rows = rows[start:stop]
        return {
            field: [row.get(field) for row in rows] for field in fields
        }

    def read_column_arrays(
        self,
        collection: str,
        fields: Optional[list[str]] = None,
        start: int = 0,
        limit: Optional[int] = None,
    ) -> dict[str, Column]:
        """Typed-column read — the zero-conversion half of the data
        plane. Same row semantics as :meth:`read_columns`. Default
        implementation wraps the list read."""
        return {
            name: Column.from_values(values)
            for name, values in self.read_columns(
                collection, fields, start=start, limit=limit
            ).items()
        }

    def collection_rev(self, collection: str) -> int:
        """Mutation counter for torn-read detection and device-cache
        invalidation (core/devcache.py). -1 = unknown/missing: backends
        that cannot report one opt every cached reader out, never into
        staleness."""
        return -1

    def collection_block_rows(self, collection: str) -> int:
        """Rows in the collection's columnar block (excluding overlay
        documents), -1 when the collection is missing. The sharded
        client (core/shardstore.py) sums these across groups to place
        appends and split positional reads; row-oriented backends that
        cannot tell block from overlay report -1 too."""
        return -1

    # --- dataset metadata contract -------------------------------------------
    def metadata(self, collection: str) -> Optional[dict]:
        return self.find_one(collection, {ROW_ID: METADATA_ID})

    def is_finished(self, collection: str) -> bool:
        meta = self.metadata(collection)
        return bool(meta and meta.get("finished"))


def _group_count(documents: Iterator[dict], field: str) -> list[dict]:
    # Keys carry a bool tag: True hashes equal to 1, and a plain dict
    # would merge the two groups (Mongo keeps true and 1 distinct).
    counts: dict[Any, int] = {}
    for document in documents:
        if document.get(ROW_ID) == METADATA_ID:
            continue
        value = document.get(field)
        key = (isinstance(value, bool), value)
        counts[key] = counts.get(key, 0) + 1
    return [{"_id": key[1], "count": count} for key, count in counts.items()]


def _is_int_id(doc_id: Any) -> bool:
    return isinstance(doc_id, int) and not isinstance(doc_id, bool)


# Columns below this size are never worth a file + mapping.
_SPILL_MIN_COLUMN_BYTES = 16 * 1024 * 1024

# Distinguishes multiple stores in ONE process under a shared
# LO_SPILL_DIR (e.g. a primary + follower pair in tests).
_SPILL_DIR_SEQ = itertools.count()

# Seconds between advise_cold sweeps: every sweep evicts resident mapped
# pages a concurrent scan may just have faulted in, so it is
# rate-limited rather than run per insert batch.
_ADVISE_INTERVAL_S = 5.0


def _path_safe(name: str) -> str:
    """Collection/field names as filesystem-safe path components."""
    return "".join(
        ch if ch.isalnum() or ch in "._-" else "_" for ch in name
    ) or "_"


class _Collection:
    """One collection's storage: a contiguous column-major block for the
    dataset body plus a row-document overlay for everything else.

    The block holds rows ``block_start..block_start+n-1`` as typed
    :class:`Column` buffers (core/columns.py), one per field — ~8
    bytes/cell, zero boxed objects — the shape bulk ingest/projection
    write and ``read_columns`` returns. The overlay holds the ``_id: 0``
    metadata document and any out-of-band inserts. Ids never overlap
    between the two.
    """

    __slots__ = ("block_fields", "block_columns", "block_start", "rows", "rev")

    def __init__(self):
        self.block_fields: list[str] = []
        self.block_columns: dict[str, Column] = {}
        self.block_start = 1
        self.rows: dict[Any, dict] = {}
        # Mutation counter: paged wire readers compare it across chunks
        # to detect (and retry) a torn multi-request read, and the
        # device cache keys entries by it. Values are drawn from the
        # STORE's monotonic sequence (never per-collection counting) so
        # a dropped-and-recreated collection can't reissue a rev a cache
        # somewhere still holds.
        self.rev = 0

    def snapshot(self) -> "_Collection":
        """A consistent read view: columns are copy-on-write snapshots
        (O(1) per column), overlay documents shallow-copied — so
        ``find`` can yield outside the store lock without seeing
        concurrent mutations tear a document mid-iteration. Must be
        called while holding the store lock."""
        clone = _Collection()
        clone.block_fields = list(self.block_fields)
        clone.block_columns = {
            name: column.snapshot()
            for name, column in self.block_columns.items()
        }
        clone.block_start = self.block_start
        clone.rows = {doc_id: dict(row) for doc_id, row in self.rows.items()}
        clone.rev = self.rev
        return clone

    # --- block geometry -------------------------------------------------------
    @property
    def block_rows(self) -> int:
        if not self.block_columns:
            return 0
        return len(next(iter(self.block_columns.values())))

    @property
    def block_stop(self) -> int:
        """One past the last block id."""
        return self.block_start + self.block_rows

    def in_block(self, doc_id: Any) -> bool:
        return _is_int_id(doc_id) and self.block_start <= doc_id < self.block_stop

    def has_id(self, doc_id: Any) -> bool:
        return self.in_block(doc_id) or doc_id in self.rows

    def next_id(self) -> int:
        top = self.block_stop - 1 if self.block_columns else 0
        for doc_id in self.rows:
            if _is_int_id(doc_id) and doc_id > top:
                top = doc_id
        return top + 1

    # --- row synthesis --------------------------------------------------------
    def block_document(self, doc_id: int) -> dict:
        i = doc_id - self.block_start
        document = {}
        for name in self.block_fields:
            value = self.block_columns[name].get(i)
            if value is not _MISSING:
                document[name] = value
        document[ROW_ID] = doc_id
        return document

    def document(self, doc_id: Any) -> dict:
        if self.in_block(doc_id):
            return self.block_document(doc_id)
        return dict(self.rows[doc_id])

    def iter_ids(self) -> Iterator:
        """All ids: ints ascending (overlay and block merged), then
        non-int ids in string order."""
        import heapq

        overlay_ints = sorted(i for i in self.rows if _is_int_id(i))
        yield from heapq.merge(
            overlay_ints, range(self.block_start, self.block_stop)
        )
        yield from sorted(
            (i for i in self.rows if not _is_int_id(i)), key=str
        )

    def overlay_data_ids(self) -> list:
        """Overlay ids other than the metadata document."""
        return [i for i in self.rows if i != METADATA_ID]

    # --- block mutation -------------------------------------------------------
    def ensure_block_field(self, field: str) -> Column:
        if field == ROW_ID:
            raise KeyError("_id is not a block field")
        column = self.block_columns.get(field)
        if column is None:
            column = Column.pads(self.block_rows)
            self.block_columns[field] = column
            self.block_fields.append(field)
        return column

    def set_block_values(self, doc_id: int, new_values: dict) -> None:
        i = doc_id - self.block_start
        for field, value in new_values.items():
            if field == ROW_ID:
                continue
            column = self.ensure_block_field(field)
            self.block_columns[field] = column.set(i, value)

    def append_columns(
        self, columns: dict[str, Column], start_id: int
    ) -> None:
        num_new = len(next(iter(columns.values()))) if columns else 0
        if self.block_columns:
            if start_id != self.block_stop:
                if self.block_start <= start_id < self.block_stop:
                    # overlapping append: the chunk's ids already exist —
                    # a DUPLICATE-id condition (KeyError → wire 409), not
                    # a malformed request; the client's landed-ok retry
                    # machinery relies on the distinction to recognize a
                    # replayed chunk that already landed
                    raise KeyError(
                        f"duplicate _id {start_id!r} (block rows "
                        f"{self.block_start}..{self.block_stop - 1} exist)"
                    )
                raise ValueError(
                    f"columnar append must start at id {self.block_stop}, "
                    f"got {start_id}"
                )
        else:
            self.block_start = start_id
        for doc_id in range(start_id, start_id + num_new):
            if doc_id in self.rows:
                raise KeyError(f"duplicate _id {doc_id!r}")
        for field in columns:
            self.ensure_block_field(field)
        for field in list(self.block_columns):
            column = self.block_columns[field]
            incoming = columns.get(field)
            if incoming is not None:
                self.block_columns[field] = column.append_column(incoming)
            else:
                self.block_columns[field] = column.append_pads(num_new)


class InMemoryStore(DocumentStore):
    """Thread-safe in-process store with optional JSONL write-ahead log.

    Durability model: every mutation appends one JSON line to
    ``<data_dir>/wal.jsonl``; opening a store with the same ``data_dir``
    replays the log. ``compact()`` rewrites the log as a snapshot.
    Columnar payloads ride the WAL as base64-encoded typed buffers
    (``Column.to_json_record``), not per-value JSON.
    """

    def __init__(self, data_dir: Optional[str] = None, replicate: bool = False):
        self._lock = threading.RLock()
        self._collections: dict[str, _Collection] = {}
        # Store-wide rev sequence (see _Collection.rev), started at a
        # random per-boot base: revs are in-memory only, so a restarted
        # store would otherwise count from 1 again and could reissue a
        # rev that a client's device cache (core/devcache.py) still
        # holds for DIFFERENT pre-restart content. 48 random bits keep
        # collisions negligible while staying far under 2^53 (revs ride
        # JSON frames).
        import secrets

        self._rev_seq = itertools.count(secrets.randbits(48) + 1)
        self._wal = None
        # Replication: when enabled, every WAL record (as its serialized
        # JSON line) is also kept in an in-memory buffer so followers can
        # fetch the log over the wire (``wal_feed``). ``_wal_epoch``
        # bumps on every compaction — a follower whose offset belongs to
        # a previous epoch must resync from record 0 (the compacted
        # snapshot IS the new log prefix).
        self._wal_buffer: Optional[list[str]] = [] if replicate else None
        self._wal_epoch = 0
        # During a compaction, mutations are additionally captured here
        # so the snapshot being written can be completed with the
        # records that landed while it was serialized (see compact()).
        self._compact_side: Optional[list[str]] = None
        # Bumped by resync_apply: an in-flight compaction whose
        # generation no longer matches must ABANDON — its snapshot
        # predates the resync and publishing it would revert the log.
        self._compact_gen = 0
        # Out-of-core: RAM budget for column payloads (LO_SPILL_BYTES,
        # 0 disables); past it, cold blocks move to disk-backed
        # mappings under LO_SPILL_DIR (default <data_dir>/spill, or a
        # temp dir for pure in-memory stores). See _maybe_spill_locked.
        # lo: allow[LO305] per-store state, frozen at construction
        self._spill_budget = float(os.environ.get("LO_SPILL_BYTES", "8e9") or 0)
        # lo: allow[LO301,LO305] free-form path knob, no numeric domain
        explicit_spill_dir = os.environ.get("LO_SPILL_DIR")
        if explicit_spill_dir:
            # an operator-chosen directory may be shared between stores
            # (or hold unrelated files): take a per-STORE subdirectory
            # (pid + in-process sequence — a primary and follower in one
            # process must not overwrite each other's mapped files)
            # instead of claiming — and never cleaning — the root.
            # Stale subdirs from dead processes linger until the
            # operator clears them (spill files are process-lifetime
            # artifacts; the WAL is the durability story).
            self._spill_dir = os.path.join(
                explicit_spill_dir,
                f"store-{os.getpid()}-{next(_SPILL_DIR_SEQ)}",
            )
        else:
            self._spill_dir = (
                os.path.join(data_dir, "spill") if data_dir else None
            )
            if (
                self._spill_budget > 0
                and self._spill_dir
                and os.path.isdir(self._spill_dir)
            ):
                # OUR data_dir's spill folder: a previous process's
                # files there are garbage — reclaim at startup
                import shutil

                shutil.rmtree(self._spill_dir, ignore_errors=True)
        self._spill_seq = 0
        # collection → its unique spill folder (collision-proof even for
        # names that sanitize identically); dropped with the collection
        self._spill_folders: dict[str, str] = {}
        if data_dir is not None:
            os.makedirs(data_dir, exist_ok=True)
            wal_path = os.path.join(data_dir, "wal.jsonl")
            # construction is single-threaded, but the replay runs the
            # same _locked helpers the live mutators use — hold the
            # (reentrant) lock so their caller-holds-the-lock contract
            # is true at every call site
            with self._lock:
                if os.path.exists(wal_path):
                    self._replay_locked(wal_path)
                self._wal = open(wal_path, "a", encoding="utf-8")

    # --- WAL ------------------------------------------------------------------
    def _wal_enabled_locked(self) -> bool:
        return self._wal is not None or self._wal_buffer is not None

    def _log_locked(self, record: dict) -> None:
        if self._wal is None and self._wal_buffer is None:
            return
        line = json.dumps(record)
        if self._wal is not None:
            self._wal.write(line + "\n")
            self._wal.flush()
        if self._wal_buffer is not None:
            self._wal_buffer.append(line)
        if self._compact_side is not None:
            self._compact_side.append(line)

    def _replay_locked(self, wal_path: str) -> None:
        with open(wal_path, encoding="utf-8") as handle:
            for line in handle:
                line = line.strip()
                if not line:
                    continue
                self._apply_record_locked(json.loads(line))
                if self._wal_buffer is not None:
                    self._wal_buffer.append(line)

    def _apply_record_locked(self, record: dict) -> None:
        """Apply one WAL record (caller holds the lock; no logging) —
        the single switch shared by startup replay and follower
        replication."""
        op = record["op"]
        if op == "insert":
            self._apply_insert_locked(record["c"], record["d"])
        elif op == "insert_many":
            for document in record["d"]:
                self._apply_insert_locked(record["c"], document)
        elif op == "insert_cols_b":
            self._apply_insert_columns_locked(
                record["c"],
                {
                    field: Column.from_json_record(col)
                    for field, col in record["cols"].items()
                },
                record["s"],
            )
        elif op == "insert_cols":
            # legacy list form (pre-typed-block WALs)
            self._apply_insert_columns_locked(
                record["c"],
                _legacy_columns(record["d"], record.get("m")),
                record["s"],
            )
        elif op == "update":
            self._apply_update_locked(record["c"], record["q"], record["v"])
        elif op == "set_field":
            # Logged as [id, value] pairs so JSON preserves the
            # id's type (dict keys would stringify int ids).
            self._apply_set_field_locked(record["c"], record["f"], dict(record["d"]))
        elif op == "set_col_b":
            self._apply_set_column_locked(
                record["c"],
                record["f"],
                Column.from_json_record(record["col"]),
                record["s"],
            )
        elif op == "set_col":
            self._apply_set_column_locked(
                record["c"],
                record["f"],
                Column.from_values(record["d"]),
                record["s"],
            )
        elif op == "trim":
            self._apply_trim_locked(record["c"], record["n"])
        elif op == "create":
            self._collections.setdefault(record["c"], _Collection())
        elif op == "drop":
            self._collections.pop(record["c"], None)
            # replicated/replayed drops must reclaim spill files too:
            # a follower applying a primary's drop through this switch
            # used to strand the folder AND mis-route a recreated
            # same-name collection into it (stale mapping via
            # _maybe_spill_locked's setdefault) — the drop() entry point below
            # cleaned up, this one didn't (ADVICE r5 class)
            self._drop_spill_folder_locked(record["c"])
        elif op == "epoch":
            # Epoch is part of the log so it survives restarts: a
            # follower cursor is only valid against the SAME log, and a
            # primary that compacted then rebooted must not hand out its
            # pre-compaction epoch (stale cursors would silently apply
            # the wrong suffix).
            self._wal_epoch = record["e"]

    # --- replication ----------------------------------------------------------
    @property
    def wal_length(self) -> int:
        """Records in the replication feed (0 when replication is off)."""
        with self._lock:
            return len(self._wal_buffer or ())

    @property
    def wal_epoch(self) -> int:
        """Current feed epoch (bumps on compaction)."""
        with self._lock:
            return self._wal_epoch

    @property
    def wal_position(self) -> tuple[int, int]:
        """``(epoch, length)`` under ONE lock acquisition — the
        sync-repl ack wait must capture both atomically or a compaction
        between two reads pairs a stale epoch with the new epoch's tiny
        length and falsely satisfies the wait."""
        with self._lock:
            return self._wal_epoch, len(self._wal_buffer or ())

    @property
    def replicating(self) -> bool:
        """True when this store keeps the in-memory feed followers tail.
        Lock-free on purpose: _wal_buffer is bound once in __init__ (or
        swapped whole under the lock) and this is an identity check, so
        a torn read is impossible."""
        return self._wal_buffer is not None  # lo: allow[LO203]

    def wal_feed(self, epoch: int, offset: int, limit: int = 10000) -> dict:
        """Serialized WAL records from ``(epoch, offset)`` onward.

        Returns ``{"epoch", "offset", "next", "records", "resync"}`` with
        ``records`` as raw JSON lines. A stale epoch (the primary
        compacted since) or an impossible offset answers ``resync: True``
        with the current epoch — the follower clears and pulls from 0,
        where the compacted snapshot now lives.
        """
        with self._lock:
            if self._wal_buffer is None:
                raise ValueError("replication not enabled on this store")
            if epoch != self._wal_epoch or offset > len(self._wal_buffer):
                return {
                    "epoch": self._wal_epoch,
                    "offset": 0,
                    "next": 0,
                    "length": len(self._wal_buffer),
                    "records": [],
                    "resync": True,
                }
            records = self._wal_buffer[offset : offset + limit]
            return {
                "epoch": self._wal_epoch,
                "offset": offset,
                "next": offset + len(records),
                # total feed length: followers compute replication lag
                # (and the loss window of a takeover) from it
                "length": len(self._wal_buffer),
                "records": records,
                "resync": False,
            }

    def apply_replicated(self, lines: list[str]) -> None:
        """Follower-side ingestion: apply raw WAL lines from the primary
        and re-log them locally (the follower's own WAL/buffer make it
        promotable to primary with full durability)."""
        with self._lock:
            for line in lines:
                record = json.loads(line)
                self._apply_record_locked(record)
                self._log_locked(record)

    def resync_apply(self, lines: list[str]) -> None:
        """Replace ALL state with the given WAL lines (stale-epoch
        resync): the new log is written to a temp file and
        ``os.replace``d over the local WAL FIRST, then memory is rebuilt
        from it — the durable copy is never empty, so a crash at any
        point leaves either the old replica state or the new snapshot,
        never nothing."""
        with self._lock:
            # Invalidate any in-flight compaction: its snapshot views
            # predate this resync and MUST NOT be published over the
            # resynced log (compact() checks the generation before its
            # buffer/file swaps and abandons).
            self._compact_gen += 1
            self._compact_side = None
            if self._wal is not None:
                path = self._wal.name
                tmp_path = path + ".resync.tmp"
                with open(tmp_path, "w", encoding="utf-8") as handle:
                    handle.write("\n".join(lines) + ("\n" if lines else ""))
                    handle.flush()
                    os.fsync(handle.fileno())
                self._wal.close()
                try:
                    os.replace(tmp_path, path)
                finally:
                    self._wal = open(path, "a", encoding="utf-8")
            self._collections.clear()
            # The cleared collections' spill files are dead weight after
            # a resync (the rebuilt columns are resident); leaving the
            # folder mappings would also mis-route a NEW collection of
            # the same name into a folder full of stale files. rmtree
            # and forget them — every follower resync used to leak both.
            if self._spill_folders:
                import shutil

                for folder in self._spill_folders.values():
                    shutil.rmtree(folder, ignore_errors=True)
                self._spill_folders.clear()
            if self._wal_buffer is not None:
                self._wal_buffer[:] = list(lines)
            for line in lines:
                self._apply_record_locked(json.loads(line))

    def compact(self) -> bool:
        """Rewrite the WAL as a snapshot — WITHOUT stalling the world.
        Returns True when THIS call durably published a snapshot; False
        when it was skipped (another compaction in flight) or abandoned
        (a replication resync superseded the snapshot mid-write) —
        callers that need an on-return durability guarantee must check.

        The expensive work (serializing every block to base64 lines,
        writing + fsyncing the snapshot file) happens OUTSIDE the store
        lock against copy-on-write column snapshots; concurrent
        mutations keep flowing and are captured on a side log
        (``_compact_side``) that completes the snapshot before the
        atomic rename. The lock is held only for O(collections)
        snapshotting and list swaps — at 100M rows the old
        serialize-under-lock design was a multi-second outage for every
        reader and writer.

        Crash-safe: the snapshot is written to a temp file and
        ``os.replace``d over ``wal.jsonl`` only after its suffix is
        fsynced, so a failure at any point leaves the old log intact.
        Typed blocks serialize as base64 buffer records — null masks and
        missing-pad masks ride along explicitly (JSON has no
        missing/null distinction to round-trip).
        """
        # Phase A (locked, O(collections)): consistent snapshot views +
        # start capturing concurrent mutations.
        with self._lock:
            if self._wal is None and self._wal_buffer is None:
                return False
            if self._compact_side is not None:
                return False  # a compaction is already in flight
            views = {
                name: col.snapshot()
                for name, col in self._collections.items()
            }
            self._compact_side = []
            gen = self._compact_gen

        # Phase B (unlocked): the expensive serialization.
        try:
            body = [
                json.dumps(record)
                for record in self._snapshot_records_of(views)
            ]
        except BaseException:
            # Deliberate split-phase mutation of _compact_side: the
            # whole method is the generation-guarded compaction
            # protocol (phases A–E documented above), and every
            # re-acquisition re-checks _compact_gen before touching it.
            with self._lock:  # lo: allow[LO205]
                self._compact_side = None
            raise

        # Phase C (locked, O(1)-ish): freeze the new log identity. The
        # epoch record + body + captured suffix ARE the new log; the
        # in-memory feed switches now (followers on the old epoch
        # resync via wal_feed), while capture continues for the records
        # that land during the file write.
        with self._lock:
            if self._compact_gen != gen:
                return False  # a resync superseded this snapshot
            new_epoch = self._wal_epoch + 1
            lines = [json.dumps({"op": "epoch", "e": new_epoch})]
            lines.extend(body)
            lines.extend(self._compact_side)
            self._compact_side = []
            if self._wal_buffer is not None:
                self._wal_buffer[:] = lines
            self._wal_epoch = new_epoch
            if self._wal is None:
                self._compact_side = None
                return True
            path = self._wal.name

        # Phase D (unlocked): write + fsync the snapshot file.
        tmp_path = path + ".compact.tmp"
        try:
            with open(tmp_path, "w", encoding="utf-8") as handle:
                for line in lines:
                    handle.write(line + "\n")
                handle.flush()
                os.fsync(handle.fileno())  # data durable before rename

            # Phase E (locked): drain the last captured suffix into the
            # snapshot file, then atomically publish it.
            with self._lock:
                if self._compact_gen != gen:
                    # resync landed during the file write: ITS log is
                    # the truth now — discard this snapshot entirely
                    try:
                        os.remove(tmp_path)
                    except OSError:
                        pass
                    return False
                side = self._compact_side or []
                self._compact_side = None
                if side:
                    with open(tmp_path, "a", encoding="utf-8") as handle:
                        for line in side:
                            handle.write(line + "\n")
                        handle.flush()
                        os.fsync(handle.fileno())
                self._wal.close()
                try:
                    os.replace(tmp_path, path)
                    directory_fd = os.open(
                        os.path.dirname(path) or ".", os.O_RDONLY
                    )
                    try:
                        os.fsync(directory_fd)  # make the rename durable
                    finally:
                        os.close(directory_fd)
                finally:
                    # Reopen whichever file now lives at `path` so later
                    # writes never hit a closed handle.
                    self._wal = open(path, "a", encoding="utf-8")
        except BaseException:
            with self._lock:
                self._compact_side = None
            try:
                os.remove(tmp_path)
            except OSError:
                pass
            raise
        return True

    def _snapshot_records_of(
        self, collections: dict[str, "_Collection"]
    ) -> Iterator[dict]:
        """State as a minimal WAL record sequence — the body of a
        compacted log (and, under replication, of a new epoch)."""
        for name, col in collections.items():
            yield {"op": "create", "c": name}
            if col.block_columns:
                yield {
                    "op": "insert_cols_b",
                    "c": name,
                    "s": col.block_start,
                    "cols": {
                        field: column.to_json_record()
                        for field, column in col.block_columns.items()
                    },
                }
            if col.rows:
                yield {"op": "insert_many", "c": name, "d": list(col.rows.values())}

    # --- primitive ops (caller holds the lock; no logging) --------------------
    # The _locked suffix is the analyzer-checked contract (LO203,
    # docs/analysis.md): these touch guarded state and must only be
    # called with self._lock held.
    def _apply_insert_locked(self, collection: str, document: dict) -> None:
        col = self._collections.setdefault(collection, _Collection())
        doc_id = document.get(ROW_ID)
        if doc_id is None:
            doc_id = col.next_id()
            document = dict(document)
            document[ROW_ID] = doc_id
        if col.has_id(doc_id):
            raise KeyError(f"duplicate _id {doc_id!r} in {collection!r}")
        col.rows[doc_id] = dict(document)
        col.rev = next(self._rev_seq)

    def _apply_insert_columns_locked(
        self,
        collection: str,
        columns: dict[str, Column],
        start_id: int,
    ) -> None:
        col = self._collections.setdefault(collection, _Collection())
        col.append_columns(columns, start_id)
        col.rev = next(self._rev_seq)
        try:
            self._maybe_spill_locked()
        except OSError as error:
            self._disable_spill_locked(error)

    def _disable_spill_locked(self, error: OSError) -> None:
        """Spilling is an optimization; an unwritable/full spill disk
        must not fail the mutation that triggered it (the rows ARE
        applied, and the caller still writes the WAL record — aborting
        would leave memory ahead of the log). Disabled loudly so an
        operator can see why LO_SPILL_BYTES stopped being honored."""
        import sys

        print(
            f"store: spill failed, staying in RAM from here on: {error}",
            file=sys.stderr,
            flush=True,
        )
        self._spill_budget = 0.0  # stop retrying every batch

    # --- out-of-core spill ----------------------------------------------------
    def _ensure_spill_dir(self) -> str:
        if self._spill_dir is None:
            import atexit
            import shutil
            import tempfile

            self._spill_dir = tempfile.mkdtemp(prefix="lo_spill_")
            # a pure in-memory store's spill files have no meaning past
            # the process (durability is the WAL's job when configured)
            atexit.register(
                shutil.rmtree, self._spill_dir, ignore_errors=True
            )
        return self._spill_dir

    def _maybe_spill_locked(self) -> None:
        """Under the store lock: when anonymous-RAM column bytes exceed
        ``LO_SPILL_BYTES``, move the largest column payloads to
        disk-backed mappings (``Column.spill_to``) — the Mongo-owns-disk
        property (reference docker-compose.yml:335-340): the store's
        ceiling becomes disk, with RAM as a bounded working set. Spilled
        columns keep streaming appends straight to their files, so bulk
        ingestion past the budget never re-materializes them; point
        mutations copy back to RAM and the stale file is reclaimed when
        the collection drops.

        Runs on the writer's thread under the store lock: concurrent
        readers wait out the spill write like any other mutation
        (bounded by one pass over the columns being spilled; a
        copy-then-swap outside the lock, like compaction's, is the
        escalation path if that stall ever matters)."""
        if self._spill_budget <= 0:
            return
        import time

        candidates = []
        spilled_columns = []
        resident = 0
        for name, col in self._collections.items():
            for field, column in col.block_columns.items():
                bytes_here = column.resident_nbytes()
                resident += bytes_here
                if column.is_spilled():
                    spilled_columns.append(column)
                elif bytes_here >= _SPILL_MIN_COLUMN_BYTES:
                    candidates.append((bytes_here, name, field, column))
        # release already-spilled columns' resident mapped pages (they
        # stay in the page cache) so RSS tracks the budget, not the
        # bytes the last scan happened to touch — rate-limited: each
        # sweep evicts pages concurrent scans just faulted in
        now = time.monotonic()
        if spilled_columns and (
            now - getattr(self, "_last_advise", 0.0) >= _ADVISE_INTERVAL_S
        ):
            self._last_advise = now
            for column in spilled_columns:
                column.advise_cold()
        if resident <= self._spill_budget:
            return
        candidates.sort(key=lambda entry: -entry[0])
        for bytes_here, name, field, column in candidates:
            self._spill_seq += 1
            folder = self._spill_folders.setdefault(
                name,
                os.path.join(
                    self._ensure_spill_dir(),
                    f"{_path_safe(name)}.{len(self._spill_folders)}",
                ),
            )
            released = column.spill_to(
                folder, f"{_path_safe(field)}.{self._spill_seq}"
            )
            resident -= released
            # hysteresis: stop well under budget so the next batch does
            # not immediately re-trigger a scan-and-spill
            if resident <= self._spill_budget * 0.75:
                break

    def _apply_update_locked(self, collection: str, query: dict, new_values: dict) -> None:
        col = self._collections.get(collection)
        if col is None:
            return
        col.rev = next(self._rev_seq)
        if list(query.keys()) == [ROW_ID] and (
            _is_int_id(query[ROW_ID]) or isinstance(query[ROW_ID], str)
        ):  # the dominant fast path: literal-id lookup
            doc_id = query[ROW_ID]
            if col.in_block(doc_id):
                col.set_block_values(doc_id, new_values)
            elif doc_id in col.rows:
                col.rows[doc_id].update(new_values)
            return
        for doc_id in col.iter_ids():
            if matches(col.document(doc_id), query):
                if col.in_block(doc_id):
                    col.set_block_values(doc_id, new_values)
                else:
                    col.rows[doc_id].update(new_values)
                return

    def _apply_set_field_locked(
        self, collection: str, field: str, values_by_id: dict
    ) -> None:
        col = self._collections.get(collection)
        if col is None:
            return
        col.rev = next(self._rev_seq)
        ensured = False
        for doc_id, value in values_by_id.items():
            if col.in_block(doc_id):
                if not ensured:
                    col.ensure_block_field(field)
                    ensured = True
                column = col.block_columns[field]
                col.block_columns[field] = column.set(
                    doc_id - col.block_start, value
                )
            elif doc_id in col.rows:
                col.rows[doc_id][field] = value

    def _apply_set_column_locked(
        self, collection: str, field: str, values: Column, start_id: int
    ) -> None:
        col = self._collections.get(collection)
        if col is None:
            return
        col.rev = next(self._rev_seq)
        # Whole-block replace: one column swap, no per-id work.
        if (
            col.block_columns
            and start_id == col.block_start
            and len(values) == col.block_rows
        ):
            col.ensure_block_field(field)
            col.block_columns[field] = values
            try:
                # bulk casts land whole replacement columns: give the
                # spill budget a chance (and advise cold mappings) so a
                # 100M-row fieldtypes pass doesn't accumulate every
                # converted column in RAM
                self._maybe_spill_locked()
            except OSError as error:
                self._disable_spill_locked(error)
            return
        self._apply_set_field_locked(
            collection,
            field,
            {
                start_id + i: value
                for i, value in enumerate(values.tolist(pad_as_none=False))
            },
        )

    # --- DocumentStore implementation -----------------------------------------
    def telemetry_stats(self) -> dict:
        """Occupancy for /metrics (telemetry.register_store): collection
        count, on-disk WAL bytes, and bytes currently spilled to
        disk-backed mappings. File sizes are read at scrape time — cheap
        next to a scrape interval, and always truthful after compaction
        or resync rewrites."""
        with self._lock:
            collections = len(self._collections)
            wal = self._wal
            folders = list(self._spill_folders.values())
        wal_bytes = 0
        if wal is not None:
            try:
                wal_bytes = os.fstat(wal.fileno()).st_size
            except (OSError, ValueError):  # closed mid-resync
                pass
        spill_bytes = 0
        for folder in folders:
            try:
                with os.scandir(folder) as entries:
                    for entry in entries:
                        try:
                            spill_bytes += entry.stat().st_size
                        except OSError:
                            continue
            except OSError:
                continue
        return {
            "collections": collections,
            "wal_bytes": wal_bytes,
            "spill_bytes": spill_bytes,
        }

    def list_collections(self) -> list[str]:
        with self._lock:
            return list(self._collections.keys())

    def create_collection(self, collection: str) -> bool:
        with self._lock:
            if collection in self._collections:
                return False
            self._collections[collection] = _Collection()
            self._log_locked({"op": "create", "c": collection})
            return True

    def _drop_spill_folder_locked(self, collection: str) -> None:
        """Reclaim a collection's spill files; memmaps still held by
        snapshots keep reads valid (POSIX unlink semantics) until the
        last reference dies."""
        folder = self._spill_folders.pop(collection, None)
        if folder is not None:
            import shutil

            shutil.rmtree(folder, ignore_errors=True)

    def drop(self, collection: str) -> None:
        with self._lock:
            self._collections.pop(collection, None)
            self._log_locked({"op": "drop", "c": collection})
            self._drop_spill_folder_locked(collection)

    def _apply_trim_locked(self, collection: str, max_docs: int) -> int:
        col = self._collections.get(collection)
        if col is None:
            return 0
        data_ids = sorted(
            doc_id
            for doc_id in col.rows
            if doc_id != METADATA_ID and _is_int_id(doc_id)
        )
        excess = len(data_ids) - max_docs
        if excess <= 0:
            return 0
        for doc_id in data_ids[:excess]:
            del col.rows[doc_id]
        col.rev = next(self._rev_seq)
        return excess

    def trim_collection(self, collection: str, max_docs: int) -> int:
        if isinstance(max_docs, bool) or not isinstance(max_docs, int):
            raise ValueError(f"max_docs must be an integer, got {max_docs!r}")
        if max_docs < 0:
            raise ValueError(f"max_docs must be >= 0, got {max_docs}")
        with self._lock:
            removed = self._apply_trim_locked(collection, max_docs)
            if removed:
                # The WAL logs the CAP, not the removed ids: replay and
                # follower replication re-derive the same eviction from
                # the same state (oldest-first is deterministic).
                self._log_locked(
                    {"op": "trim", "c": collection, "n": max_docs}
                )
            return removed

    def insert_one(self, collection: str, document: dict) -> None:
        with self._lock:
            self._apply_insert_locked(collection, document)
            self._log_locked({"op": "insert", "c": collection, "d": document})

    def insert_many(self, collection: str, documents: list[dict]) -> None:
        with self._lock:
            # Validate the whole batch before applying anything so a
            # duplicate-_id failure can't leave the in-memory state and
            # the WAL divergent (all-or-nothing).
            col = self._collections.get(collection) or _Collection()
            seen: set = set()
            for document in documents:
                doc_id = document.get(ROW_ID)
                if doc_id is None:
                    continue  # auto-assigned at apply time, cannot collide
                if col.has_id(doc_id) or doc_id in seen:
                    raise KeyError(f"duplicate _id {doc_id!r} in {collection!r}")
                seen.add(doc_id)
            for document in documents:
                self._apply_insert_locked(collection, document)
            self._log_locked({"op": "insert_many", "c": collection, "d": documents})

    def insert_columns(
        self,
        collection: str,
        columns: dict[str, ColumnInput],
        start_id: Optional[int] = None,
    ) -> None:
        if ROW_ID in columns:
            raise ValueError("_id is implicit in insert_columns (start_id..)")
        typed = {name: as_column(values) for name, values in columns.items()}
        lengths = {len(values) for values in typed.values()}
        if len(lengths) > 1:
            raise ValueError("ragged columns")
        with self._lock:
            col = self._collections.get(collection) or _Collection()
            if start_id is None:
                start_id = col.block_stop if col.block_columns else 1
            # append_columns validates contiguity + overlay collisions
            self._apply_insert_columns_locked(collection, typed, start_id)
            if self._wal_enabled_locked():  # base64 encode only when a log exists
                self._log_locked(
                    {
                        "op": "insert_cols_b",
                        "c": collection,
                        "s": start_id,
                        "cols": {
                            field: column.to_json_record()
                            for field, column in typed.items()
                        },
                    }
                )

    def insert_column_arrays(
        self,
        collection: str,
        columns: dict[str, Column],
        start_id: Optional[int] = None,
    ) -> None:
        self.insert_columns(collection, columns, start_id=start_id)

    def update_one(self, collection: str, query: dict, new_values: dict) -> None:
        with self._lock:
            self._apply_update_locked(collection, query, new_values)
            self._log_locked({"op": "update", "c": collection, "q": query, "v": new_values})

    def set_field_values(
        self, collection: str, field: str, values_by_id: dict
    ) -> None:
        with self._lock:
            self._apply_set_field_locked(collection, field, values_by_id)
            self._log_locked(
                {
                    "op": "set_field",
                    "c": collection,
                    "f": field,
                    "d": list(values_by_id.items()),
                }
            )

    def set_column(
        self,
        collection: str,
        field: str,
        values: ColumnInput,
        start_id: int = 1,
    ) -> None:
        typed = as_column(values)
        with self._lock:
            self._apply_set_column_locked(collection, field, typed, start_id)
            if self._wal_enabled_locked():
                self._log_locked(
                    {
                        "op": "set_col_b",
                        "c": collection,
                        "f": field,
                        "s": start_id,
                        "col": typed.to_json_record(),
                    }
                )

    def find(
        self,
        collection: str,
        query: Optional[dict] = None,
        skip: int = 0,
        limit: Optional[int] = None,
    ) -> Iterator[dict]:
        query = query or {}
        with self._lock:
            col = self._collections.get(collection)
            if col is None:
                return iter(())
            # Literal-id point lookup (the poll loop's shape: metadata
            # reads every few seconds) — synthesize ONE document under
            # the lock, no snapshot of the whole collection.
            if (
                list(query.keys()) == [ROW_ID]
                and not isinstance(query[ROW_ID], dict)
                and skip == 0
            ):
                doc_id = query[ROW_ID]
                if col.has_id(doc_id):
                    document = col.document(doc_id)
                    return iter(() if limit == 0 else (document,))
                return iter(())
            # Snapshot under the lock (cheap: copy-on-write columns,
            # copied overlay dicts), synthesize row dicts outside it —
            # an unlimited find over a large block never holds the store
            # lock for O(rows) dict building.
            view = col.snapshot()

        def generate() -> Iterator[dict]:
            produced = 0
            skipped = 0
            for doc_id in view.iter_ids():
                document = view.document(doc_id)
                if not matches(document, query):
                    continue
                if skipped < skip:
                    skipped += 1
                    continue
                if limit is not None and produced >= limit:
                    return
                produced += 1
                yield document

        return generate()

    def count(self, collection: str) -> int:
        with self._lock:
            col = self._collections.get(collection)
            if col is None:
                return 0
            return col.block_rows + len(col.rows)

    def collection_rev(self, collection: str) -> int:
        """Mutation counter for torn-read detection on paged wire reads."""
        with self._lock:
            col = self._collections.get(collection)
            return -1 if col is None else col.rev

    def collection_block_rows(self, collection: str) -> int:
        with self._lock:
            col = self._collections.get(collection)
            return -1 if col is None else col.block_rows

    def aggregate(self, collection: str, pipeline: list[dict]) -> list[dict]:
        # Columnar fast path: the histogram's value-count $group runs
        # straight over the typed block column — np.unique / Counter in
        # C, no row synthesis (the on-store analogue of the reference's
        # Mongo-server $group pushdown, histogram.py:63-69).
        with self._lock:
            col = self._collections.get(collection)
            if (
                col is not None
                and len(pipeline) == 1
                and "$group" in pipeline[0]
                and not col.overlay_data_ids()
            ):
                key_expr = pipeline[0]["$group"].get("_id")
                if isinstance(key_expr, str) and key_expr.startswith("$"):
                    field = key_expr[1:]
                    if field == ROW_ID:
                        return [
                            {"_id": doc_id, "count": 1}
                            for doc_id in range(col.block_start, col.block_stop)
                        ]
                    column = col.block_columns.get(field)
                    if column is None:
                        return (
                            [{"_id": None, "count": col.block_rows}]
                            if col.block_rows
                            else []
                        )
                    column = column.snapshot()
                else:
                    column = None
            else:
                column = None
        if column is not None:
            return column.unique_counts()
        results: list[dict] = list(self.find(collection))
        for stage in pipeline:
            if "$match" in stage:
                results = [doc for doc in results if matches(doc, stage["$match"])]
            elif "$group" in stage:
                group = stage["$group"]
                key_expr = group.get("_id")
                if not (isinstance(key_expr, str) and key_expr.startswith("$")):
                    raise NotImplementedError(f"unsupported $group key {key_expr!r}")
                results = _group_count(iter(results), key_expr[1:])
            else:
                raise NotImplementedError(f"unsupported pipeline stage {stage}")
        return results

    def read_columns(
        self,
        collection: str,
        fields: Optional[list[str]] = None,
        start: int = 0,
        limit: Optional[int] = None,
    ) -> dict[str, list]:
        arrays = self.read_column_arrays(collection, fields, start, limit)
        return {name: column.tolist() for name, column in arrays.items()}

    def read_column_arrays(
        self,
        collection: str,
        fields: Optional[list[str]] = None,
        start: int = 0,
        limit: Optional[int] = None,
    ) -> dict[str, Column]:
        return self.read_column_arrays_rev(collection, fields, start, limit)[0]

    def read_column_arrays_rev(
        self,
        collection: str,
        fields: Optional[list[str]] = None,
        start: int = 0,
        limit: Optional[int] = None,
    ) -> tuple[dict[str, Column], int]:
        """``(columns, rev)`` with the rev captured under the SAME lock
        acquisition as the read — a write can never land between the
        data and its reported rev, so equal revs across paged chunks
        prove no tear."""
        with self._lock:
            col = self._collections.get(collection)
            if col is None:
                return (
                    {field: Column() for field in fields} if fields else {}
                ), -1
            rev = col.rev
            if not col.overlay_data_ids():
                # Pure-block dataset: hand back copy-on-write column
                # slices directly — a paged read costs O(chunk) for
                # strings and O(1) for numeric kinds, never O(rows).
                stop = (
                    col.block_rows
                    if limit is None
                    else min(start + limit, col.block_rows)
                )
                names = fields if fields is not None else list(col.block_fields)
                out: dict[str, Column] = {}
                for name in names:
                    if name == ROW_ID:
                        out[name] = Column.from_numpy(
                            np.arange(
                                col.block_start + start,
                                col.block_start + stop,
                                dtype=np.int64,
                            )
                        )
                    elif name in col.block_columns:
                        out[name] = col.block_columns[name].slice(start, stop)
                    else:
                        pads = Column(
                            "empty"
                        )
                        pads.size = max(stop - start, 0)
                        pads.data = np.zeros(pads.size, dtype=np.uint8)
                        if pads.size:
                            pads.none = np.ones(pads.size, dtype=bool)
                        out[name] = pads
                return out, rev
            # Mixed block + overlay rows: page over the merged id order,
            # synthesizing row dicts ONLY for the requested slice — a
            # paged read costs O(ids + chunk), never O(rows) dict
            # synthesis per chunk (the wire loop would otherwise go
            # quadratic on a block dataset with one stray overlay row).
            view = col.snapshot()
        data_ids = [
            doc_id for doc_id in view.iter_ids() if doc_id != METADATA_ID
        ]
        if fields is None:
            names = [f for f in view.block_fields if f != ROW_ID]
            seen = set(names)
            for doc_id in data_ids:
                if doc_id in view.rows:
                    for key in view.rows[doc_id]:
                        if key != ROW_ID and key not in seen:
                            seen.add(key)
                            names.append(key)
            fields = names
        stop_index = None if limit is None else start + limit
        lists: dict[str, list] = {field: [] for field in fields}
        for doc_id in data_ids[start:stop_index]:
            document = view.document(doc_id)
            for field in fields:
                lists[field].append(document.get(field))
        return {
            field: Column.from_values(values) for field, values in lists.items()
        }, rev


def _legacy_columns(
    raw: dict[str, list], missing: Optional[dict]
) -> dict[str, Column]:
    """Decode a legacy list-form ``insert_cols`` WAL record (with its
    optional missing-index mask) into typed columns."""
    out: dict[str, Column] = {}
    for field, values in raw.items():
        indices = set((missing or {}).get(field, ()))
        if indices:
            values = [
                _MISSING if i in indices else v for i, v in enumerate(values)
            ]
        out[field] = Column.from_values(values)
    return out


_GLOBAL_STORE: Optional[InMemoryStore] = None
_GLOBAL_LOCK = threading.Lock()


def global_store() -> InMemoryStore:
    """Process-wide shared store (single-process deployments and tests)."""
    global _GLOBAL_STORE
    with _GLOBAL_LOCK:
        if _GLOBAL_STORE is None:
            _GLOBAL_STORE = InMemoryStore()
        return _GLOBAL_STORE


def reset_global_store() -> None:
    global _GLOBAL_STORE
    with _GLOBAL_LOCK:
        _GLOBAL_STORE = None
