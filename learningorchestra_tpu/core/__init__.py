"""Core: document store, columnar tables, job management, CSV ingestion."""

from learningorchestra_tpu.core.store import (  # noqa: F401
    METADATA_ID,
    ROW_ID,
    DocumentStore,
    InMemoryStore,
    global_store,
)
