"""Asynchronous job management with explicit states.

The reference's only job abstraction is the ``finished`` boolean on a
dataset's metadata document: a service writes ``finished: false``, does
work on daemon threads, and flips it to ``true``; a crashed job leaves
``finished: false`` forever and clients poll indefinitely (reference:
microservices/database_api_image/database.py:199-216,
learning_orchestra_client/__init__.py:24-32).

This JobManager keeps that wire contract (so unchanged clients still
poll ``finished``) but adds real states — PENDING/RUNNING/FINISHED/
FAILED with an error payload and timings — and, on failure, *still*
flips ``finished`` on the tracked dataset so pollers terminate, while
recording the error in the metadata document.
"""

from __future__ import annotations

import threading
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Callable, Optional

from learningorchestra_tpu.core.store import METADATA_ID, ROW_ID, DocumentStore

PENDING = "pending"
RUNNING = "running"
FINISHED = "finished"
FAILED = "failed"


@dataclass
class JobRecord:
    name: str
    state: str = PENDING
    error: Optional[str] = None
    submitted_at: float = field(default_factory=time.time)
    started_at: Optional[float] = None
    ended_at: Optional[float] = None

    def as_dict(self) -> dict:
        return {
            "name": self.name,
            "state": self.state,
            "error": self.error,
            "submitted_at": self.submitted_at,
            "started_at": self.started_at,
            "ended_at": self.ended_at,
        }


class JobManager:
    def __init__(self, max_workers: int = 8):
        self._pool = ThreadPoolExecutor(max_workers=max_workers)
        self._jobs: dict[str, JobRecord] = {}
        self._lock = threading.Lock()
        self._events: dict[str, threading.Event] = {}

    def submit(
        self,
        name: str,
        fn: Callable,
        *args,
        store: Optional[DocumentStore] = None,
        collection: Optional[str] = None,
        **kwargs,
    ) -> JobRecord:
        """Run ``fn`` on the pool. If ``store``/``collection`` are given,
        a failure marks that dataset's metadata ``finished: true`` with an
        ``error`` field so pollers terminate instead of hanging."""
        record = JobRecord(name=name)
        with self._lock:
            existing = self._jobs.get(name)
            if existing is not None and existing.state in (PENDING, RUNNING):
                raise ValueError(f"job {name!r} is already {existing.state}")
            self._jobs[name] = record
            done = threading.Event()
            self._events[name] = done

        def run():
            record.state = RUNNING
            record.started_at = time.time()
            try:
                fn(*args, **kwargs)
                record.state = FINISHED
            except Exception as error:
                record.state = FAILED
                record.error = f"{type(error).__name__}: {error}"
                traceback.print_exc()
                if store is not None and collection is not None:
                    store.update_one(
                        collection,
                        {ROW_ID: METADATA_ID},
                        {"finished": True, "error": record.error},
                    )
            finally:
                record.ended_at = time.time()
                done.set()

        self._pool.submit(run)
        return record

    def get(self, name: str) -> Optional[JobRecord]:
        with self._lock:
            return self._jobs.get(name)

    def wait(self, name: str, timeout: Optional[float] = None) -> JobRecord:
        event = self._events.get(name)
        if event is None:
            raise KeyError(f"unknown job {name!r}")
        if not event.wait(timeout):
            raise TimeoutError(f"job {name!r} still {self._jobs[name].state}")
        return self._jobs[name]

    def all_jobs(self) -> list[dict]:
        with self._lock:
            return [record.as_dict() for record in self._jobs.values()]


_MANAGER: Optional[JobManager] = None
_MANAGER_LOCK = threading.Lock()


def global_job_manager() -> JobManager:
    global _MANAGER
    with _MANAGER_LOCK:
        if _MANAGER is None:
            _MANAGER = JobManager()
        return _MANAGER
