"""Asynchronous job management with explicit states.

The reference's only job abstraction is the ``finished`` boolean on a
dataset's metadata document: a service writes ``finished: false``, does
work on daemon threads, and flips it to ``true``; a crashed job leaves
``finished: false`` forever and clients poll indefinitely (reference:
microservices/database_api_image/database.py:199-216,
learning_orchestra_client/__init__.py:24-32).

This JobManager keeps that wire contract (so unchanged clients still
poll ``finished``) but adds real states — PENDING/RUNNING/FINISHED/
FAILED/CANCELLED with an error payload and timings — and, on terminal
failure, *still* flips ``finished`` on the tracked dataset so pollers
terminate, while recording the error in the metadata document.

Since the scheduler subsystem (learningorchestra_tpu/sched/) the
manager no longer owns a thread pool: :meth:`JobManager.submit` admits
work into a class-aware priority queue (device-bound jobs serialize so
SPMD dispatches never contend for the mesh; host-bound jobs run at
``LO_JOB_WORKERS``) and this module executes what the scheduler admits —
including transient-failure retries with seeded backoff, per-job
deadlines, cooperative cancellation (``DELETE /jobs/<name>``), and a
durable journal the next process replays after a crash.
"""

from __future__ import annotations

import contextvars
import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Callable, Optional

from learningorchestra_tpu.core.store import METADATA_ID, ROW_ID, DocumentStore
from learningorchestra_tpu.sched import cancel as _cancel
from learningorchestra_tpu.sched import config as _config
from learningorchestra_tpu.sched import policy as _policy
from learningorchestra_tpu.sched.cancel import (
    CancelToken,
    JobCancelledError,
    JobTimeoutError,
)
from learningorchestra_tpu.sched.scheduler import (
    HOST_CLASS,
    QueueFullError,
    Scheduler,
    Task,
)
from learningorchestra_tpu.telemetry import metrics as _metrics
from learningorchestra_tpu.telemetry import tracing as _tracing

PENDING = "pending"
RUNNING = "running"
FINISHED = "finished"
FAILED = "failed"
CANCELLED = "cancelled"

TERMINAL_STATES = (FINISHED, FAILED, CANCELLED)


class DuplicateJobError(ValueError):
    """The job name is already PENDING/RUNNING. A ValueError subclass so
    existing ``except ValueError`` duplicate handling keeps working —
    but catchable specifically, which matters for callers whose job
    function can itself raise ValueError (the sync model build must not
    mistake a failed build for "already active" and run it twice)."""


@dataclass
class JobRecord:
    name: str
    state: str = PENDING
    error: Optional[str] = None
    submitted_at: float = field(default_factory=time.time)
    started_at: Optional[float] = None
    ended_at: Optional[float] = None
    job_class: str = HOST_CLASS
    priority: int = 0
    # attempts completed or underway; 0 until first execution starts
    attempts: int = 0
    # The request's correlation ID and span tree: submit() binds the
    # job to a Trace carrying the submitting request's ID, the worker
    # opens the root span, and everything the work emits (PhaseTimer
    # phases, SPMD dispatch spans) nests under it — served by
    # GET /jobs/<name>/trace (utils/web.register_job_routes).
    trace: Optional[_tracing.Trace] = None
    # the terminal exception object, re-raised by run_sync so the
    # synchronous REST surface keeps reference-parity 500 bodies
    exception: Optional[BaseException] = field(default=None, repr=False)
    # journal this job's lifecycle? Ephemeral synchronous work (no
    # replay op, no tracked collection, a waiter who sees the failure
    # directly) skips the journal: 3+ store writes per request with
    # zero recovery value would grow __lo_jobs__ for nothing.
    journaled: bool = field(default=True, repr=False)
    # the dataset this job materialises (the filename clients know).
    # GET /jobs/<name>/wait resolves a bare filename through this, so
    # a client that only knows "titanic" finds "ingest:titanic".
    collection: Optional[str] = None
    # structured per-job detail the work itself attaches while running
    # (JobHandle.annotate) — e.g. a multi-classifier build's per-name
    # outcome map when one member fails (``finished_partial``). Rides
    # as_dict, so GET /jobs/<name> and the /wait terminal body surface
    # it without route changes.
    detail: Optional[dict] = None

    @property
    def correlation_id(self) -> Optional[str]:
        return self.trace.correlation_id if self.trace is not None else None

    def as_dict(self) -> dict:
        return {
            "name": self.name,
            "state": self.state,
            "error": self.error,
            "submitted_at": self.submitted_at,
            "started_at": self.started_at,
            "ended_at": self.ended_at,
            "job_class": self.job_class,
            "priority": self.priority,
            "attempts": self.attempts,
            "correlation_id": self.correlation_id,
            "collection": self.collection,
            "detail": self.detail,
        }

    def trace_dict(self) -> dict:
        out = self.as_dict()
        out["trace"] = self.trace.as_dict() if self.trace is not None else None
        return out


class JobHandle:
    """The running job's back-channel to its own record and journal.

    Bound by the worker around ``fn`` (:func:`current_job_handle`), so
    deep work — the model builder, several layers below the JobManager —
    can attach structured detail and journal ``progress`` events without
    threading the manager through every signature. NOTE: contextvars do
    not cross thread-pool boundaries; work that fans out (the builder's
    per-classifier pool) must capture the handle once at entry and pass
    it explicitly.
    """

    def __init__(self, manager: "JobManager", record: JobRecord):
        self._manager = manager
        self._record = record

    @property
    def name(self) -> str:
        return self._record.name

    def annotate(self, **detail) -> None:
        """Merge fields into the record's ``detail`` dict (whole-dict
        replace, so a concurrent as_dict never sees a half-written
        map)."""
        merged = dict(self._record.detail or {})
        merged.update(detail)
        self._record.detail = merged

    def progress(self, **fields) -> None:
        """Append a durable ``progress`` event to the job journal —
        best-effort, like every journal write; recovery folds these
        into the resume payload for an orphaned RUNNING job."""
        self._manager._journal_event(self._record, "progress", **fields)


_JOB_HANDLE: contextvars.ContextVar[Optional[JobHandle]] = (
    contextvars.ContextVar("lo_job_handle", default=None)
)


def current_job_handle() -> Optional[JobHandle]:
    """The JobHandle of the job running on this thread, or None for
    work executed outside the JobManager (library use, tests)."""
    return _JOB_HANDLE.get()


class JobManager:
    """Tracked execution of what the scheduler admits.

    ``scheduler`` may be shared across services (the runner shares one
    so the device class serializes process-wide); by default each
    manager owns a private one sized from the env knobs.
    ``max_workers`` keeps the old constructor signature working and
    overrides the host-class width.
    """

    def __init__(
        self,
        max_workers: Optional[int] = None,
        scheduler: Optional[Scheduler] = None,
    ):
        self._scheduler = scheduler or Scheduler(host_width=max_workers)
        self._jobs: dict[str, JobRecord] = {}
        self._lock = threading.Lock()
        self._events: dict[str, threading.Event] = {}
        self._tasks: dict[str, Task] = {}
        # push-notification hooks: GET /jobs/<name>/wait parks a waiter
        # and registers its notify here; _finalize fires them after the
        # record goes terminal (utils/webloop.Waiter)
        self._done_callbacks: dict[str, list[Callable[[], None]]] = {}
        self._max_history = _config.job_history()
        self._ttl_s = _config.job_ttl_s()
        self._retry_budget = _config.retry_budget()
        self._default_timeout_s = _config.default_timeout_s()
        registry = _metrics.global_registry()
        self._jobs_total = registry.counter(
            "lo_jobs_total",
            "Jobs reaching a terminal state",
            labels=("state",),
        )
        self._jobs_running = registry.gauge(
            "lo_jobs_running", "Jobs currently executing"
        )
        self._job_seconds = registry.histogram(
            "lo_job_duration_seconds", "Job wall-clock, submit to done"
        )
        self._cancelled_total = registry.counter(
            "lo_sched_cancelled_total",
            "Jobs cancelled via DELETE /jobs/<name>",
        )

    @property
    def scheduler(self) -> Scheduler:
        return self._scheduler

    @property
    def journal(self):
        return self._scheduler.journal

    def submit(
        self,
        name: str,
        fn: Callable,
        *args,
        store: Optional[DocumentStore] = None,
        collection: Optional[str] = None,
        job_class: str = HOST_CLASS,
        priority: int = 0,
        timeout: Optional[float] = None,
        replay: Optional[tuple[str, dict]] = None,
        token: Optional[CancelToken] = None,
        **kwargs,
    ) -> JobRecord:
        """Admit ``fn`` into ``job_class``'s queue. If ``store``/
        ``collection`` are given, a terminal failure marks that
        dataset's metadata ``finished: true`` with an ``error`` field so
        pollers terminate instead of hanging. ``replay=(op, payload)``
        journals enough lineage for a restarted process to re-enqueue
        the job if it never started (sched/recovery.py). ``token``
        injects a caller-held :class:`CancelToken` — the coalescing
        stage needs the token visible on the member BEFORE the task
        exists, so a leader can mask a cancelled member out of its
        fused dispatch (sched/coalesce.py).

        Raises :class:`DuplicateJobError` if ``name`` is active and
        :class:`QueueFullError` (→ HTTP 429) at the class's queue cap.
        """
        record, _ = self._submit(
            name,
            fn,
            args,
            kwargs,
            store,
            collection,
            job_class,
            priority,
            timeout,
            replay,
            token=token,
        )
        return record

    def _submit(
        self,
        name: str,
        fn: Callable,
        args: tuple,
        kwargs: dict,
        store: Optional[DocumentStore],
        collection: Optional[str],
        job_class: str,
        priority: int,
        timeout: Optional[float],
        replay: Optional[tuple[str, dict]],
        keep_exception: bool = False,
        journaled: bool = True,
        token: Optional[CancelToken] = None,
    ) -> tuple[JobRecord, threading.Event]:
        # Cheap rejection first: a flood past the cap must not pay the
        # journal's store writes per rejected request (enqueue below
        # still closes the admit race authoritatively).
        self._scheduler.check_admission(job_class)
        if timeout is None:
            timeout = self._default_timeout_s
        if token is None:
            token = CancelToken(
                deadline=time.monotonic() + timeout if timeout else None
            )
        elif token.deadline is None and timeout:
            token.deadline = time.monotonic() + timeout
        op, payload = replay if replay is not None else (None, None)
        record = JobRecord(
            name=name,
            job_class=job_class,
            priority=priority,
            journaled=journaled,
            collection=collection,
            trace=_tracing.Trace(
                # a job submitted from a REST handler inherits the
                # request's correlation ID; elsewhere a fresh one
                _tracing.current_correlation_id(),
                name=name,
            ),
        )
        done = threading.Event()

        def run(task: Task) -> Optional[float]:
            return self._execute(
                task,
                record,
                done,
                fn,
                args,
                kwargs,
                store,
                collection,
                keep_exception,
            )

        task = Task(name, job_class, priority, run, token=token)
        # record, event, and task publish atomically: a cancel() that
        # sees the record must also see the task, or its 202 would
        # acknowledge a cancellation that never flips the token
        with self._lock:
            existing = self._jobs.get(name)
            if existing is not None and existing.state not in TERMINAL_STATES:
                raise DuplicateJobError(
                    f"job {name!r} is already {existing.state}"
                )
            self._evict_locked()
            self._jobs[name] = record
            self._events[name] = done
            self._tasks[name] = task
        self._journal_event(
            record,
            "submitted",
            job_class=job_class,
            priority=priority,
            op=op,
            payload=payload,
            collection=collection,
            cid=record.correlation_id,
        )
        try:
            self._scheduler.enqueue(task)
        except QueueFullError:
            self._journal_event(record, "rejected")
            # Deliberate two-phase publish (register → enqueue →
            # rollback on rejection): the identity check makes the
            # rollback surgical, and the worst interleaving is a
            # cancel() 202-ing a job that was never admitted — the
            # journal's "rejected" event is the durable truth.
            with self._lock:  # lo: allow[LO205]
                if self._jobs.get(name) is record:
                    del self._jobs[name]
                    self._events.pop(name, None)
                    self._tasks.pop(name, None)
                    # waiters that raced in hit their timeout and re-poll
                    self._done_callbacks.pop(name, None)
            raise
        return record, done

    def run_sync(
        self,
        name: str,
        fn: Callable,
        *args,
        store: Optional[DocumentStore] = None,
        collection: Optional[str] = None,
        job_class: str = HOST_CLASS,
        priority: int = 0,
        timeout: Optional[float] = None,
        replay: Optional[tuple[str, dict]] = None,
        token: Optional[CancelToken] = None,
        **kwargs,
    ) -> JobRecord:
        """Submit and block until terminal; re-raise the job's own
        exception. The synchronous REST routes (projection, histogram,
        fieldtypes, embeddings, the reference-parity sync model build)
        run through this so they get admission control and device-class
        serialization while keeping their blocking contract — the
        request thread waits, a scheduler worker executes."""
        record, done = self._submit(
            name,
            fn,
            args,
            kwargs,
            store,
            collection,
            job_class,
            priority,
            timeout,
            replay,
            keep_exception=True,
            token=token,
            # the caller waits and sees the failure directly; without a
            # replay op or a polled collection the journal could only
            # ever mark this 'orphaned' at restart — skip the writes
            journaled=replay is not None or collection is not None,
        )
        # the event captured at registration, NOT re-read by name: a
        # terminal job's name is reusable, and a lookup could pair this
        # record with a successor's still-unset event
        done.wait()
        if record.state != FINISHED:
            # detach before re-raising: the record outlives this
            # request by up to LO_JOB_TTL_S, and the traceback would
            # pin every frame of the failed job (feature matrices,
            # device buffers) for that whole window
            error = record.exception
            record.exception = None
            if error is not None:
                raise error
            raise RuntimeError(record.error or f"job {name!r} {record.state}")
        return record

    def _evict_locked(self) -> None:
        """Bound the record map: terminal records expire by TTL and by
        max-count (oldest-ended first). Terminal-state counters are
        monotonic regardless (they incremented at finalize), and
        ``/jobs`` simply stops listing evicted history. Active jobs are
        never evicted."""
        now = time.time()
        expired = [
            name
            for name, record in self._jobs.items()
            if record.state in TERMINAL_STATES
            and record.ended_at is not None
            and now - record.ended_at > self._ttl_s
        ]
        overflow = len(self._jobs) - len(expired) + 1 - self._max_history
        if overflow > 0:
            survivors = sorted(
                (
                    (record.ended_at or 0.0, name)
                    for name, record in self._jobs.items()
                    if record.state in TERMINAL_STATES and name not in expired
                ),
            )
            expired.extend(name for _, name in survivors[:overflow])
        for name in expired:
            del self._jobs[name]
            self._events.pop(name, None)
            self._tasks.pop(name, None)
            self._done_callbacks.pop(name, None)

    def _journal_event(self, record: JobRecord, event: str, **fields) -> None:
        journal = self._scheduler.journal
        if journal is not None and record.journaled:
            journal.append(record.name, event, **fields)

    def _execute(
        self,
        task: Task,
        record: JobRecord,
        done: threading.Event,
        fn: Callable,
        args: tuple,
        kwargs: dict,
        store: Optional[DocumentStore],
        collection: Optional[str],
        keep_exception: bool = False,
    ) -> Optional[float]:
        """Run one admitted attempt on the scheduler worker. Returns a
        backoff delay to retry a transient failure, or None when the
        record reached a terminal state. ``keep_exception`` parks the
        terminal exception on the record for run_sync to re-raise;
        async jobs skip it so a failed build cannot pin its frames
        (feature matrices, device buffers) until record eviction."""
        def finalize_interrupted(error: JobCancelledError) -> None:
            """One terminal path for deadline/cancel, before OR during
            execution: timeout → FAILED (the job did not do what was
            asked), explicit cancel → CANCELLED."""
            if isinstance(error, JobTimeoutError):
                self._finalize(
                    record,
                    done,
                    FAILED,
                    f"JobTimeoutError: {error}",
                    error,
                    store,
                    collection,
                    keep_exception,
                    task=task,
                )
            else:
                self._finalize(
                    record,
                    done,
                    CANCELLED,
                    f"JobCancelledError: {error}",
                    error,
                    store,
                    collection,
                    keep_exception,
                    task=task,
                )
                self._cancelled_total.inc()

        try:
            # expired or cancelled while QUEUED: terminal without ever
            # journaling "started" or counting an attempt
            task.token.check()
        except JobCancelledError as interruption:  # incl. JobTimeoutError
            finalize_interrupted(interruption)
            return None
        record.state = RUNNING
        record.started_at = record.started_at or time.time()
        record.attempts = task.attempt
        self._jobs_running.inc()
        self._journal_event(record, "started", attempt=task.attempt)
        error: Optional[BaseException] = None
        handle_token = _JOB_HANDLE.set(JobHandle(self, record))
        try:
            with _cancel.bind(task.token), _tracing.activate(
                record.trace
            ), _tracing.span(
                f"job:{record.name}",
                job_class=task.job_class,
                attempt=task.attempt,
                queue_wait_s=round(task.wait_s, 6),
            ):
                fn(*args, **kwargs)
        except BaseException as caught:  # noqa: BLE001 — classified below
            error = caught
        finally:
            _JOB_HANDLE.reset(handle_token)
            self._jobs_running.dec()
        if error is None:
            self._finalize(
                record, done, FINISHED, None, None, store, collection, False,
                task=task,
            )
            return None
        if isinstance(error, JobCancelledError):  # incl. JobTimeoutError
            finalize_interrupted(error)
            return None
        if (
            _policy.is_transient(error)
            and task.attempt < self._retry_budget
            and not task.token.cancelled
        ):
            delay = _policy.backoff_delay(record.name, task.attempt)
            record.state = PENDING
            record.error = (
                f"{type(error).__name__}: {error} "
                f"(attempt {task.attempt}/{self._retry_budget}, "
                f"retrying in {delay:.2f}s)"
            )
            self._journal_event(
                record,
                "retry",
                attempt=task.attempt,
                delay_s=round(delay, 3),
                error=record.error,
            )
            return delay
        traceback.print_exception(type(error), error, error.__traceback__)
        self._finalize(
            record,
            done,
            FAILED,
            f"{type(error).__name__}: {error}",
            error,
            store,
            collection,
            keep_exception,
            task=task,
        )
        return None

    def _finalize(
        self,
        record: JobRecord,
        done: threading.Event,
        state: str,
        error: Optional[str],
        exception: Optional[BaseException],
        store: Optional[DocumentStore],
        collection: Optional[str],
        keep_exception: bool = False,
        task: "Optional[Task]" = None,
    ) -> None:
        try:
            record.state = state
            record.error = error
            record.exception = exception if keep_exception else None
            record.ended_at = time.time()
            started = record.started_at or record.submitted_at
            self._jobs_total.labels(state).inc()
            self._job_seconds.observe(record.ended_at - started)
            if (
                state in (FAILED, CANCELLED)
                and store is not None
                and collection is not None
            ):
                # the reference's hang: a dead job leaving finished:
                # false forever — every terminal non-success flips the
                # flag. Best-effort: a store that is down mid-failover
                # must not stop the record from finalizing.
                try:
                    store.update_one(
                        collection,
                        {ROW_ID: METADATA_ID},
                        {"finished": True, "error": error},
                    )
                except Exception:  # noqa: BLE001
                    traceback.print_exc()
            self._journal_event(record, state, error=error)
            if record.trace is not None:
                # cross-process stitching: the job's span tree (cid
                # inherited from the submitting request) joins the
                # export buffer at terminal state, where it is complete
                _tracing.export_trace(record.trace, service="jobs")
            with self._lock:
                # identity check: after record.state went terminal a
                # same-name successor may have registered its own task,
                # and popping THAT would turn its DELETE into a no-op
                if task is not None and self._tasks.get(record.name) is task:
                    self._tasks.pop(record.name)
        finally:
            # waiters MUST wake no matter what failed above — a hung
            # done event is this subsystem's cardinal sin
            done.set()
            # Push hooks fire AFTER the terminal state is visible. Pop
            # under the lock: add_done_callback also holds it, so a
            # registration either lands before this pop (fired here) or
            # observes the terminal state and fires immediately — no
            # callback is ever lost. A same-name successor registered
            # after this record went terminal can at worst receive a
            # spurious notify; waiters re-poll and re-park on those.
            with self._lock:
                callbacks = self._done_callbacks.pop(record.name, [])
            for callback in callbacks:
                try:
                    callback()
                except Exception:  # noqa: BLE001 — a waiter's bug
                    traceback.print_exc()  # must not mask others' wake

    def cancel(self, name: str) -> str:
        """Request cancellation: ``"unknown"`` (→404), ``"terminal"``
        (→409, already done), or ``"cancelling"`` (→202). Cooperative:
        a queued job terminates when a worker drains to it; a running
        one at its next ``check_cancelled()``."""
        with self._lock:
            record = self._jobs.get(name)
            task = self._tasks.get(name)
        if record is None:
            return "unknown"
        if record.state in TERMINAL_STATES:
            return "terminal"
        if task is not None:
            task.token.cancel(f"job {name!r} cancelled by request")
        return "cancelling"

    def add_done_callback(self, name: str, callback: Callable[[], None]) -> str:
        """Register ``callback`` to fire once job ``name`` reaches a
        terminal state — the push half of ``GET /jobs/<name>/wait``.
        Returns ``"unknown"`` (no such job), ``"terminal"`` (already
        done — the callback fired before returning), or
        ``"registered"``. Callbacks must be cheap and thread-safe:
        they run on the finalizing scheduler worker."""
        with self._lock:
            record = self._jobs.get(name)
            if record is None:
                return "unknown"
            if record.state in TERMINAL_STATES:
                fire_now = True
            else:
                self._done_callbacks.setdefault(name, []).append(callback)
                fire_now = False
        if fire_now:
            callback()
            return "terminal"
        return "registered"

    def resolve_wait(self, name: str) -> Optional[JobRecord]:
        """The record ``GET /jobs/<name>/wait`` should watch: an exact
        job-name match first, else the newest job materialising ``name``
        as its collection — clients know dataset filenames ("titanic"),
        while jobs carry prefixed names ("ingest:titanic")."""
        with self._lock:
            record = self._jobs.get(name)
            if record is not None:
                return record
            best: Optional[JobRecord] = None
            for candidate in self._jobs.values():
                if candidate.collection != name:
                    continue
                if best is None or candidate.submitted_at >= best.submitted_at:
                    best = candidate
            return best

    def get(self, name: str) -> Optional[JobRecord]:
        with self._lock:
            return self._jobs.get(name)

    def wait(self, name: str, timeout: Optional[float] = None) -> JobRecord:
        # snapshot under the lock: a concurrent _register for the same
        # name swaps BOTH maps, and the unlocked `self._jobs[name]`
        # this used to do could pair the old event with the new record
        with self._lock:
            record = self._jobs.get(name)
            event = self._events.get(name)
        if event is None or record is None:
            raise KeyError(f"unknown job {name!r}")
        if not event.wait(timeout):
            raise TimeoutError(f"job {name!r} still {record.state}")
        return record

    def all_jobs(self) -> list[dict]:
        with self._lock:
            return [record.as_dict() for record in self._jobs.values()]


_MANAGER: Optional[JobManager] = None
_MANAGER_LOCK = threading.Lock()


def global_job_manager() -> JobManager:
    global _MANAGER
    with _MANAGER_LOCK:
        if _MANAGER is None:
            _MANAGER = JobManager()
        return _MANAGER
