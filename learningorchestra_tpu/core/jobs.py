"""Asynchronous job management with explicit states.

The reference's only job abstraction is the ``finished`` boolean on a
dataset's metadata document: a service writes ``finished: false``, does
work on daemon threads, and flips it to ``true``; a crashed job leaves
``finished: false`` forever and clients poll indefinitely (reference:
microservices/database_api_image/database.py:199-216,
learning_orchestra_client/__init__.py:24-32).

This JobManager keeps that wire contract (so unchanged clients still
poll ``finished``) but adds real states — PENDING/RUNNING/FINISHED/
FAILED with an error payload and timings — and, on failure, *still*
flips ``finished`` on the tracked dataset so pollers terminate, while
recording the error in the metadata document.
"""

from __future__ import annotations

import threading
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Callable, Optional

from learningorchestra_tpu.core.store import METADATA_ID, ROW_ID, DocumentStore
from learningorchestra_tpu.telemetry import metrics as _metrics
from learningorchestra_tpu.telemetry import tracing as _tracing

PENDING = "pending"
RUNNING = "running"
FINISHED = "finished"
FAILED = "failed"


class DuplicateJobError(ValueError):
    """The job name is already PENDING/RUNNING. A ValueError subclass so
    existing ``except ValueError`` duplicate handling keeps working —
    but catchable specifically, which matters for callers whose job
    function can itself raise ValueError (the sync model build must not
    mistake a failed build for "already active" and run it twice)."""


@dataclass
class JobRecord:
    name: str
    state: str = PENDING
    error: Optional[str] = None
    submitted_at: float = field(default_factory=time.time)
    started_at: Optional[float] = None
    ended_at: Optional[float] = None
    # The request's correlation ID and span tree: submit() binds the
    # job to a Trace carrying the submitting request's ID, run() opens
    # the root span, and everything the work emits (PhaseTimer phases,
    # SPMD dispatch spans) nests under it — served by
    # GET /jobs/<name>/trace (utils/web.register_job_traces).
    trace: Optional[_tracing.Trace] = None

    @property
    def correlation_id(self) -> Optional[str]:
        return self.trace.correlation_id if self.trace is not None else None

    def as_dict(self) -> dict:
        return {
            "name": self.name,
            "state": self.state,
            "error": self.error,
            "submitted_at": self.submitted_at,
            "started_at": self.started_at,
            "ended_at": self.ended_at,
            "correlation_id": self.correlation_id,
        }

    def trace_dict(self) -> dict:
        out = self.as_dict()
        out["trace"] = self.trace.as_dict() if self.trace is not None else None
        return out


class JobManager:
    def __init__(self, max_workers: int = 8):
        self._pool = ThreadPoolExecutor(max_workers=max_workers)
        self._jobs: dict[str, JobRecord] = {}
        self._lock = threading.Lock()
        self._events: dict[str, threading.Event] = {}
        registry = _metrics.global_registry()
        self._jobs_total = registry.counter(
            "lo_jobs_total",
            "Jobs reaching a terminal state",
            labels=("state",),
        )
        self._jobs_running = registry.gauge(
            "lo_jobs_running", "Jobs currently executing"
        )
        self._job_seconds = registry.histogram(
            "lo_job_duration_seconds", "Job wall-clock, submit to done"
        )

    def submit(
        self,
        name: str,
        fn: Callable,
        *args,
        store: Optional[DocumentStore] = None,
        collection: Optional[str] = None,
        **kwargs,
    ) -> JobRecord:
        """Run ``fn`` on the pool. If ``store``/``collection`` are given,
        a failure marks that dataset's metadata ``finished: true`` with an
        ``error`` field so pollers terminate instead of hanging."""
        record, done = self._register(name)

        def run():
            self._run_tracked(record, done, fn, args, kwargs, store, collection)

        self._pool.submit(run)
        return record

    def run_inline(
        self,
        name: str,
        fn: Callable,
        *args,
        store: Optional[DocumentStore] = None,
        collection: Optional[str] = None,
        **kwargs,
    ) -> JobRecord:
        """Run ``fn`` synchronously but with the full job bookkeeping —
        state record, correlation-ID trace, metrics. This is how the
        reference-parity SYNCHRONOUS model build (201 only after all
        fits) still gets a ``/jobs/<name>/trace`` span tree. The
        caller's exception propagates after the record is finalized."""
        record, done = self._register(name)
        self._run_tracked(
            record, done, fn, args, kwargs, store, collection, reraise=True
        )
        return record

    def _register(self, name: str) -> tuple[JobRecord, threading.Event]:
        record = JobRecord(
            name=name,
            trace=_tracing.Trace(
                # a job submitted from a REST handler inherits the
                # request's correlation ID; elsewhere a fresh one
                _tracing.current_correlation_id(),
                name=name,
            ),
        )
        with self._lock:
            existing = self._jobs.get(name)
            if existing is not None and existing.state in (PENDING, RUNNING):
                raise DuplicateJobError(
                    f"job {name!r} is already {existing.state}"
                )
            self._jobs[name] = record
            done = threading.Event()
            self._events[name] = done
        return record, done

    def _run_tracked(
        self,
        record: JobRecord,
        done: threading.Event,
        fn: Callable,
        args: tuple,
        kwargs: dict,
        store: Optional[DocumentStore],
        collection: Optional[str],
        reraise: bool = False,
    ) -> None:
        record.state = RUNNING
        record.started_at = time.time()
        self._jobs_running.inc()
        try:
            with _tracing.activate(record.trace), _tracing.span(
                f"job:{record.name}"
            ):
                fn(*args, **kwargs)
            record.state = FINISHED
        except Exception as error:
            record.state = FAILED
            record.error = f"{type(error).__name__}: {error}"
            if not reraise:
                traceback.print_exc()
            if store is not None and collection is not None:
                store.update_one(
                    collection,
                    {ROW_ID: METADATA_ID},
                    {"finished": True, "error": record.error},
                )
            if reraise:
                raise
        finally:
            record.ended_at = time.time()
            self._jobs_running.dec()
            self._jobs_total.labels(record.state).inc()
            self._job_seconds.observe(record.ended_at - record.started_at)
            done.set()

    def get(self, name: str) -> Optional[JobRecord]:
        with self._lock:
            return self._jobs.get(name)

    def wait(self, name: str, timeout: Optional[float] = None) -> JobRecord:
        event = self._events.get(name)
        if event is None:
            raise KeyError(f"unknown job {name!r}")
        if not event.wait(timeout):
            raise TimeoutError(f"job {name!r} still {self._jobs[name].state}")
        return self._jobs[name]

    def all_jobs(self) -> list[dict]:
        with self._lock:
            return [record.as_dict() for record in self._jobs.values()]


_MANAGER: Optional[JobManager] = None
_MANAGER_LOCK = threading.Lock()


def global_job_manager() -> JobManager:
    global _MANAGER
    with _MANAGER_LOCK:
        if _MANAGER is None:
            _MANAGER = JobManager()
        return _MANAGER
