"""Device-resident data plane: a rev-keyed cache of prepared arrays.

BENCH_r05 measured the five-classifier kernel suite at ~457k rows/s
while the product path (store read → preprocess → fits → prediction
write-back) delivered ~14.6k rows/s: the hardware is ~30× ahead of the
host path, and most of the gap is the SAME dataset crossing the wire
and the PCIe/ICI boundary once per job. The reference is worse still —
every service re-reads its collection from Mongo per request
(reference: microservices/model_builder_image/model_builder.py:96-116,
pca_image/pca.py:74-88) and never times that tail.

This module makes a dataset cross each boundary **once per revision**:

- One process-wide :class:`DeviceCache` (``global_devcache``), a
  capacity-bounded (``LO_DEVCACHE_BYTES``) LRU over both **host-level**
  entries (decoded :class:`~learningorchestra_tpu.core.table.ColumnTable`
  columns — skip the wire read + frame decode) and **device-level**
  entries (padded, row-sharded :class:`~learningorchestra_tpu.ml.base.
  DeviceMatrix` buffers — skip the host→device transfer).
- Dataset entries are keyed by ``(store scope, collection, subkey)``
  and stamped with the collection's **mutation rev** — the same counter
  the store service
  already ships per binary frame (``core/store_service.py``
  ``read_columns_bin`` ``extra={"rev": rev}``) for torn-read detection.
  A lookup probes the live rev first; a mismatch **evicts** the stale
  entry and reloads. That makes invalidation correct for a
  :class:`RemoteStore` too, where push invalidation is impossible: every
  mutating op bumps the collection's rev server-side, so the next cached
  reader anywhere observes it.
- Preprocessed frames (whose bytes are produced by arbitrary
  ``preprocessor_code``) are cached **content-addressed** instead
  (:func:`content_device_matrix`): the key is a BLAKE2 digest of the
  host buffer plus the mesh signature, so an entry can never be stale —
  it only LRU-evicts. This is what lets a second ``build_model`` over
  the same collection skip every H2D for train/test/eval matrices.

Device entries are per-process and per-mesh (``mesh_signature``): on a
multi-host mesh every process caches its own shards, and lookups are
pure host work — no collectives — so cache hits can never desynchronize
SPMD dispatch.

Import cost: numpy + stdlib only. JAX is imported lazily inside the
device-level helpers, so the store SERVER process (which imports
``core.store_service`` → this module's invalidation hook) never pays a
jax import.
"""

from __future__ import annotations

import hashlib
import itertools
import os
import threading
from collections import OrderedDict
from typing import Any, Callable, Optional

import numpy as np

from learningorchestra_tpu.utils.dtypepolicy import dtype_policy

# Content-addressed entries live under this pseudo-collection: their key
# embeds a digest of the bytes, so they cannot go stale and are never
# rev-invalidated — only LRU-evicted.
CONTENT = "__content__"

DEFAULT_CAPACITY_BYTES = 2_000_000_000


def capacity_bytes() -> int:
    """``LO_DEVCACHE_BYTES`` validated (deploy/run.sh preflights this):
    total bytes of cached payloads, host and device entries against one
    budget. ``0`` disables caching entirely."""
    # lo: allow[LO305] this IS the validated accessor preflight calls
    raw = os.environ.get("LO_DEVCACHE_BYTES", "").strip()
    if not raw:
        return DEFAULT_CAPACITY_BYTES
    try:
        value = int(float(raw))
    except ValueError:
        raise ValueError(
            f"LO_DEVCACHE_BYTES must be a number of bytes, got {raw!r}"
        ) from None
    if value < 0:
        raise ValueError(f"LO_DEVCACHE_BYTES must be >= 0, got {value}")
    return value


def store_rev(store, collection: str) -> int:
    """The collection's mutation counter, or -1 when the backend cannot
    report one (unknown backends never cache)."""
    rev_fn = getattr(store, "collection_rev", None)
    if rev_fn is None:
        return -1
    return rev_fn(collection)


_STORE_TOKENS = itertools.count(1)
_TOKEN_LOCK = threading.Lock()


def store_token(store) -> str:
    """A per-store-instance cache scope. Revs are monotonic only WITHIN
    one store, so entries must never be shared across stores: two
    stores holding a same-named collection at a coincidentally equal
    rev (trivial for two fresh in-memory stores) would otherwise alias.
    The token is minted once and pinned on the instance — stable for
    the store's lifetime, and unlike ``id()`` it can never recycle into
    a live entry after garbage collection. Minting is locked: two
    threads racing the first lookup must agree on ONE scope, or the
    loser's entries would be stranded (unreachable for hits and for
    scoped purges) while still charging the byte budget."""
    token = getattr(store, "_lo_devcache_token", None)
    if token is None:
        with _TOKEN_LOCK:
            token = getattr(store, "_lo_devcache_token", None)
            if token is None:
                token = f"s{next(_STORE_TOKENS)}"
                try:
                    store._lo_devcache_token = token
                except AttributeError:  # __slots__ backend: no cache
                    return ""
    # shard topology dimension: a ShardedStore's rev is a SUM over
    # groups, so a re-wired topology (different shard count or stripe)
    # could reproduce an old sum over different bytes — scoping the
    # token by the shard signature invalidates every cached entry on
    # any topology change instead
    return token + getattr(store, "shard_signature", "")


def mesh_signature(mesh) -> tuple:
    """A hashable, structural mesh identity: device entries prepared for
    one mesh must never serve another (different sharding layout), and
    ``id(mesh)`` alone would alias after garbage collection."""
    return (
        tuple(mesh.axis_names),
        tuple(mesh.devices.shape),
        tuple(int(d.id) for d in mesh.devices.flat),
    )


class _Entry:
    __slots__ = ("value", "nbytes", "rev")

    def __init__(self, value: Any, nbytes: int, rev: int):
        self.value = value
        self.nbytes = nbytes
        self.rev = rev


class DeviceCache:
    """Capacity-bounded LRU keyed by ``(scope, collection, subkey)``
    where ``scope`` identifies the store instance (``store_token``) —
    revs are only comparable within one store.

    Staleness is checked at lookup against the caller-probed rev: a
    mismatched entry is dropped (counted as an invalidation) and the
    lookup misses, so one key never holds two revisions and a mutating
    store op needs no push channel into this process.
    """

    def __init__(self, capacity: Optional[int] = None):
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self.capacity = capacity_bytes() if capacity is None else capacity
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.invalidations = 0

    # --- primitive get/put ----------------------------------------------------
    def get(
        self, scope: str, collection: str, subkey: tuple, rev: int
    ) -> Optional[Any]:
        key = (scope, collection, subkey)
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and rev >= 0 and entry.rev == rev:
                self._entries.move_to_end(key)
                self.hits += 1
                return entry.value
            if entry is not None:
                # stale (a write bumped the rev, or the collection is
                # gone and the probe answered -1): evict now — rev-keyed
                # invalidation IS this line
                self._drop_locked(key)
                self.invalidations += 1
            self.misses += 1
            return None

    def put(
        self,
        scope: str,
        collection: str,
        subkey: tuple,
        rev: int,
        value: Any,
        nbytes: int,
    ) -> Any:
        nbytes = max(int(nbytes), 0)
        if (
            self.capacity <= 0
            or rev < 0
            or not scope
            or nbytes > self.capacity
        ):
            return value  # uncacheable: hand the value through
        key = (scope, collection, subkey)
        with self._lock:
            if key in self._entries:
                self._drop_locked(key)
            while self.bytes + nbytes > self.capacity and self._entries:
                oldest = next(iter(self._entries))
                self._drop_locked(oldest)
                self.evictions += 1
            self._entries[key] = _Entry(value, nbytes, rev)
            self.bytes += nbytes
        return value

    def _drop_locked(self, key: tuple) -> None:
        entry = self._entries.pop(key, None)
        if entry is not None:
            self.bytes -= entry.nbytes

    def invalidate(
        self, collection: Optional[str] = None, scope: Optional[str] = None
    ) -> int:
        """Drop every entry for ``collection`` (all collections when
        None), restricted to one store ``scope`` when given. Mid-stream
        read failures call this — scoped to the failing store, so an
        aborted read of one store's collection never purges another
        store's same-named one — and a partially-populated entry can
        never survive a retried read. Returns the drop count."""
        with self._lock:
            keys = [
                key
                for key in self._entries
                if (collection is None or key[1] == collection)
                and (scope is None or key[0] == scope)
            ]
            for key in keys:
                self._drop_locked(key)
            self.invalidations += len(keys)
            return len(keys)

    # --- the one loader shape every helper shares -----------------------------
    def get_or_load(
        self,
        store,
        collection: str,
        subkey: tuple,
        loader: Callable[[], Any],
        nbytes_fn: Callable[[Any], int],
    ) -> Any:
        """Rev-probed lookup; on miss run ``loader`` and cache the result
        — but only when the rev is unchanged after the load (a write
        landing mid-read must not be cached under the pre-write rev)."""
        scope = store_token(store)
        rev = store_rev(store, collection)
        cached = self.get(scope, collection, subkey, rev)
        if cached is not None:
            return cached
        value = loader()
        if rev >= 0 and store_rev(store, collection) == rev:
            self.put(scope, collection, subkey, rev, value, nbytes_fn(value))
        return value

    def stats(self) -> dict:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "invalidations": self.invalidations,
                "bytes": self.bytes,
                "entries": len(self._entries),
            }

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self.bytes = 0


_GLOBAL: Optional[DeviceCache] = None
_GLOBAL_LOCK = threading.Lock()


def global_devcache() -> DeviceCache:
    """The process-wide cache every data-plane consumer shares. First
    call registers the ``lo_devcache_*`` gauges on the process metrics
    registry (docs/observability.md)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = DeviceCache()
            _register_metrics(_GLOBAL)
        return _GLOBAL


def reset_global_devcache() -> None:
    """Tests only: drop the global cache's entries and counters. The
    metrics collector holds the OLD instance, so a full replacement
    would orphan its gauges — clear in place instead."""
    with _GLOBAL_LOCK:
        if _GLOBAL is not None:
            _GLOBAL.clear()
            _GLOBAL.hits = _GLOBAL.misses = 0
            _GLOBAL.evictions = _GLOBAL.invalidations = 0


def invalidate_collection(
    collection: str, store: Optional[object] = None
) -> None:
    """Invalidation hook for writers and for mid-stream read failures
    (``RemoteStore.read_column_arrays``): cheap no-op before the global
    cache exists. ``store`` (when given) restricts the purge to that
    store's scope."""
    with _GLOBAL_LOCK:
        cache = _GLOBAL
    if cache is not None:
        scope = store_token(store) if store is not None else None
        cache.invalidate(collection, scope=scope or None)


def _register_metrics(cache: DeviceCache) -> None:
    from learningorchestra_tpu.telemetry import global_registry

    registry = global_registry()
    gauges = {
        name: registry.gauge(f"lo_devcache_{name}", help_text)
        for name, help_text in (
            ("hits", "Device-cache lookups served without a reload"),
            ("misses", "Device-cache lookups that ran the loader"),
            ("evictions", "Entries dropped by the LRU capacity bound"),
            (
                "invalidations",
                "Entries dropped because the collection rev moved "
                "(or a mid-stream read failure forced a purge)",
            ),
            ("bytes", "Bytes of cached payloads (host + device)"),
            ("entries", "Entries resident in the device cache"),
        )
    }

    def collect(_registry) -> None:
        stats = cache.stats()
        for name, gauge in gauges.items():
            gauge.set(stats[name])

    registry.register_collector(collect)


# --- dataset-level helpers (collection + rev keyed) ---------------------------


def _fields_key(fields) -> tuple:
    return ("*",) if fields is None else tuple(fields)


def _device_matrix_nbytes(dm) -> int:
    return int(dm.data.nbytes) + int(dm.mask.nbytes)


def _table_nbytes(table) -> int:
    total = 0
    for column in table.columns.values():
        total += column.nbytes
        if column.dtype == object:
            # nbytes counts pointers only; charge a rough boxed-object
            # footprint so string-heavy tables don't dodge the budget
            total += 48 * len(column)
    return total


def dataset_table(store, collection: str, fields=None, cache=None):
    """The collection as a :class:`ColumnTable`, cached by rev — the
    host half of the data plane: a warm hit skips the wire read and the
    frame decode entirely. Callers share the returned table's arrays;
    every consumer in this codebase treats columns as immutable (frame
    verbs copy-on-write), which is the same contract the per-frame
    device cache already relies on."""
    from learningorchestra_tpu.core.table import ColumnTable
    from learningorchestra_tpu.telemetry import span

    cache = cache or global_devcache()

    def load():
        # store:read wraps the whole store→host materialization (local
        # or remote backend; a RemoteStore nests its wire:read inside)
        # with rows + decoded host bytes, so the timeline attributes
        # the host-boundary cost even when no wire is involved.
        with span("store:read", collection=collection) as span_obj:
            table = ColumnTable.from_store(store, collection, fields)
            if span_obj is not None:
                span_obj.meta["rows"] = table.num_rows
                span_obj.meta["bytes"] = _table_nbytes(table)
            return table

    return cache.get_or_load(
        store,
        collection,
        ("table", _fields_key(fields)),
        load,
        _table_nbytes,
    )


def dataset_embedding_inputs(store, collection: str, mesh=None, cache=None):
    """``(encoded_table, vocabularies, DeviceMatrix)`` as ONE cache
    entry — the PCA/t-SNE image pipeline's inputs. A single entry (not
    separate encoded/devmat lookups) so the hue labels and the device
    matrix can never come from different revisions when a write lands
    between lookups: everything in the triple derives from one
    ``dataset_table`` read. With caching disabled this also stays one
    wire read per request."""
    from learningorchestra_tpu.ml.base import resolve_mesh, shard_matrix
    from learningorchestra_tpu.telemetry import span

    mesh = resolve_mesh(mesh)
    cache = cache or global_devcache()

    def load():
        table = dataset_table(store, collection, cache=cache).dropna()
        encoded, vocabularies = table.encoded()
        X = encoded.matrix()
        # h2d byte accounting happens inside shard_matrix (the
        # shard_rows funnel, parallel/sharding.py) and accumulates onto
        # this span as h2d_bytes
        with span(
            "h2d:dataset",
            collection=collection,
            rows=len(X),
            dtype=dtype_policy(),
        ):
            return encoded, vocabularies, shard_matrix(X, mesh)

    return cache.get_or_load(
        store,
        collection,
        ("embed_inputs", mesh_signature(mesh), dtype_policy()),
        load,
        lambda value: _table_nbytes(value[0]) + _device_matrix_nbytes(value[2]),
    )


# --- content-addressed helpers (preprocessed frames) --------------------------


def _content_digest(array: np.ndarray) -> tuple:
    array = np.ascontiguousarray(array)
    digest = hashlib.blake2b(array.view(np.uint8), digest_size=16)
    return (str(array.dtype), array.shape, digest.hexdigest())


def content_device_matrix(X: np.ndarray, mesh):
    """A padded + row-sharded :class:`DeviceMatrix` for ``X``, cached by
    content digest + mesh signature. Content addressing makes the entry
    stale-proof (a different matrix is a different key), so arbitrary
    ``preprocessor_code`` output can ride the cache safely: the second
    build over the same collection hashes the recomputed host matrix,
    hits, and skips the H2D. The digest costs one linear pass over host
    bytes — microseconds per MB next to a PCIe (let alone tunneled)
    transfer."""
    from learningorchestra_tpu.ml.base import shard_matrix
    from learningorchestra_tpu.telemetry import span

    cache = global_devcache()
    subkey = (
        "devmat", _content_digest(X), mesh_signature(mesh), dtype_policy()
    )
    cached = cache.get(CONTENT, CONTENT, subkey, rev=0)
    if cached is not None:
        return cached
    with span("h2d:matrix", rows=len(X), dtype=dtype_policy()):
        dm = shard_matrix(X, mesh)
    return cache.put(
        CONTENT, CONTENT, subkey, 0, dm, _device_matrix_nbytes(dm)
    )


def content_device_labels(y: np.ndarray, mesh):
    """Label-vector analogue of :func:`content_device_matrix`."""
    from learningorchestra_tpu.ml.base import shard_labels
    from learningorchestra_tpu.telemetry import span

    cache = global_devcache()
    subkey = ("devlab", _content_digest(y), mesh_signature(mesh), "i32")
    cached = cache.get(CONTENT, CONTENT, subkey, rev=0)
    if cached is not None:
        return cached
    with span("h2d:labels", rows=len(y), dtype="i32"):
        dl = shard_labels(y, mesh)
    return cache.put(CONTENT, CONTENT, subkey, 0, dl, int(dl.data.nbytes))
