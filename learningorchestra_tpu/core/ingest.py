"""CSV dataset ingestion: URL → document store.

Reference behaviour (microservices/database_api_image/database.py:134-216):
a 3-thread pipeline (download → row-to-dict → per-row ``insert_one``)
guarded by a first-line sniff that rejects HTML/JSON bodies; metadata is
written up front with ``finished: false`` and flipped when the save thread
drains. Values are stored as raw strings — type conversion is a separate
service.

This implementation keeps the observable contract (metadata shape,
``finished`` flag, string values, 201-then-poll asynchrony) but streams
into *batched* ``insert_many`` calls instead of one RPC per row, and
supports ``file://``/local paths so tests need no network.
"""

from __future__ import annotations

import csv
import io
from contextlib import ExitStack, closing
from datetime import datetime, timezone
from typing import Iterator, TextIO

import requests

from learningorchestra_tpu.core.store import METADATA_ID, ROW_ID, DocumentStore

INVALID_URL = "invalid_url"
DUPLICATE_FILE = "duplicate_file"
FINISHED = "finished"
BATCH_SIZE = 4096


class IngestError(Exception):
    pass


def _open_text(url: str, stack: ExitStack) -> TextIO:
    """A text stream over an http(s) URL, file:// URL or local path.

    Returns a real character stream (not pre-split lines) so the csv
    parser sees quoted embedded newlines intact.
    """
    if url.startswith(("http://", "https://")):
        response = stack.enter_context(closing(requests.get(url, stream=True)))
        response.raise_for_status()
        response.raw.decode_content = True
        return stack.enter_context(
            io.TextIOWrapper(response.raw, encoding="utf-8", newline="")
        )
    path = url[len("file://") :] if url.startswith("file://") else url
    return stack.enter_context(open(path, encoding="utf-8", newline=""))


def _csv_rows(stream: TextIO) -> Iterator[list[str]]:
    return iter(csv.reader(stream, delimiter=",", quotechar='"'))


def validate_csv_url(url: str) -> list[str]:
    """Sniff the header row; reject HTML/JSON bodies.

    Mirrors the reference's first-character check (reference:
    database.py:183-197). Returns the header row.
    """
    try:
        with ExitStack() as stack:
            header = next(_csv_rows(_open_text(url, stack)))
    except (OSError, requests.exceptions.RequestException, StopIteration) as error:
        raise IngestError(INVALID_URL) from error
    if not header or not header[0] or header[0][0] in ("<", "{"):
        raise IngestError(INVALID_URL)
    return header


def timestamp() -> str:
    """UTC timestamp in the reference's metadata format (reference:
    database.py:201-204)."""
    return datetime.now(timezone.utc).strftime("%Y-%m-%dT%H:%M:%S-00:00")


def write_ingest_metadata(store: DocumentStore, filename: str, url: str) -> None:
    """The up-front ``finished: false`` metadata document (reference:
    database.py:205-213). Raises KeyError on duplicate collection.

    The duplicate gate is the same atomic ``create_collection`` claim the
    create routes use, so an ingest can never share a collection with a
    concurrently created projection/histogram output."""
    if not store.create_collection(filename):
        raise KeyError(f"collection {filename!r} already exists")
    store.insert_one(
        filename,
        {
            "filename": filename,
            "url": url,
            "time_created": timestamp(),
            ROW_ID: METADATA_ID,
            FINISHED: False,
            "fields": "processing",
        },
    )


def ingest_csv(
    store: DocumentStore,
    filename: str,
    url: str,
    batch_size: int = BATCH_SIZE,
) -> int:
    """Stream the CSV at ``url`` into collection ``filename``.

    Rows become documents ``{header[i]: value, _id: 1..N}`` with values
    kept as strings (type conversion is the fieldtypes service's job).
    Flips the metadata to ``finished: true`` with the field list when the
    stream drains. Returns the row count.
    """
    # Always the streaming path: memory is bounded at one batch
    # regardless of file size, and it is tolerant of ragged rows. The
    # native C++ parser serves the columnar ``ColumnTable.from_csv``
    # route, where full materialization is inherent.
    with ExitStack() as stack:
        reader = _csv_rows(_open_text(url, stack))
        file_header = next(reader)

        batch: list[dict] = []
        row_id = 0
        width = len(file_header)
        for row in reader:
            if not row:
                continue
            row_id += 1
            document = {
                file_header[i]: (row[i] if i < len(row) else "") for i in range(width)
            }
            document[ROW_ID] = row_id
            batch.append(document)
            if len(batch) >= batch_size:
                store.insert_many(filename, batch)
                batch = []
        if batch:
            store.insert_many(filename, batch)

    store.update_one(
        filename,
        {ROW_ID: METADATA_ID},
        {FINISHED: True, "fields": file_header},
    )
    return row_id
