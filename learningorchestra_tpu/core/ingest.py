"""CSV dataset ingestion: URL → document store.

Reference behaviour (microservices/database_api_image/database.py:134-216):
a 3-thread pipeline (download → row-to-dict → per-row ``insert_one``)
guarded by a first-line sniff that rejects HTML/JSON bodies; metadata is
written up front with ``finished: false`` and flipped when the save thread
drains. Values are stored as raw strings — type conversion is a separate
service.

This implementation keeps the observable contract (metadata shape,
``finished`` flag, string values, 201-then-poll asynchrony) but streams
into *batched* ``insert_many`` calls instead of one RPC per row, and
supports ``file://``/local paths so tests need no network.
"""

from __future__ import annotations

import csv
import io
import os
import tempfile
from contextlib import ExitStack, closing
from datetime import datetime, timezone
from typing import Iterator, Optional, TextIO

import requests

from learningorchestra_tpu.core.store import METADATA_ID, ROW_ID, DocumentStore

INVALID_URL = "invalid_url"
DUPLICATE_FILE = "duplicate_file"
FINISHED = "finished"
BATCH_SIZE = 4096

# Files beyond this parse as a sequence of slabs so ingest's transient
# working set stays bounded (the whole-file native parse holds file
# bytes + cell index in anonymous RAM — ~2x file size — which a 12 GB
# CSV cannot afford on an out-of-core store). 0 disables slabbing.
_SLAB_BYTES = int(
    # lo: allow[LO305] module-level read-once by design (see above)
    float(os.environ.get("LO_INGEST_SLAB_BYTES", "536870912") or 0)
)


class IngestError(Exception):
    pass


def _open_text(url: str, stack: ExitStack) -> TextIO:
    """A text stream over an http(s) URL, file:// URL or local path.

    Returns a real character stream (not pre-split lines) so the csv
    parser sees quoted embedded newlines intact.
    """
    if url.startswith(("http://", "https://")):
        response = stack.enter_context(closing(requests.get(url, stream=True)))
        response.raise_for_status()
        response.raw.decode_content = True
        return stack.enter_context(
            io.TextIOWrapper(response.raw, encoding="utf-8", newline="")
        )
    path = url[len("file://") :] if url.startswith("file://") else url
    return stack.enter_context(open(path, encoding="utf-8", newline=""))


def _csv_rows(stream: TextIO) -> Iterator[list[str]]:
    return iter(csv.reader(stream, delimiter=",", quotechar='"'))


def validate_csv_url(url: str) -> list[str]:
    """Sniff the header row; reject HTML/JSON bodies.

    Mirrors the reference's first-character check (reference:
    database.py:183-197). Returns the header row.
    """
    try:
        with ExitStack() as stack:
            header = next(_csv_rows(_open_text(url, stack)))
    except (OSError, requests.exceptions.RequestException, StopIteration) as error:
        raise IngestError(INVALID_URL) from error
    if not header or not header[0] or header[0][0] in ("<", "{"):
        raise IngestError(INVALID_URL)
    return header


def timestamp() -> str:
    """UTC timestamp in the reference's metadata format (reference:
    database.py:201-204)."""
    return datetime.now(timezone.utc).strftime("%Y-%m-%dT%H:%M:%S-00:00")


def write_ingest_metadata(store: DocumentStore, filename: str, url: str) -> None:
    """The up-front ``finished: false`` metadata document (reference:
    database.py:205-213). Raises KeyError on duplicate collection.

    The duplicate gate is the same atomic ``create_collection`` claim the
    create routes use, so an ingest can never share a collection with a
    concurrently created projection/histogram output."""
    if not store.create_collection(filename):
        raise KeyError(f"collection {filename!r} already exists")
    store.insert_one(
        filename,
        {
            "filename": filename,
            "url": url,
            "time_created": timestamp(),
            ROW_ID: METADATA_ID,
            FINISHED: False,
            "fields": "processing",
        },
    )


def _local_csv_path(url: str, stack: ExitStack) -> str:
    """A local filesystem path for ``url``, downloading http(s) bodies to
    a temp file (deleted when ``stack`` closes). The columnar parsers —
    native C++ and Python alike — work from a file."""
    if not url.startswith(("http://", "https://")):
        return url[len("file://") :] if url.startswith("file://") else url
    response = stack.enter_context(closing(requests.get(url, stream=True)))
    response.raise_for_status()
    handle = stack.enter_context(
        tempfile.NamedTemporaryFile(suffix=".csv", delete=True)
    )
    for chunk in response.iter_content(chunk_size=1 << 20):
        handle.write(chunk)
    handle.flush()
    return handle.name


def _python_raw_columns(path: str) -> tuple[list[str], list[list[str]]]:
    """Python fallback for :func:`native.loader.read_csv_raw_columns`:
    same raw-string contract, tolerant of ragged rows (short rows pad
    with ``""``, oversized rows truncate to the header width)."""
    with open(path, encoding="utf-8", newline="") as handle:
        reader = _csv_rows(handle)
        header = next(reader)
        width = len(header)
        columns: list[list[str]] = [[] for _ in range(width)]
        for row in reader:
            if not row:
                continue
            for i in range(width):
                columns[i].append(row[i] if i < len(row) else "")
    return header, columns


def ingest_csv(
    store: DocumentStore,
    filename: str,
    url: str,
    batch_size: Optional[int] = None,
) -> int:
    """Ingest the CSV at ``url`` into collection ``filename``,
    column-major.

    Observable contract unchanged from the reference (rows are documents
    ``{header[i]: value, _id: 1..N}``, values kept as raw strings, type
    conversion is the fieldtypes service's job, metadata flips to
    ``finished: true`` with the field list at the end — reference:
    microservices/database_api_image/database.py:144-216) — but the body
    lands as the store's columnar block via batched ``insert_columns``:
    the native C++ parser (native/csv_loader.cpp) feeds column lists
    straight in, and no per-row Python dict is ever built. Returns the
    row count.

    Memory model: the dataset body is resident in the store regardless
    (that is what an in-memory store is); ingest transiently holds a
    second copy (the parse result) before the batched hand-off, so peak
    is ~2× the body — same order as the reference's Mongo working set.
    """
    from learningorchestra_tpu.native.loader import read_csv_string_columns

    with ExitStack() as stack:
        path = _local_csv_path(url, stack)
        if _SLAB_BYTES and os.path.getsize(path) > _SLAB_BYTES:
            num_rows, file_header = _ingest_slabbed(
                store, filename, path, batch_size
            )
        else:
            # Native path: NUL-joined column buffers → Arrow string
            # columns, no Python string objects between the parser and
            # the store.
            parsed = read_csv_string_columns(path)
            if parsed is None:
                parsed = _python_raw_columns(path)
            file_header, raw_columns = parsed
            num_rows = _insert_parsed(
                store, filename, file_header, raw_columns, 1, batch_size
            )

    store.update_one(
        filename,
        {ROW_ID: METADATA_ID},
        {FINISHED: True, "fields": file_header},
    )
    return num_rows


def _insert_parsed(
    store, filename, file_header, raw_columns, start_id, batch_size
) -> int:
    """Batched columnar hand-off of one parse result.

    Duplicate header names collapse last-wins, as the reference's
    per-row dict build did (database.py:156-169); a CSV column named
    `_id` is discarded the same way the reference's row ids overwrote
    it (database.py:161-168) — row ids are always 1..N."""
    from learningorchestra_tpu.core.table import insert_columns_batched

    columns = dict(zip(file_header, raw_columns))
    columns.pop(ROW_ID, None)
    num_rows = len(raw_columns[0]) if raw_columns else 0
    insert_columns_batched(
        store, filename, columns, start_id=start_id, batch_size=batch_size
    )
    return num_rows


def _ingest_slabbed(
    store, filename, path, batch_size
) -> tuple[int, list[str]]:
    """Parse + insert a big CSV one ~slab at a time so the transient
    working set is slab-sized, not file-sized — with the store spilling
    past its RAM budget, total ingest memory stays bounded at any file
    size (the Mongo-owns-disk ingestion story). Slab boundaries land
    only on lines with balanced quotes, so quoted embedded newlines
    never split across parses."""
    from learningorchestra_tpu.native.loader import read_csv_string_columns

    total_rows = 0
    file_header: list[str] = []
    with open(path, encoding="utf-8", newline="") as source:
        header_line = source.readline()
        file_header = next(_csv_rows(io.StringIO(header_line)))
        while True:
            # stream lines STRAIGHT into the slab temp file — holding
            # them in a list first would cost per-str object overhead
            # several times the nominal slab size for short rows
            slab_bytes = 0
            slab_lines = 0
            open_quotes = False
            # slab next to the source file, NOT the default tempdir: on
            # hosts where /tmp is tmpfs a 0.5-2 GB slab would be
            # RAM-backed — the exact cost slabbing exists to avoid
            try:
                slab_handle = tempfile.NamedTemporaryFile(
                    "w",
                    suffix=".csv",
                    delete=False,
                    encoding="utf-8",
                    newline="",
                    dir=os.path.dirname(os.path.abspath(path)) or None,
                )
            except OSError:  # source dir unwritable: default tempdir
                slab_handle = tempfile.NamedTemporaryFile(
                    "w",
                    suffix=".csv",
                    delete=False,
                    encoding="utf-8",
                    newline="",
                )
            with slab_handle as slab:
                slab.write(header_line)
                slab_path = slab.name
                for line in source:
                    slab.write(line)
                    slab_lines += 1
                    if line.count('"') % 2:
                        open_quotes = not open_quotes
                    slab_bytes += len(line)
                    if slab_bytes >= _SLAB_BYTES and (
                        not open_quotes
                        # hard cap: a stray quote in an unquoted field
                        # (legal for csv.reader, e.g. inch marks) would
                        # otherwise pin open_quotes and buffer the rest
                        # of the file into one slab. Files quoted to
                        # RFC-4180 never hit this; a mis-quoted file
                        # splits where a line-based reader would.
                        or slab_bytes >= 4 * _SLAB_BYTES
                    ):
                        break
            if not slab_lines:
                os.unlink(slab_path)
                break
            try:
                parsed = read_csv_string_columns(slab_path)
                if parsed is None:
                    parsed = _python_raw_columns(slab_path)
            finally:
                os.unlink(slab_path)
            slab_header, raw_columns = parsed
            total_rows += _insert_parsed(
                store,
                filename,
                slab_header,
                raw_columns,
                total_rows + 1,
                batch_size,
            )
            del parsed, raw_columns
    return total_rows, file_header
