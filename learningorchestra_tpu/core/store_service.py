"""The store as a network service: HTTP wire protocol + client backend.

The reference's only data plane is a MongoDB replica set every service
container points at via ``DATABASE_URL`` (reference:
docker-compose.yml:27-91 replica set, :188-192 per-service env). This
module is that role for the TPU framework: a store server process
exposing the full :class:`DocumentStore` interface over HTTP, and
:class:`RemoteStore`, the client backend the seven services use to run
as independent processes/containers against one shared store.

Wire protocol (JSON bodies; both ends are this module, so it is an
internal contract, versioned by the framework):

- ``GET  /collections``                         → ``{"collections": [...]}``
- ``POST /collections/<name>``                  → ``{"created": bool}`` (atomic claim)
- ``DELETE /collections/<name>``                → ``{}``
- ``POST /c/<name>/insert_one``     ``{"document": {...}}``
- ``POST /c/<name>/insert_many``    ``{"documents": [...]}``
- ``POST /c/<name>/insert_columns`` ``{"columns": {...}, "start_id": n|null}``
- ``POST /c/<name>/update_one``     ``{"query": {...}, "new_values": {...}}``
- ``POST /c/<name>/set_field_values`` ``{"field": f, "values": [[id, v], ...]}``
  (id/value pairs, not an object — JSON objects would stringify int ids)
- ``POST /c/<name>/set_column``     ``{"field": f, "values": [...], "start_id": n}``
- ``POST /c/<name>/find``           ``{"query", "skip", "limit"}`` → ``{"documents"}``
- ``POST /c/<name>/read_columns``   ``{"fields": [...]|null}`` → ``{"columns"}``
- ``POST /c/<name>/aggregate``      ``{"pipeline": [...]}`` → ``{"results"}``
- ``GET  /c/<name>/count``                      → ``{"count": n}``
- ``GET  /health``                              → ``{"ok": true, "writable": bool}``
- ``GET  /wal?epoch&offset&limit``              → WAL feed for followers
- ``POST /promote``                             → follower becomes writable

Binary columnar verbs (typed buffers, ``core/wire.py`` framing — the
data plane large datasets actually ride; the JSON forms above remain
for small bodies and debuggability):

- ``POST /c/<name>/read_columns_bin``  JSON ``{"fields","start","limit"}``
  → ``application/x-lo-columns`` frame; the frame's ``extra`` carries
  ``rev`` (collection mutation counter) so paged readers can detect a
  write landing between chunks and retry instead of returning a torn
  result.
- ``POST /c/<name>/insert_columns_bin``  frame with ``extra.start_id``
- ``POST /c/<name>/set_column_bin``      frame with ``extra.field`` /
  ``extra.start_id``

Error mapping: ``KeyError`` (duplicate ids/collections) → 409;
``UnsupportedQueryError`` → 400 with ``kind: unsupported_query``; other
``ValueError`` → 400; mutation on a follower → 503. :class:`RemoteStore`
re-raises the same exception types, so service code behaves identically
on a local or remote store.

Durability/replication posture: the server runs one WAL-backed
:class:`InMemoryStore`; the WAL is the durability story and the primary
is the single writer. HA mirrors the reference's Mongo replica set
(docker-compose.yml:27-91) with WAL shipping: a primary started with
``LO_REPLICATE=1`` feeds ``GET /wal``; followers started with
``LO_PRIMARY_URL`` tail it (:class:`ReplicationClient`, the oplog-tailing
secondary role), serve reads, reject writes with 503, and take over on
``POST /promote`` — promotion instead of election: one HTTP call by the
operator or supervisor instead of a quorum protocol.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Iterator, Optional

import requests

from learningorchestra_tpu.core.columns import Column
from learningorchestra_tpu.core.store import (
    DocumentStore,
    InMemoryStore,
    UnsupportedQueryError,
)
from learningorchestra_tpu.core.wire import (
    CONTENT_TYPE as BIN_CONTENT_TYPE,
    decode_frame,
    encode_frame,
)
from learningorchestra_tpu.utils.web import Response, ServerThread, WebApp

DEFAULT_STORE_PORT = 27027


def create_store_app(store: DocumentStore, role: Optional[dict] = None) -> WebApp:
    """``role`` (mutable, shared with the caller) carries the HA state:
    ``{"writable": bool, "poller": ReplicationClient | None}``. A
    follower serves every read with ``writable: False`` and answers
    mutations with 503 until ``POST /promote`` flips it — the failover
    the reference delegates to Mongo's replica-set election
    (docker-compose.yml:27-91)."""
    app = WebApp("store")
    role = role if role is not None else {"writable": True, "poller": None}

    def guarded(handler):
        def wrapped(request, **kwargs):
            try:
                return handler(request, **kwargs)
            except KeyError as error:
                return {"error": str(error)}, 409
            except UnsupportedQueryError as error:
                return {"error": str(error), "kind": "unsupported_query"}, 400
            except ValueError as error:
                return {"error": str(error)}, 400

        wrapped.__name__ = handler.__name__
        return wrapped

    def mutating(handler):
        def wrapped(request, **kwargs):
            if not role.get("writable", True):
                return {"error": "read-only follower; POST /promote"}, 503
            return handler(request, **kwargs)

        wrapped.__name__ = handler.__name__
        return wrapped

    @app.route("/health", methods=("GET",))
    def health(request):
        return {
            "ok": True,
            "writable": role.get("writable", True),
            "columns_wire": "bin1",
        }, 200

    @app.route("/wal", methods=("GET",))
    def wal(request):
        try:
            epoch = int(request.args.get("epoch", -1))
            offset = int(request.args.get("offset", 0))
            limit = int(request.args.get("limit", 10000))
        except ValueError:
            return {"error": "epoch/offset/limit must be integers"}, 400
        try:
            feed = store.wal_feed(epoch, offset, limit=limit)
        except (AttributeError, ValueError):
            return {"error": "replication not enabled (LO_REPLICATE=1)"}, 404
        return feed, 200

    @app.route("/compact", methods=("POST",))
    def compact(request):
        if not hasattr(store, "compact"):
            return {"error": "store does not support compaction"}, 404
        # compacted: false = skipped (another compaction in flight) or
        # superseded by a replication resync — the caller must NOT
        # assume the on-disk log is a fresh snapshot
        compacted = bool(store.compact())
        return {"compacted": compacted}, 200

    @app.route("/promote", methods=("POST",))
    def promote(request):
        """Flip this follower writable. The response reports the last
        WAL position applied from the old primary so the operator can
        see the acknowledged replication lag (records the dead primary
        accepted but never shipped are LOST — durability follows the
        new primary from here). Fencing the OLD primary is the
        operator's step: if it revives, restart it with LO_PRIMARY_URL
        pointing at the new primary so it rejoins as a follower instead
        of coming back writable (deploy/README.md)."""
        poller = role.get("poller")
        applied = None
        if poller is not None:
            poller.stop()
            applied = {"epoch": poller.epoch, "offset": poller.offset}
        role["writable"] = True
        return {"promoted": True, "applied_through": applied}, 200

    @app.route("/collections", methods=("GET",))
    def list_collections(request):
        return {"collections": store.list_collections()}, 200

    @app.route("/collections/<name>", methods=("POST",))
    @mutating
    def create_collection(request, name):
        return {"created": store.create_collection(name)}, 200

    @app.route("/collections/<name>", methods=("DELETE",))
    @mutating
    def drop(request, name):
        store.drop(name)
        return {}, 200

    @app.route("/c/<name>/insert_one", methods=("POST",))
    @guarded
    @mutating
    def insert_one(request, name):
        store.insert_one(name, request.get_json()["document"])
        return {}, 200

    @app.route("/c/<name>/insert_many", methods=("POST",))
    @guarded
    @mutating
    def insert_many(request, name):
        store.insert_many(name, request.get_json()["documents"])
        return {}, 200

    @app.route("/c/<name>/insert_columns", methods=("POST",))
    @guarded
    @mutating
    def insert_columns(request, name):
        body = request.get_json()
        store.insert_columns(name, body["columns"], start_id=body.get("start_id"))
        return {}, 200

    @app.route("/c/<name>/update_one", methods=("POST",))
    @guarded
    @mutating
    def update_one(request, name):
        body = request.get_json()
        store.update_one(name, body["query"], body["new_values"])
        return {}, 200

    @app.route("/c/<name>/set_field_values", methods=("POST",))
    @guarded
    @mutating
    def set_field_values(request, name):
        body = request.get_json()
        store.set_field_values(name, body["field"], dict(body["values"]))
        return {}, 200

    @app.route("/c/<name>/set_column", methods=("POST",))
    @guarded
    @mutating
    def set_column(request, name):
        body = request.get_json()
        store.set_column(
            name, body["field"], body["values"], start_id=body.get("start_id", 1)
        )
        return {}, 200

    @app.route("/c/<name>/find", methods=("POST",))
    @guarded
    def find(request, name):
        body = request.get_json()
        documents = list(
            store.find(
                name,
                body.get("query") or {},
                skip=body.get("skip", 0),
                limit=body.get("limit"),
            )
        )
        return {"documents": documents}, 200

    @app.route("/c/<name>/read_columns", methods=("POST",))
    @guarded
    def read_columns(request, name):
        body = request.get_json()
        columns = store.read_columns(
            name,
            body.get("fields"),
            start=body.get("start", 0),
            limit=body.get("limit"),
        )
        return {"columns": columns}, 200

    @app.route("/c/<name>/read_columns_bin", methods=("POST",))
    @guarded
    def read_columns_bin(request, name):
        body = request.get_json()
        if hasattr(store, "read_column_arrays_rev"):
            # rev captured under the same lock as the read — equal revs
            # across chunks prove no write interleaved
            columns, rev = store.read_column_arrays_rev(
                name,
                body.get("fields"),
                start=body.get("start", 0),
                limit=body.get("limit"),
            )
        else:
            columns = store.read_column_arrays(
                name,
                body.get("fields"),
                start=body.get("start", 0),
                limit=body.get("limit"),
            )
            rev = -1
        frame = encode_frame(columns, extra={"rev": rev})
        return Response(frame, mimetype=BIN_CONTENT_TYPE, status=200)

    @app.route("/c/<name>/insert_columns_bin", methods=("POST",))
    @guarded
    @mutating
    def insert_columns_bin(request, name):
        columns, extra = decode_frame(request.get_data())
        store.insert_column_arrays(
            name, columns, start_id=extra.get("start_id")
        )
        return {}, 200

    @app.route("/c/<name>/set_column_bin", methods=("POST",))
    @guarded
    @mutating
    def set_column_bin(request, name):
        columns, extra = decode_frame(request.get_data())
        field = extra["field"]
        store.set_column(
            name, field, columns[field], start_id=extra.get("start_id", 1)
        )
        return {}, 200

    @app.route("/c/<name>/aggregate", methods=("POST",))
    @guarded
    def aggregate(request, name):
        try:
            results = store.aggregate(name, request.get_json()["pipeline"])
        except NotImplementedError as error:
            return {"error": str(error)}, 400
        return {"results": results}, 200

    @app.route("/c/<name>/count", methods=("GET",))
    def count(request, name):
        return {"count": store.count(name)}, 200

    return app


class RemoteStore(DocumentStore):
    """A :class:`DocumentStore` over the store server's wire protocol.

    Drop-in for :class:`InMemoryStore` in every service — this is what
    turns the single-process runner into the reference's seven
    independent containers sharing one database (reference:
    docker-compose.yml:173-330)."""

    def __init__(
        self,
        base_url: str,
        timeout: float = 600.0,
        wire_rows: Optional[int] = None,
    ):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        # Rows per read_columns wire chunk (LO_WIRE_ROWS): bounds every
        # JSON body the data plane ships, mirroring the write batching
        # in core/table.py insert_columns_batched.
        self.wire_rows = max(
            1, wire_rows or int(os.environ.get("LO_WIRE_ROWS", "100000"))
        )
        # Rows per binary-frame chunk: typed buffers are ~10× denser
        # than JSON, so the binary plane pages in much larger strides.
        self.wire_rows_bin = max(
            1, int(os.environ.get("LO_WIRE_ROWS_BIN", "2000000"))
        )
        self._local = threading.local()

    # one session per thread: requests.Session pools connections but is
    # not formally thread-safe
    @property
    def _session(self) -> requests.Session:
        session = getattr(self._local, "session", None)
        if session is None:
            session = requests.Session()
            self._local.session = session
        return session

    def _raise_for(self, response) -> None:
        if response.status_code == 409:
            raise KeyError(response.json().get("error", "duplicate"))
        if response.status_code == 400:
            payload = response.json()
            if payload.get("kind") == "unsupported_query":
                raise UnsupportedQueryError(payload.get("error", "bad query"))
            raise ValueError(payload.get("error", "bad request"))
        if response.status_code == 503:
            raise PermissionError(
                response.json().get("error", "read-only follower")
            )
        response.raise_for_status()

    def _post(self, path: str, body: dict) -> dict:
        response = self._session.post(
            f"{self.base_url}{path}",
            data=json.dumps(body),
            headers={"Content-Type": "application/json"},
            timeout=self.timeout,
        )
        self._raise_for(response)
        return response.json()

    def _post_frame(self, path: str, frame: bytes) -> dict:
        response = self._session.post(
            f"{self.base_url}{path}",
            data=frame,
            headers={"Content-Type": BIN_CONTENT_TYPE},
            timeout=self.timeout,
        )
        self._raise_for(response)
        return response.json()

    def _post_for_frame(self, path: str, body: dict):
        """POST JSON, receive a binary columnar frame."""
        response = self._session.post(
            f"{self.base_url}{path}",
            data=json.dumps(body),
            headers={"Content-Type": "application/json"},
            timeout=self.timeout,
        )
        self._raise_for(response)
        return decode_frame(response.content)

    def _get(self, path: str) -> dict:
        response = self._session.get(f"{self.base_url}{path}", timeout=self.timeout)
        self._raise_for(response)
        return response.json()

    def _delete(self, path: str) -> dict:
        response = self._session.delete(f"{self.base_url}{path}", timeout=self.timeout)
        self._raise_for(response)
        return response.json()

    # --- DocumentStore implementation -----------------------------------------
    def list_collections(self) -> list[str]:
        return self._get("/collections")["collections"]

    def create_collection(self, collection: str) -> bool:
        return self._post(f"/collections/{collection}", {})["created"]

    def drop(self, collection: str) -> None:
        self._delete(f"/collections/{collection}")

    def insert_one(self, collection: str, document: dict) -> None:
        self._post(f"/c/{collection}/insert_one", {"document": document})

    def insert_many(self, collection: str, documents: list[dict]) -> None:
        self._post(f"/c/{collection}/insert_many", {"documents": documents})

    def insert_columns(
        self,
        collection: str,
        columns: dict,
        start_id: Optional[int] = None,
    ) -> None:
        from learningorchestra_tpu.core.store import as_column

        self.insert_column_arrays(
            collection,
            {name: as_column(values) for name, values in columns.items()},
            start_id=start_id,
        )

    def insert_column_arrays(
        self,
        collection: str,
        columns: dict[str, Column],
        start_id: Optional[int] = None,
    ) -> None:
        """Typed columns ride the binary wire, paged in
        ``wire_rows_bin`` strides so one call never builds an unbounded
        frame. Client-side ragged validation keeps the error local."""
        lengths = {len(column) for column in columns.values()}
        if len(lengths) > 1:
            raise ValueError("ragged columns")
        num_rows = lengths.pop() if lengths else 0
        if not columns:
            return
        stride = self.wire_rows_bin
        for offset in range(0, max(num_rows, 1), stride):
            stop = min(offset + stride, num_rows)
            chunk = {
                name: column.slice(offset, stop)
                for name, column in columns.items()
            }
            extra = {
                "start_id": None if start_id is None else start_id + offset
            }
            self._post_frame(
                f"/c/{collection}/insert_columns_bin",
                encode_frame(chunk, extra=extra),
            )
            if stop >= num_rows:
                break

    def update_one(self, collection: str, query: dict, new_values: dict) -> None:
        self._post(
            f"/c/{collection}/update_one",
            {"query": query, "new_values": new_values},
        )

    def set_field_values(
        self, collection: str, field: str, values_by_id: dict
    ) -> None:
        self._post(
            f"/c/{collection}/set_field_values",
            {"field": field, "values": list(values_by_id.items())},
        )

    def set_column(
        self, collection: str, field: str, values, start_id: int = 1
    ) -> None:
        from learningorchestra_tpu.core.store import as_column

        column = as_column(values)
        # Page large replaces in strides; each stride is itself a
        # contiguous set_column at the shifted start_id.
        stride = self.wire_rows_bin
        for offset in range(0, max(len(column), 1), stride):
            stop = min(offset + stride, len(column))
            self._post_frame(
                f"/c/{collection}/set_column_bin",
                encode_frame(
                    {field: column.slice(offset, stop)},
                    extra={"field": field, "start_id": start_id + offset},
                ),
            )
            if stop >= len(column):
                break

    def find(
        self,
        collection: str,
        query: Optional[dict] = None,
        skip: int = 0,
        limit: Optional[int] = None,
    ) -> Iterator[dict]:
        payload = self._post(
            f"/c/{collection}/find",
            {"query": query or {}, "skip": skip, "limit": limit},
        )
        return iter(payload["documents"])

    def read_columns(
        self,
        collection: str,
        fields: Optional[list[str]] = None,
        start: int = 0,
        limit: Optional[int] = None,
    ) -> dict[str, list]:
        """Paged on the wire: rows travel in ``wire_rows`` chunks (the
        read half of ``insert_columns_batched``'s write batching), so a
        10M-row dataset never rides one giant JSON body. The chunk loop
        stops at a short chunk; an explicit ``limit`` caps the total."""
        out: dict[str, list] = {}
        fetched = 0
        while True:
            chunk_limit = self.wire_rows
            if limit is not None:
                chunk_limit = min(chunk_limit, limit - fetched)
                if chunk_limit <= 0:
                    break
            chunk = self._post(
                f"/c/{collection}/read_columns",
                {
                    "fields": fields,
                    "start": start + fetched,
                    "limit": chunk_limit,
                },
            )["columns"]
            if not out:
                out = {name: list(values) for name, values in chunk.items()}
            else:
                for name, values in chunk.items():
                    out[name].extend(values)
            chunk_rows = max((len(v) for v in chunk.values()), default=0)
            fetched += chunk_rows
            # Short chunk = exhausted; empty chunk breaks unconditionally
            # so a degenerate chunk_limit can never spin forever.
            if chunk_rows < chunk_limit or chunk_rows == 0:
                break
        return out

    def read_column_arrays(
        self,
        collection: str,
        fields: Optional[list[str]] = None,
        start: int = 0,
        limit: Optional[int] = None,
    ) -> dict[str, Column]:
        """Typed columns over the binary wire, paged in
        ``wire_rows_bin`` strides. Multi-chunk reads are NOT one atomic
        store snapshot; the server echoes the collection's mutation
        counter per chunk, and a mismatch (a write landed between
        chunks) restarts the read — after ``LO_READ_RETRIES`` (default
        3) torn attempts the last result is returned best-effort, which
        matches the reference's own read semantics (Mongo cursors don't
        snapshot either)."""
        retries = int(os.environ.get("LO_READ_RETRIES", "3"))
        for _ in range(max(retries, 1)):
            out, torn = self._read_column_arrays_once(
                collection, fields, start, limit, check_rev=True
            )
            if not torn:
                return out
        # Still torn after retries: read to completion WITHOUT the rev
        # check — complete but non-snapshot, the Mongo-cursor semantics
        # (never a silently truncated result).
        out, _ = self._read_column_arrays_once(
            collection, fields, start, limit, check_rev=False
        )
        return out

    def _read_column_arrays_once(
        self,
        collection: str,
        fields: Optional[list[str]],
        start: int,
        limit: Optional[int],
        check_rev: bool = True,
    ) -> tuple[dict[str, Column], bool]:
        out: dict[str, Column] = {}
        fetched = 0
        rev: Optional[int] = None
        while True:
            chunk_limit = self.wire_rows_bin
            if limit is not None:
                chunk_limit = min(chunk_limit, limit - fetched)
                if chunk_limit <= 0:
                    break
            columns, extra = self._post_for_frame(
                f"/c/{collection}/read_columns_bin",
                {
                    "fields": fields,
                    "start": start + fetched,
                    "limit": chunk_limit,
                },
            )
            chunk_rev = extra.get("rev", -1)
            if rev is None:
                rev = chunk_rev
            elif check_rev and rev != -1 and chunk_rev != rev:
                return out, True  # a write interleaved: torn read
            elif chunk_rev != rev:
                rev = chunk_rev  # unchecked mode: follow the rev along
            if not out:
                out = columns
            else:
                for name, column in columns.items():
                    existing = out.get(name)
                    if existing is None:
                        # field appeared mid-read (unchecked mode):
                        # earlier rows lack it → pad prefix
                        existing = Column.pads(fetched)
                    out[name] = existing.append_column(column)
            chunk_rows = max((len(c) for c in columns.values()), default=0)
            fetched += chunk_rows
            if chunk_rows < chunk_limit or chunk_rows == 0:
                break
        return out, False

    def aggregate(self, collection: str, pipeline: list[dict]) -> list[dict]:
        return self._post(f"/c/{collection}/aggregate", {"pipeline": pipeline})[
            "results"
        ]

    def count(self, collection: str) -> int:
        return self._get(f"/c/{collection}/count")["count"]


def connect(url: Optional[str] = None) -> DocumentStore:
    """The services' store factory: a :class:`RemoteStore` when a store
    URL is configured (``LO_STORE_URL`` — the analogue of the reference's
    ``DATABASE_URL``), else a process-local WAL-backed store."""
    url = url if url is not None else os.environ.get("LO_STORE_URL")
    if url:
        return RemoteStore(url)
    data_dir = os.environ.get("LO_DATA_DIR")
    return InMemoryStore(data_dir=data_dir)


class ReplicationClient:
    """Follower-side WAL shipper: polls the primary's ``GET /wal`` and
    applies new records to the local store — the role Mongo's secondary
    oplog tailing plays in the reference's replica set
    (docker-compose.yml:27-91). On a stale epoch (the primary
    compacted) the local store resets and re-pulls from record 0, where
    the compacted snapshot now lives. ``stop()`` (or ``POST /promote``
    on the follower's server) halts shipping for failover."""

    def __init__(
        self,
        store: InMemoryStore,
        primary_url: str,
        interval: float = 0.5,
        batch: int = 10000,
    ):
        self.store = store
        self.primary_url = primary_url.rstrip("/")
        self.interval = interval
        self.batch = batch
        self.epoch = -1
        self.offset = 0
        # A resync signal only marks intent; local state is replaced
        # atomically when the replacement records are actually in hand
        # (resync_apply) — never truncated on the signal alone, so a
        # primary that dies mid-resync cannot leave the follower empty.
        self._pending_resync = True
        self.last_error: Optional[str] = None
        self._stop = threading.Event()
        # Serializes apply against stop(): once stop() returns, no
        # further records can land (promote must not race an in-flight
        # poll into applying the old primary's records after new writes).
        self._apply_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    def poll_once(self) -> int:
        """One fetch+apply round; returns the number of records applied."""
        response = requests.get(
            f"{self.primary_url}/wal",
            params={
                "epoch": self.epoch,
                "offset": self.offset,
                "limit": self.batch,
            },
            timeout=60,
        )
        response.raise_for_status()
        feed = response.json()
        with self._apply_lock:
            if self._stop.is_set():
                return 0
            if feed["resync"]:
                self.epoch = feed["epoch"]
                self.offset = 0
                self._pending_resync = True
                return 0
            try:
                if self._pending_resync and feed["offset"] == 0:
                    self.store.resync_apply(feed["records"])
                    self._pending_resync = False
                else:
                    self.store.apply_replicated(feed["records"])
            except Exception:
                # A mid-batch failure (divergence, duplicate id) leaves
                # an ambiguous prefix applied; re-pulling the same batch
                # would fail forever. Self-heal: force a full resync.
                self.epoch = -1
                self.offset = 0
                self._pending_resync = True
                raise
            self.offset = feed["next"]
            return len(feed["records"])

    def run(self) -> None:
        while not self._stop.is_set():
            try:
                applied = self.poll_once()
                self.last_error = None
            except Exception as error:  # primary down: keep serving reads
                self.last_error = str(error)
                applied = 0
            if applied == 0:
                self._stop.wait(self.interval)

    def start(self) -> "ReplicationClient":
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        """Halt shipping. On return, no further records will be applied:
        the stop flag is checked under the apply lock, so an in-flight
        poll either finished applying before this or discards its
        response."""
        self._stop.set()
        with self._apply_lock:
            pass
        if self._thread is not None:
            self._thread.join(timeout=10)


def serve(
    host: str = "127.0.0.1",
    port: int = DEFAULT_STORE_PORT,
    data_dir: Optional[str] = None,
    replicate: bool = False,
    primary_url: Optional[str] = None,
) -> ServerThread:
    """Start a store server thread; returns it (caller stops).

    ``replicate=True`` keeps the in-memory WAL buffer so followers can
    ship the log; ``primary_url`` starts THIS server as a follower of
    that primary (read-only until promoted). The server's ``role`` dict
    and poller are attached to the returned thread as ``.store_role`` /
    ``.replication`` for operators and tests.
    """
    store = InMemoryStore(
        data_dir=data_dir, replicate=replicate or primary_url is not None
    )
    role = {"writable": primary_url is None, "poller": None}
    if primary_url is not None:
        role["poller"] = ReplicationClient(store, primary_url).start()
    server = ServerThread(create_store_app(store, role), host, port).start()
    server.store = store
    server.store_role = role
    server.replication = role["poller"]
    if replicate or primary_url is not None:
        # The replication feed duplicates the write history in RAM —
        # on the primary AND on every follower (a follower re-logs each
        # applied record so it is promotable with full durability).
        # Compact when it grows past LO_COMPACT_RECORDS: the snapshot
        # replaces the history; on the primary the epoch bump resyncs
        # followers, on a follower compaction is purely local (the
        # poller's cursor tracks the PRIMARY's epoch, not the local
        # one), and a follower promoted later keeps compacting.
        threshold = int(os.environ.get("LO_COMPACT_RECORDS", "200000"))
        stop = threading.Event()

        def maintain():
            while not stop.wait(10.0):
                if store.wal_length > threshold:
                    store.compact()

        thread = threading.Thread(target=maintain, daemon=True)
        thread.start()
        server.compaction_stop = stop
    return server


def main() -> None:
    host = os.environ.get("LO_HOST", "127.0.0.1")
    port = int(os.environ.get("LO_STORE_PORT", DEFAULT_STORE_PORT))
    data_dir = os.environ.get("LO_DATA_DIR")
    replicate = os.environ.get("LO_REPLICATE") == "1"
    primary_url = os.environ.get("LO_PRIMARY_URL")
    server = serve(host, port, data_dir, replicate, primary_url)
    mode = (
        f"follower of {primary_url}"
        if primary_url
        else ("primary (replicating)" if replicate else "standalone")
    )
    print(
        f"store server on {host}:{server.port} (data_dir={data_dir}, {mode})",
        flush=True,
    )
    server._thread.join()


if __name__ == "__main__":
    main()
