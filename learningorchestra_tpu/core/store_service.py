"""The store as a network service: HTTP wire protocol + client backend.

The reference's only data plane is a MongoDB replica set every service
container points at via ``DATABASE_URL`` (reference:
docker-compose.yml:27-91 replica set, :188-192 per-service env). This
module is that role for the TPU framework: a store server process
exposing the full :class:`DocumentStore` interface over HTTP, and
:class:`RemoteStore`, the client backend the seven services use to run
as independent processes/containers against one shared store.

Wire protocol (JSON bodies; both ends are this module, so it is an
internal contract, versioned by the framework):

- ``GET  /collections``                         → ``{"collections": [...]}``
- ``POST /collections/<name>``                  → ``{"created": bool}`` (atomic claim)
- ``DELETE /collections/<name>``                → ``{}``
- ``POST /c/<name>/insert_one``     ``{"document": {...}}``
- ``POST /c/<name>/insert_many``    ``{"documents": [...]}``
- ``POST /c/<name>/insert_columns`` ``{"columns": {...}, "start_id": n|null}``
- ``POST /c/<name>/update_one``     ``{"query": {...}, "new_values": {...}}``
- ``POST /c/<name>/set_field_values`` ``{"field": f, "values": [[id, v], ...]}``
  (id/value pairs, not an object — JSON objects would stringify int ids)
- ``POST /c/<name>/set_column``     ``{"field": f, "values": [...], "start_id": n}``
- ``POST /c/<name>/find``           ``{"query", "skip", "limit"}`` → ``{"documents"}``
- ``POST /c/<name>/read_columns``   ``{"fields": [...]|null}`` → ``{"columns"}``
- ``POST /c/<name>/aggregate``      ``{"pipeline": [...]}`` → ``{"results"}``
- ``GET  /c/<name>/count``                      → ``{"count": n}``
- ``GET  /health``                              → ``{"ok": true, "writable": bool}``
- ``GET  /wal?epoch&offset&limit``              → WAL feed for followers
- ``POST /promote``                             → follower becomes writable

Binary columnar verbs (typed buffers, ``core/wire.py`` framing — the
data plane large datasets actually ride; the JSON forms above remain
for small bodies and debuggability):

- ``POST /c/<name>/read_columns_bin``  JSON ``{"fields","start","limit"}``
  → ``application/x-lo-columns`` frame; the frame's ``extra`` carries
  ``rev`` (collection mutation counter) so paged readers can detect a
  write landing between chunks and retry instead of returning a torn
  result.
- ``POST /c/<name>/insert_columns_bin``  frame with ``extra.start_id``
- ``POST /c/<name>/set_column_bin``      frame with ``extra.field`` /
  ``extra.start_id``

Error mapping: ``KeyError`` (duplicate ids/collections) → 409;
``UnsupportedQueryError`` → 400 with ``kind: unsupported_query``; other
``ValueError`` → 400; mutation on a follower → 503. :class:`RemoteStore`
re-raises the same exception types, so service code behaves identically
on a local or remote store.

Durability/replication posture: the server runs one WAL-backed
:class:`InMemoryStore`; the WAL is the durability story and the primary
is the single writer. HA mirrors the reference's Mongo replica set
(docker-compose.yml:27-91) with WAL shipping: a primary started with
``LO_REPLICATE=1`` feeds ``GET /wal``; followers started with
``LO_PRIMARY_URL`` tail it (:class:`ReplicationClient`, the oplog-tailing
secondary role), serve reads, reject writes with 503, and take over on
``POST /promote``.

Failover is automatic when configured (the replica-set election the
reference gets from its Mongo arbiter, docker-compose.yml:49-91):

- ``LO_AUTO_PROMOTE_S=<seconds>`` — a follower whose primary has been
  unreachable for that long promotes ITSELF (no operator ``POST
  /promote`` needed). Two-node semantics, stated honestly: with exactly
  one follower there is no quorum to consult, so a network partition
  between the pair can open a write-accepting server on each side; the
  term fence below heals it in favor of the newest promotion when they
  reconnect.
- ``LO_ARBITERS=<url,...>`` — QUORUM mode (docs/replication.md): the
  vote-only arbiter (core/arbiter.py — the reference's
  ``mongodbarbiter``) joins the voting population, and failover becomes
  *prevented* rather than healed: a follower auto-promotes only after
  winning a majority of votes for an explicit term, and a primary that
  cannot reach a majority of voters SUSPENDS writes (503 +
  ``Retry-After``; reads keep serving) until quorum returns — the
  minority side of a partition degrades gracefully instead of opening
  a second primary.
- ``LO_STORE_SYNC_REPL=1`` — acknowledge mutations only once a
  follower's WAL cursor has passed them (bounded by
  ``LO_STORE_ACK_TIMEOUT_S``): the majority-write-concern analogue
  that makes "zero lost acknowledged writes" hold across a primary
  kill. Off by default; without it the loss window of a takeover is
  *measured and reported* (promotion response, ``/health``,
  ``lo_store_loss_window``) rather than zero.
- Promotions bump a **term** (primary starts at 1; each takeover is
  ``max(seen primary term, own) + 1``), reported by ``/health``.
- ``LO_PEERS=<url,url>`` — fencing: at startup AND every few seconds, a
  writable server probes its peers; seeing a writable peer with a
  HIGHER term means it was superseded while dead/partitioned, and it
  demotes itself to a follower of that peer (full resync replaces any
  diverged local writes). A revived old primary therefore rejoins as a
  follower instead of silently accepting writes (round-3 advisor item).
- :class:`RemoteStore` accepts a comma-separated URL list
  (``LO_STORE_URL=http://a,http://b``) and re-points itself at whichever
  server is writable when a write fails — client writes resume after a
  failover without reconfiguration.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Iterator, Optional

import numpy as np
import requests

from learningorchestra_tpu.core.arbiter import grant_vote
from learningorchestra_tpu.core.columns import Column
from learningorchestra_tpu.core.store import (
    ROW_ID,
    DocumentStore,
    InMemoryStore,
    UnsupportedQueryError,
)
from learningorchestra_tpu.telemetry import profile as _profile
from learningorchestra_tpu.telemetry import tracing as _tracing
from learningorchestra_tpu.testing import faults
from learningorchestra_tpu.core import shmring
from learningorchestra_tpu.core.wire import (
    ACCEPT_HEADER,
    COMPRESS_MIN_BYTES,
    CONTENT_TYPE as BIN_CONTENT_TYPE,
    ENCODING_HEADER,
    WIRE_COMPRESSION,
    WIRE_V2,
    accept_tokens,
    compress_frame,
    decode_body,
    decode_frame,
    encode_frame,
)
from learningorchestra_tpu.utils.web import (
    Response,
    ServerThread,
    Waiter,
    WebApp,
)

DEFAULT_STORE_PORT = 27027


# Deployment-knob readers (sched/config.py pattern): every LO_* env
# read in this module funnels through these so the knob surface stays
# greppable and the contract analyzer (LO305) can verify the
# read-once discipline. The deploy/run.sh preflight validates the
# numeric domains before any service boots; an unset/empty value
# means "use the default" at every call site below.


def _str_env(name: str, default: str | None = None) -> str | None:
    return os.environ.get(name, default)


def _int_env(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError as error:
        raise ValueError(f"{name} must be an integer, got {raw!r}") from error


def _float_env(name: str, default: float | None) -> float | None:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError as error:
        raise ValueError(f"{name} must be a number, got {raw!r}") from error


def _flag_env(name: str, default: bool = False) -> bool:
    """Strict 0/1 flags (the domain deploy/run.sh's preflight
    enforces): unset/empty -> ``default``, else ``raw == "1"``."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    return raw == "1"


class StoreUnavailableError(PermissionError):
    """The store rejected or cannot currently accept a write — a
    read-only follower's 503, a quorum-suspended primary's 503 +
    Retry-After, or no writable server within the failover window.
    Subclasses :class:`PermissionError` for existing handlers;
    classified TRANSIENT by the scheduler's retry policy
    (sched/policy.py) so jobs ride out a failover window with backoff
    instead of failing terminally."""


def _values_match(stored, sent) -> bool:
    """Loose equality for landed-write verification: JSON round-trips
    preserve Python scalar equality, but NaN != NaN needs handling."""
    if stored == sent:
        return True
    try:
        import math

        return math.isnan(stored) and math.isnan(sent)
    except TypeError:
        return False


def create_store_app(
    store: DocumentStore,
    role: Optional[dict] = None,
    shm: Optional[bool] = None,
) -> WebApp:
    """``role`` (mutable, shared with the caller) carries the HA state:
    ``{"writable": bool, "poller": ReplicationClient | None}``. A
    follower serves every read with ``writable: False`` and answers
    mutations with 503 until ``POST /promote`` flips it — the failover
    the reference delegates to Mongo's replica-set election
    (docker-compose.yml:27-91). ``shm`` overrides the env-derived
    shared-memory-transport enablement (tests, bench)."""
    app = WebApp("store")
    # Shared-memory ring transport (core/shmring.py): enabled when this
    # server's LO_SHM_BYTES > 0 — the runner/stack exports one value to
    # the whole co-located process tree, so client and server agree.
    # Read at app creation (not import) so tests can toggle the env.
    shm_enabled = shmring.shm_bytes() > 0 if shm is None else bool(shm)
    rings = shmring.ServerRings()
    # the store SERVER scrapes its own occupancy (collections, WAL
    # bytes, spill bytes) at GET /metrics; remote-store CLIENTS don't
    from learningorchestra_tpu.telemetry import register_store

    role = role if role is not None else {"writable": True, "poller": None}
    role.setdefault("term", 1 if role.get("writable", True) else 0)
    # serializes promote/demote transitions (HTTP promote vs the
    # auto-promote monitor vs the fencing probe)
    role.setdefault("lock", threading.Lock())
    # quorum-mode degradation: a primary that lost its voter majority
    # suspends writes (503 + Retry-After) while reads keep serving
    role.setdefault("suspended", False)
    # one-vote-per-term election ledger (grant_vote; docs/replication.md)
    role.setdefault("voted_term", 0)
    role.setdefault("voted_for", None)
    # sync-replication ack ledger: highest (epoch, offset) any follower
    # has requested the WAL from — a follower requests from its APPLIED
    # position, so this is what a replica durably holds
    role.setdefault("shipped", (-1, -1))
    role.setdefault("repl_cv", threading.Condition())
    role.setdefault("unreplicated_acks", 0)
    register_store(store, role=role)

    def guarded(handler):
        def wrapped(request, **kwargs):
            try:
                return handler(request, **kwargs)
            except KeyError as error:
                return {"error": str(error)}, 409
            except UnsupportedQueryError as error:
                return {"error": str(error), "kind": "unsupported_query"}, 400
            except ValueError as error:
                return {"error": str(error)}, 400

        wrapped.__name__ = handler.__name__
        return wrapped

    def mutating(handler):
        def wrapped(request, **kwargs):
            if not role.get("writable", True):
                return {"error": "read-only follower; POST /promote"}, 503
            if role.get("suspended"):
                # quorum lost: this (possibly minority-side) primary
                # refuses writes instead of risking a second primary;
                # Retry-After tells well-behaved clients (and the
                # scheduler's transient-retry policy) to come back
                response = Response(
                    json.dumps(
                        {
                            "error": (
                                "writes suspended: quorum lost "
                                "(reads keep serving)"
                            ),
                            "kind": "writes_suspended",
                        }
                    ),
                    mimetype="application/json",
                    status=503,
                )
                response.headers["Retry-After"] = "1"
                return response
            faults.fire("store.wire.mutate", route=handler.__name__)
            result = handler(request, **kwargs)
            faults.fire("store.wire.mutate.applied", route=handler.__name__)
            if (
                role.get("sync_repl")
                and getattr(store, "replicating", False)
                and isinstance(result, tuple)
                and result[1] == 200
                and isinstance(result[0], dict)
            ):
                if not _await_replicated(role, store):
                    # the wait timed out (follower down/lagging): the
                    # write IS applied and logged locally — flag the ack
                    # so callers and operators can see the degraded
                    # durability instead of silently assuming majority
                    with role["lock"]:
                        role["unreplicated_acks"] += 1
                    result = ({**result[0], "replicated": False}, 200)
            return result

        wrapped.__name__ = handler.__name__
        return wrapped

    @app.route("/health", methods=("GET",))
    def health(request):
        payload = {
            "ok": True,
            "writable": role.get("writable", True),
            "suspended": role.get("suspended", False),
            "term": role.get("term", 0),
            # election evidence for the supersession check: a voter
            # that granted a higher term exposes it here (the arbiter
            # does the same) so a quorum-holding primary partitioned
            # from the WINNER still hears about the election through
            # any voter it can reach — store voters included, not just
            # arbiters
            "voted_term": role.get("voted_term", 0),
            "boot": role.get("boot", ""),  # equal-term fence tiebreak
            # wire capability advertisement: bin2 = this server decodes
            # AND (when asked via X-Lo-Columns-Accept: v2) emits the
            # aligned zero-copy frame layout; clients probe it once to
            # decide their upload encoding (reads negotiate per request)
            "columns_wire": "bin2",
            "shm": shm_enabled,
        }
        stats = getattr(store, "telemetry_stats", None)
        if stats is not None:
            # occupancy surface for the sharded fleet: the client-side
            # shard gauges (telemetry/metrics.py register_sharded_store)
            # read each group's collection/WAL/spill occupancy here
            try:
                payload["occupancy"] = stats()
            except Exception:  # noqa: BLE001 — health must still answer
                pass
        poller = role.get("poller")
        if poller is not None:
            payload["replication"] = {
                "lag": poller.lag,
                "caught_up": poller.caught_up,
                "last_error": poller.last_error,
            }
        if role.get("loss_window") is not None:
            # what this server's last takeover cost (docs/replication.md
            # loss-window semantics); also exported as
            # lo_store_loss_window on /metrics
            payload["loss_window"] = role["loss_window"]
        # SLO verdict over the in-store TSDB (telemetry/slo.py):
        # best-effort — health must answer even when the ring is empty
        # or the evaluation trips on a half-written tick
        try:
            from learningorchestra_tpu.telemetry import slo as _slo

            payload["degraded"] = bool(_slo.status(store)["degraded"])
        except Exception:  # noqa: BLE001
            payload["degraded"] = False
        return payload, 200

    @app.route("/vote", methods=("POST",))
    def vote(request):
        """One quorum-election vote (core/arbiter.py semantics): every
        store server is also a voter. A live, unsuspended primary
        vetoes — an election is only legitimate once the primary is
        actually unreachable or degraded."""
        body = request.get_json()
        try:
            term = int(body["term"])
            candidate = str(body["candidate"])
        except (KeyError, TypeError, ValueError):
            return {"error": "vote needs integer term + candidate"}, 400
        with role["lock"]:
            if role.get("writable") and not role.get("suspended"):
                return {
                    "granted": False,
                    "term": role.get("term", 0),
                    "writable": True,
                }, 200
            return grant_vote(role, term, candidate), 200

    @app.route("/wal", methods=("GET",))
    def wal(request):
        try:
            epoch = int(request.args.get("epoch", -1))
            offset = int(request.args.get("offset", 0))
            limit = int(request.args.get("limit", 10000))
            wait_s = float(request.args.get("wait", 0))
        except ValueError:
            return {"error": "epoch/offset/limit/wait must be numbers"}, 400
        faults.fire("store.wal.feed")
        try:
            feed = store.wal_feed(epoch, offset, limit=limit)
        except (AttributeError, ValueError):
            return {"error": "replication not enabled (LO_REPLICATE=1)"}, 404

        def ship_ack(resync: bool) -> None:
            # Sync-repl ack ledger: a follower requests from its APPLIED
            # position, so this request's (epoch, offset) is what a
            # replica durably holds — wake writers in _await_replicated.
            cv = role.get("repl_cv")
            if cv is not None and not resync:
                with cv:
                    if (epoch, offset) > tuple(role.get("shipped", (-1, -1))):
                        role["shipped"] = (epoch, offset)
                        cv.notify_all()

        if wait_s > 0 and not feed["records"] and not feed["resync"]:
            # LONG-POLL on the shared waiter machinery (utils/webloop):
            # a caught-up follower parks here until a record lands or
            # the wait expires — this is what keeps sync-repl ack
            # latency at ~tens of milliseconds rather than one poll
            # period per acknowledged mutation. Under the event-loop
            # server the CONNECTION parks (no thread per waiting
            # replica); the threaded escape hatch blocks the request
            # thread as before. The ack ledger updates before parking:
            # it reflects the request's applied position, not the
            # response. Old followers that send no `wait` keep the
            # plain immediate-answer behavior.
            ship_ack(False)

            def wal_ready():
                current_epoch, current_length = store.wal_position
                if current_epoch != epoch or current_length > offset:
                    fresh = store.wal_feed(epoch, offset, limit=limit)
                    fresh["term"] = role.get("term", 0)
                    return fresh, 200
                return None

            def wal_timeout():
                stale = dict(feed)
                stale["term"] = role.get("term", 0)
                return stale, 200

            return Waiter(
                wal_ready,
                min(wait_s, 30.0),
                wal_timeout,
                interval_s=0.05,  # the WAL has no push hook; re-poll
            )
        feed["term"] = role.get("term", 0)  # followers track it for takeover
        ship_ack(bool(feed["resync"]))
        return feed, 200

    @app.route("/compact", methods=("POST",))
    def compact(request):
        if not hasattr(store, "compact"):
            return {"error": "store does not support compaction"}, 404
        # compacted: false = skipped (another compaction in flight) or
        # superseded by a replication resync — the caller must NOT
        # assume the on-disk log is a fresh snapshot
        compacted = bool(store.compact())
        return {"compacted": compacted}, 200

    @app.route("/promote", methods=("POST",))
    def promote(request):
        """Flip this follower writable (also invoked internally by the
        auto-promote monitor). The response reports the last WAL
        position applied from the old primary and whether the follower
        had drained the feed, so the operator can see the acknowledged
        replication lag (records the dead primary accepted but never
        shipped are LOST — durability follows the new primary from
        here). The term bump is what fences a revived old primary: it
        comes back with a lower term, sees this server's higher one via
        LO_PEERS, and rejoins as a follower."""
        return promote_role(role), 200

    @app.route("/collections", methods=("GET",))
    def list_collections(request):
        return {"collections": store.list_collections()}, 200

    @app.route("/collections/<name>", methods=("POST",))
    @mutating
    def create_collection(request, name):
        return {"created": store.create_collection(name)}, 200

    @app.route("/collections/<name>", methods=("DELETE",))
    @mutating
    def drop(request, name):
        store.drop(name)
        return {}, 200

    @app.route("/c/<name>/insert_one", methods=("POST",))
    @guarded
    @mutating
    def insert_one(request, name):
        store.insert_one(name, request.get_json()["document"])
        return {}, 200

    @app.route("/c/<name>/insert_many", methods=("POST",))
    @guarded
    @mutating
    def insert_many(request, name):
        store.insert_many(name, request.get_json()["documents"])
        return {}, 200

    @app.route("/c/<name>/insert_columns", methods=("POST",))
    @guarded
    @mutating
    def insert_columns(request, name):
        body = request.get_json()
        store.insert_columns(name, body["columns"], start_id=body.get("start_id"))
        return {}, 200

    @app.route("/c/<name>/update_one", methods=("POST",))
    @guarded
    @mutating
    def update_one(request, name):
        body = request.get_json()
        store.update_one(name, body["query"], body["new_values"])
        return {}, 200

    @app.route("/c/<name>/set_field_values", methods=("POST",))
    @guarded
    @mutating
    def set_field_values(request, name):
        body = request.get_json()
        store.set_field_values(name, body["field"], dict(body["values"]))
        return {}, 200

    @app.route("/c/<name>/set_column", methods=("POST",))
    @guarded
    @mutating
    def set_column(request, name):
        body = request.get_json()
        store.set_column(
            name, body["field"], body["values"], start_id=body.get("start_id", 1)
        )
        return {}, 200

    @app.route("/c/<name>/find", methods=("POST",))
    @guarded
    def find(request, name):
        body = request.get_json()
        documents = list(
            store.find(
                name,
                body.get("query") or {},
                skip=body.get("skip", 0),
                limit=body.get("limit"),
            )
        )
        return {"documents": documents}, 200

    @app.route("/c/<name>/read_columns", methods=("POST",))
    @guarded
    def read_columns(request, name):
        body = request.get_json()
        columns = store.read_columns(
            name,
            body.get("fields"),
            start=body.get("start", 0),
            limit=body.get("limit"),
        )
        return {"columns": columns}, 200

    def frame_body(request) -> bytes:
        """The request's frame bytes, wire compression undone (a client
        stamps ENCODING_HEADER on compressed uploads)."""
        return decode_body(
            request.get_data(), request.headers.get(ENCODING_HEADER)
        )

    @app.route("/c/<name>/rev", methods=("GET",))
    def collection_rev(request, name):
        """The collection's mutation counter — what remote device caches
        probe to validate an entry (core/devcache.py). Same counter the
        binary read frames carry per chunk. Every DocumentStore has the
        method (the base class answers -1 = unknown). ``block_rows``
        rides along (same base-class contract) so the sharded client
        (core/shardstore.py) places appends and splits positional reads
        with the one probe it already makes."""
        return {
            "rev": store.collection_rev(name),
            "block_rows": store.collection_block_rows(name),
        }, 200

    @app.route("/c/<name>/read_columns_bin", methods=("POST",))
    @guarded
    def read_columns_bin(request, name):
        body = request.get_json()
        if hasattr(store, "read_column_arrays_rev"):
            # rev captured under the same lock as the read — equal revs
            # across chunks prove no write interleaved
            columns, rev = store.read_column_arrays_rev(
                name,
                body.get("fields"),
                start=body.get("start", 0),
                limit=body.get("limit"),
            )
        else:
            columns = store.read_column_arrays(
                name,
                body.get("fields"),
                start=body.get("start", 0),
                limit=body.get("limit"),
            )
            rev = -1
        accepts = accept_tokens(request.headers.get(ACCEPT_HEADER))
        # frame-version negotiation: emit the aligned zero-copy layout
        # only to a client that advertised it — old clients keep
        # receiving v1 frames, and decode_frame dispatches on the magic
        # either way
        version = 2 if WIRE_V2 in accepts else 1
        frame = encode_frame(columns, extra={"rev": rev}, version=version)
        if faults.torn("store.wire.read_chunk"):
            frame = frame[: max(1, len(frame) // 2)]  # truncated mid-buffer
        segment = request.headers.get(shmring.SEGMENT_HEADER)
        if shm_enabled and segment:
            # co-located fast path: the frame goes into the client's
            # shared-memory ring and the response carries only the slot
            # coordinates — no HTTP body, no compression. An attach
            # failure (not co-located, segment gone) or an oversized
            # frame falls through to the body transparently.
            try:
                seg_bytes = int(request.headers.get(shmring.BYTES_HEADER, 0))
            except ValueError:
                seg_bytes = 0
            placed = rings.place(segment, seg_bytes, frame)
            if placed is not None:
                offset, length, generation = placed
                return Response(
                    b"{}",
                    mimetype="application/json",
                    status=200,
                    headers={
                        shmring.OFFSET_HEADER: str(offset),
                        shmring.LENGTH_HEADER: str(length),
                        shmring.GENERATION_HEADER: str(generation),
                    },
                )
        headers = {}
        if WIRE_COMPRESSION in accepts and len(frame) >= COMPRESS_MIN_BYTES:
            frame = compress_frame(frame)
            headers[ENCODING_HEADER] = WIRE_COMPRESSION
        return Response(
            frame, mimetype=BIN_CONTENT_TYPE, status=200, headers=headers
        )

    @app.route("/c/<name>/insert_columns_bin", methods=("POST",))
    @guarded
    @mutating
    def insert_columns_bin(request, name):
        columns, extra = decode_frame(frame_body(request))
        store.insert_column_arrays(
            name, columns, start_id=extra.get("start_id")
        )
        return {}, 200

    @app.route("/c/<name>/set_column_bin", methods=("POST",))
    @guarded
    @mutating
    def set_column_bin(request, name):
        columns, extra = decode_frame(frame_body(request))
        field = extra["field"]
        store.set_column(
            name, field, columns[field], start_id=extra.get("start_id", 1)
        )
        return {}, 200

    @app.route("/c/<name>/aggregate", methods=("POST",))
    @guarded
    def aggregate(request, name):
        try:
            results = store.aggregate(name, request.get_json()["pipeline"])
        except NotImplementedError as error:
            return {"error": str(error)}, 400
        return {"results": results}, 200

    @app.route("/c/<name>/count", methods=("GET",))
    def count(request, name):
        return {"count": store.count(name)}, 200

    @app.route("/c/<name>/trim", methods=("POST",))
    @guarded
    @mutating
    def trim_collection(request, name):
        removed = store.trim_collection(
            name, request.get_json()["max_docs"]
        )
        return {"removed": removed}, 200

    # fleet observability plane: /metrics/history, /metrics/ingest,
    # /debug/slo — the store head is where the cluster driver's
    # collector posts scraped samples (deploy/cluster.py)
    app.register_observability(store)

    return app


def _await_replicated(role: dict, store) -> bool:
    """Block until a follower's WAL cursor has passed everything in the
    log right now, or the ack timeout expires (sync-replication mode,
    ``LO_STORE_SYNC_REPL=1``). Epoch-aware: a compaction mid-wait bumps
    the epoch, and the snapshot carries the write — a follower draining
    the NEW epoch's log satisfies the wait."""
    import time

    target_epoch, target_offset = store.wal_position
    cv = role["repl_cv"]
    deadline = time.monotonic() + float(role.get("ack_timeout_s", 2.0))
    with cv:
        while True:
            shipped_epoch, shipped_offset = role.get("shipped", (-1, -1))
            if shipped_epoch == target_epoch and shipped_offset >= target_offset:
                return True
            if shipped_epoch > target_epoch:
                # compaction moved the feed mid-wait: the snapshot
                # carries the write, so a follower draining the NEW
                # epoch's log covers it
                current_epoch, current_length = store.wal_position
                if (
                    shipped_epoch >= current_epoch
                    and shipped_offset >= current_length
                ):
                    return True
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            cv.wait(remaining)


def promote_role(role: dict, term: Optional[int] = None) -> dict:
    """Promote the server owning ``role`` to writable primary: stop the
    WAL poller, bump the term past every term this follower has seen
    (or to the explicit quorum-granted ``term`` when the election voted
    one), and record the measured loss window — last-replicated vs the
    primary's last-acknowledged WAL position. Idempotent; shared by
    ``POST /promote`` and the auto-promote monitor."""
    faults.fire("store.promote")
    with role["lock"]:
        poller = role.get("poller")
        applied = None
        caught_up = None
        loss = None
        if poller is not None:
            # halt, not stop (LO202): the fence — no further records
            # can apply — is what promotion needs under the lock; the
            # thread JOIN waits on a poller that may be parked in a
            # 60 s long-poll, and holding role["lock"] through that
            # would block every /vote (elections) and sync-repl ack
            # accounting for the duration. The join runs below, after
            # the lock is released.
            poller.halt()
            applied = {"epoch": poller.epoch, "offset": poller.offset}
            caught_up = poller.caught_up
            # what this takeover COST: acknowledged-but-unshipped records
            # as of the last successful poll (writes the dead primary
            # accepted after that are unknowable from here — stated in
            # docs/replication.md)
            loss = poller.loss_window()
            # floor of 1: a follower that never completed a poll (primary
            # already dead at its start) must still promote PAST the
            # primary's term 1, or the strictly-greater fence would never
            # demote a partitioned-but-alive old primary
            role["term"] = max(
                max(role.get("term", 0), poller.primary_term, 1) + 1,
                term or 0,
            )
            role["poller"] = None
        elif not role.get("writable", True):
            role["term"] = max(
                max(role.get("term", 0), 1) + 1, term or 0
            )
        role["writable"] = True
        role["suspended"] = False
        if loss is not None:
            role["loss_window"] = loss
        payload = {
            "promoted": True,
            "term": role["term"],
            "applied_through": applied,
            # False = the last poll before the primary vanished still had
            # records in flight: acknowledged-but-unshipped writes are lost
            "caught_up": caught_up,
            "loss_window": loss,
        }
    if poller is not None:
        # thread hygiene outside the lock: halt() above already fenced
        # applies, this just reaps the poller thread (stop re-halts,
        # which is idempotent)
        poller.stop()
    return payload


class RemoteStore(DocumentStore):
    """A :class:`DocumentStore` over the store server's wire protocol.

    Drop-in for :class:`InMemoryStore` in every service — this is what
    turns the single-process runner into the reference's seven
    independent containers sharing one database (reference:
    docker-compose.yml:173-330)."""

    def __init__(
        self,
        base_url: str,
        timeout: float = 600.0,
        wire_rows: Optional[int] = None,
        failover_timeout: Optional[float] = None,
        compress: Optional[bool] = None,
        wire_v2: Optional[bool] = None,
        shm_bytes: Optional[int] = None,
    ):
        # A comma-separated ``base_url`` names the replica pair; the
        # client talks to one server at a time and re-points itself at
        # whichever peer answers /health writable when that server dies
        # or answers 503 (follower) — how service writes resume after an
        # auto-promotion without any reconfiguration.
        self.urls = [u.rstrip("/") for u in base_url.split(",") if u.strip()]
        self.base_url = self.urls[0]
        self.failover_timeout = (
            failover_timeout
            if failover_timeout is not None
            else _float_env("LO_FAILOVER_TIMEOUT_S", 30.0)
        )
        self.timeout = timeout
        # Rows per read_columns wire chunk (LO_WIRE_ROWS): bounds every
        # JSON body the data plane ships, mirroring the write batching
        # in core/table.py insert_columns_batched.
        self.wire_rows = max(
            1, wire_rows or _int_env("LO_WIRE_ROWS", 100000)
        )
        # Rows per binary-frame chunk: typed buffers are ~10× denser
        # than JSON, so the binary plane pages in much larger strides.
        self.wire_rows_bin = max(
            1, _int_env("LO_WIRE_ROWS_BIN", 2000000)
        )
        # LO_STORE_COMPRESS=1: zlib the binary frames both ways (the
        # client advertises on reads, stamps its uploads) — worth it on
        # narrow links (tunneled chips, cross-zone stores), off by
        # default where the store is co-located and CPU is the scarcer
        # resource.
        self.compress = (
            _flag_env("LO_STORE_COMPRESS")
            if compress is None
            else compress
        )
        # Retries for ONE failed chunk of a paged binary read before the
        # whole read surfaces the error (the stream resumes at the
        # failed chunk, never from chunk 0).
        self.chunk_retries = max(
            0, _int_env("LO_CHUNK_RETRIES", 2)
        )
        # LO_WIRE_V2=0 is the escape hatch back to v1 frames (the
        # default advertises v2 on reads and, once /health confirms a
        # bin2 server, uploads v2 too — old servers just keep talking
        # v1, negotiated per request through X-Lo-Columns-Accept).
        self.wire_v2 = (
            _flag_env("LO_WIRE_V2", default=True)
            if wire_v2 is None
            else wire_v2
        )
        # upload frame version, decided lazily by one /health probe
        # (None = not probed yet); reads negotiate per request instead
        self._upload_version_cache: Optional[int] = None
        # Shared-memory ring (core/shmring.py): LO_SHM_BYTES > 0 makes
        # this client create a segment and advertise it on binary
        # reads; a server that can attach it answers with ring slots
        # instead of HTTP bodies. Lazy — the segment exists only once a
        # binary read happens; creation failure disables the ring for
        # this client (body transport is always correct).
        self.shm_bytes = (
            shmring.shm_bytes() if shm_bytes is None else int(shm_bytes)
        )
        self._shm_ring = None
        self._shm_failed = False
        self._shm_lock = threading.Lock()
        self._local = threading.local()
        # collection → monotonic time of the last AMBIGUOUS write
        # failure (connection death / timeout / 5xx mid-request) this
        # client saw against it. A later duplicate-id 409 on an
        # explicit-id write to a marked collection is verified by
        # reading the rows back: a higher-level retry (the scheduler
        # re-running an ingest op after a failover window) replays
        # writes that DID land, and used to abort a fully durable
        # ingest with a KeyError (ADVICE r5).
        self._ambiguous_marks: dict[str, float] = {}
        self.landed_ok_window_s = _float_env("LO_LANDED_OK_WINDOW_S", 600.0)
        # Lazily-built read-ahead pool: chunk N+1's network fetch
        # overlaps chunk N's decode (+ inflate). Per-STORE and
        # persistent so the helper threads' requests.Sessions survive
        # across reads (connection reuse — a per-read thread would pay
        # a TCP handshake per read-ahead); width 4 so several
        # concurrent paged readers overlap instead of serializing
        # through one thread (each read keeps at most one prefetch in
        # flight).
        self._prefetch_pool = None
        self._prefetch_lock = threading.Lock()

    @property
    def _prefetch(self):
        # always read under the lock (LO203): the double-checked bare
        # fast path saved one uncontended acquire per paged read —
        # nanoseconds against a wire chunk — at the price of publishing
        # the pool through a race
        with self._prefetch_lock:
            if self._prefetch_pool is None:
                from concurrent.futures import ThreadPoolExecutor

                self._prefetch_pool = ThreadPoolExecutor(
                    max_workers=4, thread_name_prefix="lo-read-ahead"
                )
            return self._prefetch_pool

    # one session per thread: requests.Session pools connections but is
    # not formally thread-safe
    @property
    def _session(self) -> requests.Session:
        session = getattr(self._local, "session", None)
        if session is None:
            session = requests.Session()
            self._local.session = session
        return session

    def _raise_for(self, response) -> None:
        if response.status_code == 409:
            raise KeyError(response.json().get("error", "duplicate"))
        if response.status_code == 400:
            payload = response.json()
            if payload.get("kind") == "unsupported_query":
                raise UnsupportedQueryError(payload.get("error", "bad query"))
            raise ValueError(payload.get("error", "bad request"))
        if response.status_code == 503:
            raise StoreUnavailableError(
                response.json().get("error", "read-only follower")
            )
        response.raise_for_status()

    def _mark_ambiguous(self, collection: Optional[str]) -> None:
        """Remember that a write against ``collection`` failed
        ambiguously — it may have landed. A later 409 on an explicit-id
        write to the collection (within ``LO_LANDED_OK_WINDOW_S``) is
        then verified by read instead of raised as a duplicate."""
        if collection:
            import time

            self._ambiguous_marks[collection] = time.monotonic()

    def _recently_ambiguous(self, collection: Optional[str]) -> bool:
        if not collection:
            return False
        import time

        marked = self._ambiguous_marks.get(collection)
        return (
            marked is not None
            and time.monotonic() - marked <= self.landed_ok_window_s
        )

    @staticmethod
    def _as_http_error(response) -> Exception:
        try:
            response.raise_for_status()
        except requests.HTTPError as error:
            return error
        return requests.HTTPError(
            f"unexpected status {response.status_code}", response=response
        )

    def _finish(
        self, response, ambiguous, landed_ok, collection, verify
    ):
        if response.status_code == 409 and landed_ok:
            if ambiguous:
                # the ids we just re-sent are already present: the
                # pre-failover attempt landed — success
                return response
            if (
                self._recently_ambiguous(collection)
                and verify is not None
                and verify()
            ):
                # a higher-level retry (scheduler re-running the op
                # after an earlier ambiguous failure) replayed a write
                # that DID land: the stored rows match what we just
                # sent byte for byte, so this is idempotent success,
                # not a duplicate (ADVICE r5)
                return response
        self._raise_for(response)
        return response

    def _send(
        self,
        send,
        retry: bool = True,
        landed_ok: bool = False,
        collection: Optional[str] = None,
        verify=None,
    ):
        """Issue ``send(base_url)``, re-pointing at the writable peer on
        connection failure, a follower's/suspended primary's 503, or an
        ambiguous 5xx.

        ``retry=False`` marks non-idempotent calls (inserts whose ids
        the SERVER assigns): replaying one after a mid-write primary
        death could duplicate rows, so those surface the original error
        instead. Everything else is the store's idempotent contract
        surface (inserts at explicit ids, set_column at a start_id,
        reads). The probe loop rides out the auto-promote window
        (LO_FAILOVER_TIMEOUT_S).

        ``landed_ok=True`` marks explicit-id writes, and means: a
        duplicate-id 409 on an attempt that FOLLOWS an ambiguous
        failure (connection death / timeout / 5xx mid-request) is the
        write we just sent having already landed before the old primary
        died — treat it as success instead of raising ``KeyError``, so
        a long chunked ingest survives a failover mid-batch. A 409 on a
        clean first attempt is a genuine duplicate and still raises —
        UNLESS this client recently saw an ambiguous failure on the
        same ``collection`` and ``verify()`` confirms the stored rows
        equal what was just sent (the cross-call replay of a landed
        write, e.g. the scheduler retrying a whole ingest op)."""
        import time

        ambiguous = False  # a send died mid-request: it may have landed
        last_error: Optional[Exception] = None
        # 5xx RESPONSES get a small retry budget, not the whole failover
        # window: a handler that 500s deterministically (a bug, not a
        # dying server) must fail in a few attempts instead of hammering
        # every replica for LO_FAILOVER_TIMEOUT_S. Connection-level
        # failures keep the full window — those mean a server is gone
        # and riding out the takeover is the point.
        server_error_budget = max(2, self.chunk_retries)
        server_errors = 0
        try:
            response = send(self.base_url)
        # Timeout included: a partitioned/hung primary raises ReadTimeout
        # (not a ConnectionError subclass) and must also re-point —
        # explicit-id retries stay safe either way (duplicate-id 409 if
        # the write had landed, swallowed under landed_ok)
        except (requests.ConnectionError, requests.Timeout) as error:
            if landed_ok:
                self._mark_ambiguous(collection)
            if len(self.urls) == 1 or not retry:
                raise
            ambiguous = True
            last_error = error
        else:
            failed_5xx = (
                response.status_code >= 500 and response.status_code != 503
            )
            if failed_5xx and landed_ok:
                # a 5xx mid-request is as ambiguous as a dropped
                # connection: the handler may have applied before dying.
                # Marked on EVERY such response — single-URL clients
                # included — so a scheduler-level replay of the op can
                # verify its clean-attempt 409 instead of aborting a
                # durable ingest (the connection-death path above marks
                # before raising for the same reason).
                self._mark_ambiguous(collection)
            if response.status_code == 503 and len(self.urls) > 1:
                # a 503 is a CLEAN rejection (nothing was applied), so
                # even non-retryable auto-id inserts may safely re-point
                # and retry — the retry flag only guards AMBIGUOUS
                # failures
                pass
            elif failed_5xx and retry and len(self.urls) > 1:
                ambiguous = True
                server_errors = 1
                last_error = self._as_http_error(response)
            else:
                return self._finish(
                    response, False, landed_ok, collection, verify
                )
        deadline = time.monotonic() + self.failover_timeout
        while True:
            alive = []
            for url in self.urls:
                health = probe_health(url)
                if health:
                    alive.append((not health.get("writable"), url))
            # writable server first; else any live one (serves reads now,
            # answers writes 503 until its auto-promotion fires)
            for _, url in sorted(alive):
                try:
                    response = send(url)
                except (requests.ConnectionError, requests.Timeout) as error:
                    if landed_ok:
                        self._mark_ambiguous(collection)
                    if not retry:
                        # entered via a clean 503, but THIS attempt died
                        # ambiguously mid-request: a non-idempotent call
                        # must not be replayed again
                        raise
                    ambiguous = True
                    last_error = error
                    continue  # just died too; try the next
                if response.status_code == 503:
                    continue
                if response.status_code >= 500 and retry:
                    if landed_ok:
                        self._mark_ambiguous(collection)
                    ambiguous = True
                    server_errors += 1
                    last_error = self._as_http_error(response)
                    if server_errors > server_error_budget:
                        raise last_error
                    continue
                if url != self.base_url:
                    self.base_url = url
                    # the peer we failed over to may speak a different
                    # frame version (rolling upgrade: a bin2 primary
                    # dying onto a v1-only follower) — re-probe before
                    # the next upload instead of shipping frames the
                    # new server cannot decode
                    self._upload_version_cache = None
                return self._finish(
                    response, ambiguous, landed_ok, collection, verify
                )
            if time.monotonic() > deadline:
                if last_error is not None:
                    raise last_error
                raise StoreUnavailableError(
                    "no writable store server among "
                    + ",".join(self.urls)
                )
            time.sleep(0.3)

    def _post(
        self,
        path: str,
        body: dict,
        retry: bool = True,
        landed_ok: bool = False,
        collection: Optional[str] = None,
        verify=None,
    ) -> dict:
        data = json.dumps(body)
        return self._send(
            lambda base: self._session.post(
                f"{base}{path}",
                data=data,
                headers={"Content-Type": "application/json"},
                timeout=self.timeout,
            ),
            retry=retry,
            landed_ok=landed_ok,
            collection=collection,
            verify=verify,
        ).json()

    def _post_frame(
        self,
        path: str,
        frame: bytes,
        landed_ok: bool = False,
        collection: Optional[str] = None,
        verify=None,
    ) -> dict:
        headers = {"Content-Type": BIN_CONTENT_TYPE}
        if collection is not None:
            # flight-recorder attribution: payload bytes (pre-compression
            # — the decode-side cost a reader will pay) into
            # lo_wire_bytes_total and the ambient span (profile.py)
            _profile.account_wire("write", collection, len(frame))
        if self.compress and len(frame) >= COMPRESS_MIN_BYTES:
            frame = compress_frame(frame)
            headers[ENCODING_HEADER] = WIRE_COMPRESSION
        return self._send(
            lambda base: self._session.post(
                f"{base}{path}",
                data=frame,
                headers=headers,
                timeout=self.timeout,
            ),
            landed_ok=landed_ok,
            collection=collection,
            verify=verify,
        ).json()

    def _upload_version(self) -> int:
        """Frame version for uploads: 2 once one lazy ``/health`` probe
        confirms a bin2-capable server, else 1. Reads need no probe
        (they negotiate per request via the Accept header); uploads do,
        because the client speaks first. A failed probe means v1 — the
        version every server understands. Benignly racy: two threads
        probing concurrently cache the same answer."""
        if not self.wire_v2:
            return 1
        version = self._upload_version_cache
        if version is None:
            health = probe_health(self.base_url)
            version = (
                2 if health and health.get("columns_wire") == "bin2" else 1
            )
            self._upload_version_cache = version
        return version

    def _documents_landed(
        self, collection: str, documents: list[dict]
    ) -> bool:
        """True when every sent document is stored with equal content —
        the read-back verification behind the cross-call landed-ok path
        (a genuine duplicate with DIFFERENT content still raises)."""
        try:
            for sent in documents:
                stored = self.find_one(collection, {ROW_ID: sent[ROW_ID]})
                if stored is None:
                    return False
                for key, value in sent.items():
                    if not _values_match(stored.get(key), value):
                        return False
            return True
        except Exception:
            return False  # verification must never mask the original 409

    def _ring(self):
        """The client's shared-memory ring, created on first use; None
        when disabled or unavailable (no /dev/shm, creation failed)."""
        if self.shm_bytes <= 0:
            return None
        with self._shm_lock:
            if self._shm_ring is None and not self._shm_failed:
                try:
                    self._shm_ring = shmring.ClientRing(self.shm_bytes)
                except Exception:  # noqa: BLE001 — body transport works
                    self._shm_failed = True
            return self._shm_ring

    def _accept_value(self) -> str:
        tokens = []
        if self.wire_v2:
            tokens.append("v2")
        if self.compress:
            tokens.append(WIRE_COMPRESSION)
        return ",".join(tokens)

    def close(self) -> None:
        """Release the client's shared-memory segment (also runs at
        garbage collection via the ring's finalizer)."""
        with self._shm_lock:
            if self._shm_ring is not None:
                self._shm_ring.close()
                self._shm_ring = None
                self._shm_failed = True

    def shm_stats(self) -> Optional[dict]:
        """Ring traffic counters, or None before/without a ring."""
        with self._shm_lock:
            ring = self._shm_ring
        return None if ring is None else ring.stats()

    def _fetch_frame_bytes(self, path: str, body: dict, allow_shm: bool = True):
        """POST JSON, receive one frame — as raw bytes (wire compression
        undone) over the HTTP body, or as an aligned numpy buffer copied
        out of the shared-memory ring when the server placed it there.

        Kept separate from the decode so the double-buffered read loop
        can run the network fetch on a helper thread while the main
        thread decodes the previous chunk."""
        data = json.dumps(body)
        headers = {"Content-Type": "application/json"}
        accept = self._accept_value()
        if accept:
            headers[ACCEPT_HEADER] = accept
        ring = self._ring() if allow_shm else None
        if ring is not None:
            headers[shmring.SEGMENT_HEADER] = ring.name
            headers[shmring.BYTES_HEADER] = str(ring.nbytes)
        response = self._send(
            lambda base: self._session.post(
                f"{base}{path}",
                data=data,
                headers=headers,
                timeout=self.timeout,
            )
        )
        slot_offset = response.headers.get(shmring.OFFSET_HEADER)
        if ring is not None and slot_offset is not None:
            try:
                return ring.read(
                    int(slot_offset),
                    int(response.headers.get(shmring.LENGTH_HEADER, -1)),
                    int(response.headers.get(shmring.GENERATION_HEADER, -1)),
                )
            except shmring.ShmTornError:
                # the server lapped the ring while we copied (deep
                # prefetch against a small segment): re-fetch THIS
                # chunk over the plain body — correctness never
                # depends on the ring
                return self._fetch_frame_bytes(path, body, allow_shm=False)
        return decode_body(
            response.content, response.headers.get(ENCODING_HEADER)
        )

    def _post_for_frame(self, path: str, body: dict):
        """POST JSON, receive a binary columnar frame."""
        return decode_frame(self._fetch_frame_bytes(path, body))

    def _get(self, path: str) -> dict:
        return self._send(
            lambda base: self._session.get(
                f"{base}{path}", timeout=self.timeout
            )
        ).json()

    def _delete(self, path: str) -> dict:
        return self._send(
            lambda base: self._session.delete(
                f"{base}{path}", timeout=self.timeout
            )
        ).json()

    # --- DocumentStore implementation -----------------------------------------
    def list_collections(self) -> list[str]:
        return self._get("/collections")["collections"]

    def create_collection(self, collection: str) -> bool:
        return self._post(f"/collections/{collection}", {})["created"]

    def drop(self, collection: str) -> None:
        self._delete(f"/collections/{collection}")

    def insert_one(self, collection: str, document: dict) -> None:
        # retry across failover only with an explicit _id: a replayed
        # auto-id insert would duplicate the row instead of raising the
        # duplicate-id KeyError that makes explicit-id retries safe
        explicit = "_id" in document
        self._post(
            f"/c/{collection}/insert_one",
            {"document": document},
            retry=explicit,
            landed_ok=explicit,
            collection=collection,
            verify=(
                (lambda: self._documents_landed(collection, [document]))
                if explicit
                else None
            ),
        )

    def insert_many(self, collection: str, documents: list[dict]) -> None:
        explicit = all("_id" in document for document in documents)
        self._post(
            f"/c/{collection}/insert_many",
            {"documents": documents},
            retry=explicit,
            landed_ok=explicit,
            collection=collection,
            verify=(
                (lambda: self._documents_landed(collection, documents))
                if explicit
                else None
            ),
        )

    def insert_columns(
        self,
        collection: str,
        columns: dict,
        start_id: Optional[int] = None,
    ) -> None:
        from learningorchestra_tpu.core.store import as_column

        self.insert_column_arrays(
            collection,
            {name: as_column(values) for name, values in columns.items()},
            start_id=start_id,
        )

    def insert_column_arrays(
        self,
        collection: str,
        columns: dict[str, Column],
        start_id: Optional[int] = None,
    ) -> None:
        """Typed columns ride the binary wire, paged in
        ``wire_rows_bin`` strides so one call never builds an unbounded
        frame. Client-side ragged validation keeps the error local."""
        lengths = {len(column) for column in columns.values()}
        if len(lengths) > 1:
            raise ValueError("ragged columns")
        num_rows = lengths.pop() if lengths else 0
        if not columns:
            return
        with _tracing.span("wire:write", collection=collection, rows=num_rows):
            self._insert_column_arrays(collection, columns, num_rows, start_id)

    def _insert_column_arrays(
        self,
        collection: str,
        columns: dict[str, Column],
        num_rows: int,
        start_id: Optional[int],
    ) -> None:
        stride = self.wire_rows_bin
        for offset in range(0, max(num_rows, 1), stride):
            stop = min(offset + stride, num_rows)
            chunk = {
                name: column.slice(offset, stop)
                for name, column in columns.items()
            }
            extra = {
                "start_id": None if start_id is None else start_id + offset
            }
            verify = None
            if start_id is not None and stop > offset:
                chunk_start_id = start_id + offset
                endpoints = [
                    self._chunk_row(chunk, 0, chunk_start_id),
                    self._chunk_row(
                        chunk, stop - offset - 1, start_id + stop - 1
                    ),
                ]
                # block appends are atomic server-side, so matching
                # endpoint rows prove the whole chunk landed
                verify = lambda docs=endpoints: self._documents_landed(  # noqa: E731
                    collection, docs
                )
            self._post_frame(
                f"/c/{collection}/insert_columns_bin",
                encode_frame(chunk, extra=extra, version=self._upload_version()),
                # chunks at an explicit start_id: a duplicate rejection
                # on the post-failover replay means the chunk landed
                landed_ok=start_id is not None,
                collection=collection,
                verify=verify,
            )
            if stop >= num_rows:
                break

    @staticmethod
    def _chunk_row(chunk: dict[str, Column], index: int, doc_id) -> dict:
        """Synthesize the document a chunk row will be stored as."""
        from learningorchestra_tpu.core.columns import MISSING

        document = {ROW_ID: doc_id}
        for name, column in chunk.items():
            value = column.get(index)
            if value is not MISSING:
                document[name] = value
        return document

    def update_one(self, collection: str, query: dict, new_values: dict) -> None:
        self._post(
            f"/c/{collection}/update_one",
            {"query": query, "new_values": new_values},
        )

    def trim_collection(self, collection: str, max_docs: int) -> int:
        payload = self._post(
            f"/c/{collection}/trim", {"max_docs": max_docs}
        )
        return int(payload.get("removed", 0))

    def set_field_values(
        self, collection: str, field: str, values_by_id: dict
    ) -> None:
        self._post(
            f"/c/{collection}/set_field_values",
            {"field": field, "values": list(values_by_id.items())},
        )

    def set_column(
        self, collection: str, field: str, values, start_id: int = 1
    ) -> None:
        from learningorchestra_tpu.core.store import as_column

        column = as_column(values)
        # Page large replaces in strides; each stride is itself a
        # contiguous set_column at the shifted start_id.
        stride = self.wire_rows_bin
        for offset in range(0, max(len(column), 1), stride):
            stop = min(offset + stride, len(column))
            self._post_frame(
                f"/c/{collection}/set_column_bin",
                encode_frame(
                    {field: column.slice(offset, stop)},
                    extra={"field": field, "start_id": start_id + offset},
                    version=self._upload_version(),
                ),
                collection=collection,
            )
            if stop >= len(column):
                break

    def find(
        self,
        collection: str,
        query: Optional[dict] = None,
        skip: int = 0,
        limit: Optional[int] = None,
    ) -> Iterator[dict]:
        payload = self._post(
            f"/c/{collection}/find",
            {"query": query or {}, "skip": skip, "limit": limit},
        )
        return iter(payload["documents"])

    def read_columns(
        self,
        collection: str,
        fields: Optional[list[str]] = None,
        start: int = 0,
        limit: Optional[int] = None,
    ) -> dict[str, list]:
        """Paged on the wire: rows travel in ``wire_rows`` chunks (the
        read half of ``insert_columns_batched``'s write batching), so a
        10M-row dataset never rides one giant JSON body. The chunk loop
        stops at a short chunk; an explicit ``limit`` caps the total."""
        out: dict[str, list] = {}
        fetched = 0
        while True:
            chunk_limit = self.wire_rows
            if limit is not None:
                chunk_limit = min(chunk_limit, limit - fetched)
                if chunk_limit <= 0:
                    break
            chunk = self._post(
                f"/c/{collection}/read_columns",
                {
                    "fields": fields,
                    "start": start + fetched,
                    "limit": chunk_limit,
                },
            )["columns"]
            if not out:
                out = {name: list(values) for name, values in chunk.items()}
            else:
                for name, values in chunk.items():
                    out[name].extend(values)
            chunk_rows = max((len(v) for v in chunk.values()), default=0)
            fetched += chunk_rows
            # Short chunk = exhausted; empty chunk breaks unconditionally
            # so a degenerate chunk_limit can never spin forever.
            if chunk_rows < chunk_limit or chunk_rows == 0:
                break
        return out

    def read_column_arrays(
        self,
        collection: str,
        fields: Optional[list[str]] = None,
        start: int = 0,
        limit: Optional[int] = None,
    ) -> dict[str, Column]:
        """Typed columns over the binary wire, paged in
        ``wire_rows_bin`` strides. Multi-chunk reads are NOT one atomic
        store snapshot; the server echoes the collection's mutation
        counter per chunk, and a mismatch (a write landed between
        chunks) restarts the read — after ``LO_READ_RETRIES`` (default
        3) torn attempts the last result is returned best-effort, which
        matches the reference's own read semantics (Mongo cursors don't
        snapshot either)."""
        retries = _int_env("LO_READ_RETRIES", 3)
        for _ in range(max(retries, 1)):
            out, torn = self._read_column_arrays_once(
                collection, fields, start, limit, check_rev=True
            )
            if not torn:
                return out
        # Still torn after retries: read to completion WITHOUT the rev
        # check — complete but non-snapshot, the Mongo-cursor semantics
        # (never a silently truncated result).
        out, _ = self._read_column_arrays_once(
            collection, fields, start, limit, check_rev=False
        )
        return out

    def _fetch_chunk(
        self, collection: str, fields, chunk_start: int, chunk_limit: int
    ) -> bytes:
        """One chunk's frame bytes, retried IN PLACE on TRANSIENT
        failure (connection death, timeout, 5xx): a mid-stream fault
        purges any partially-populated device-cache entry for the
        collection (a torn entry must never outlive the read that was
        filling it) and re-requests THIS chunk — never chunk 0; earlier
        chunks' bytes are already decoded and the rev check still
        proves consistency of the final result. Deterministic errors
        (4xx mappings, a follower's 503→PermissionError) propagate
        immediately — retrying them would only add sleeps and evict
        perfectly valid cache entries."""
        attempt = 0
        while True:
            try:
                return self._fetch_frame_bytes(
                    f"/c/{collection}/read_columns_bin",
                    {
                        "fields": fields,
                        "start": chunk_start,
                        "limit": chunk_limit,
                    },
                )
            except (
                requests.ConnectionError,
                requests.Timeout,
                requests.HTTPError,
            ) as error:
                response = getattr(error, "response", None)
                if response is not None and response.status_code < 500:
                    raise  # deterministic client error: not retryable
                from learningorchestra_tpu.core import devcache

                devcache.invalidate_collection(collection, store=self)
                if attempt >= self.chunk_retries:
                    raise
                attempt += 1
                time.sleep(min(0.2 * attempt, 1.0))

    def _decode_chunk(
        self, collection: str, fields, chunk_start: int, chunk_limit: int, raw: bytes
    ):
        """Decode one chunk's frame, re-fetching THIS chunk in place on
        a corrupt frame — a torn/truncated body that slipped past HTTP
        framing (a server falling over mid-response). Same budget and
        cache hygiene as the transport-level chunk retries: the
        partially-filled device-cache entry is purged, and earlier
        chunks' decoded bytes are kept."""
        import struct

        attempt = 0
        while True:
            try:
                return decode_frame(raw)
            except (ValueError, KeyError, IndexError, struct.error):
                from learningorchestra_tpu.core import devcache

                devcache.invalidate_collection(collection, store=self)
                if attempt >= self.chunk_retries:
                    raise
                attempt += 1
                raw = self._fetch_chunk(
                    collection, fields, chunk_start, chunk_limit
                )

    def _read_column_arrays_once(
        self,
        collection: str,
        fields: Optional[list[str]],
        start: int,
        limit: Optional[int],
        check_rev: bool = True,
    ) -> tuple[dict[str, Column], bool]:
        # wire:read wraps the whole paged read: account_wire/
        # account_decode inside the chunk loop accumulate wire_bytes +
        # decode_s onto THIS span (fetches run on helper threads, but
        # the bytes are counted where they are consumed — here), so the
        # job timeline carries the read's full byte-and-decode bill.
        with _tracing.span("wire:read", collection=collection) as span_obj:
            out, torn = self._paged_read(
                collection, fields, start, limit, check_rev
            )
            if span_obj is not None:
                span_obj.meta["rows"] = max(
                    (len(c) for c in out.values()), default=0
                )
        return out, torn

    def _paged_read(
        self,
        collection: str,
        fields: Optional[list[str]],
        start: int,
        limit: Optional[int],
        check_rev: bool,
    ) -> tuple[dict[str, Column], bool]:
        out: dict[str, Column] = {}
        fetched = 0
        rev: Optional[int] = None
        pending = None  # (future, predicted_start, predicted_limit)
        try:
            while True:
                chunk_limit = self.wire_rows_bin
                if limit is not None:
                    chunk_limit = min(chunk_limit, limit - fetched)
                    if chunk_limit <= 0:
                        break
                chunk_start = start + fetched
                if (
                    pending is not None
                    and pending[1] == chunk_start
                    and pending[2] == chunk_limit
                ):
                    future = pending[0]
                    pending = None
                    try:
                        raw = future.result()
                    except Exception:
                        # the read-ahead died terminally (its own
                        # in-place retries exhausted): one more
                        # synchronous attempt before the read as a
                        # whole fails
                        raw = self._fetch_chunk(
                            collection, fields, chunk_start, chunk_limit
                        )
                else:
                    pending = self._discard_prefetch(pending)
                    raw = self._fetch_chunk(
                        collection, fields, chunk_start, chunk_limit
                    )
                # Double buffering: assume this chunk comes back full
                # and start fetching the next stride NOW, overlapping
                # the decode below. A short chunk ends the stream and
                # the speculative fetch is discarded (it reads rows
                # past the end — an empty frame, one wasted round trip
                # at most).
                next_start = chunk_start + chunk_limit
                next_limit = self.wire_rows_bin
                if limit is not None:
                    next_limit = min(next_limit, start + limit - next_start)
                if next_limit > 0 and chunk_limit > 1:
                    pending = (
                        self._prefetch.submit(
                            self._fetch_chunk,
                            collection,
                            fields,
                            next_start,
                            next_limit,
                        ),
                        next_start,
                        next_limit,
                    )
                if isinstance(raw, np.ndarray):
                    # the frame rode the shared-memory ring: these
                    # bytes never crossed the HTTP body, so they count
                    # as shm traffic, not wire traffic
                    _profile.account_shm(collection, len(raw))
                else:
                    _profile.account_wire("read", collection, len(raw))
                decode_started = time.perf_counter()
                columns, extra = self._decode_chunk(
                    collection, fields, chunk_start, chunk_limit, raw
                )
                _profile.account_decode(
                    collection, time.perf_counter() - decode_started
                )
                chunk_rev = extra.get("rev", -1)
                if rev is None:
                    rev = chunk_rev
                elif check_rev and rev != -1 and chunk_rev != rev:
                    return out, True  # a write interleaved: torn read
                elif chunk_rev != rev:
                    rev = chunk_rev  # unchecked mode: follow the rev
                if not out:
                    out = columns
                else:
                    for name, column in columns.items():
                        existing = out.get(name)
                        if existing is None:
                            # field appeared mid-read (unchecked mode):
                            # earlier rows lack it → pad prefix
                            existing = Column.pads(fetched)
                        out[name] = existing.append_column(column)
                chunk_rows = max(
                    (len(c) for c in columns.values()), default=0
                )
                fetched += chunk_rows
                if chunk_rows < chunk_limit or chunk_rows == 0:
                    break
            return out, False
        finally:
            # Every exit — short chunk, torn-read return, decode error —
            # must consume the speculative fetch (never an unretrieved
            # exception, never an orphaned request blocking a retry).
            self._discard_prefetch(pending)

    @staticmethod
    def _discard_prefetch(pending):
        """Drop a speculative fetch whose prediction didn't pan out
        (short/terminal chunk). Its failure, if any, is irrelevant —
        swallow it so a dead read-ahead never fails a finished read."""
        if pending is not None:
            future = pending[0]
            if not future.cancel():
                future.add_done_callback(lambda f: f.exception())
        return None

    def collection_rev(self, collection: str) -> int:
        return self._get(f"/c/{collection}/rev")["rev"]

    def collection_block_rows(self, collection: str) -> int:
        # older servers don't ship the field: -1 = unknown, same as the
        # base-class contract
        return self._get(f"/c/{collection}/rev").get("block_rows", -1)

    def occupancy_stats(self) -> dict:
        """The server's collection/WAL/spill occupancy (/health's
        ``occupancy`` block, absent on older servers) — the per-group
        probe behind the ``lo_store_shard_*`` gauges. Deliberately NOT
        named ``telemetry_stats``: register_store keys off that name,
        and a remote store must not be mistaken for a local one."""
        health = self._get("/health")
        occupancy = health.get("occupancy")
        return occupancy if isinstance(occupancy, dict) else {}

    def aggregate(self, collection: str, pipeline: list[dict]) -> list[dict]:
        return self._post(f"/c/{collection}/aggregate", {"pipeline": pipeline})[
            "results"
        ]

    def count(self, collection: str) -> int:
        return self._get(f"/c/{collection}/count")["count"]


def connect(url: Optional[str] = None) -> DocumentStore:
    """The services' store factory: a :class:`RemoteStore` when a store
    URL is configured (``LO_STORE_URL`` — the analogue of the reference's
    ``DATABASE_URL``; a comma-separated list names the replica pair and
    enables client-side failover), else a process-local WAL-backed
    store.

    ``;`` separates SHARD GROUPS (``primary,follower;primary,follower``
    — each group keeps its own comma replica list and failover): two or
    more groups build a scatter-gather
    :class:`~learningorchestra_tpu.core.shardstore.ShardedStore` whose
    first group is the meta group. One group — the default — stays a
    plain ``RemoteStore``, so the unsharded wire path is untouched by
    construction, not by configuration."""
    # lo: allow[LO301] free-form URL knob, no domain to preflight
    url = url if url is not None else _str_env("LO_STORE_URL")
    if url:
        group_urls = [part.strip() for part in url.split(";") if part.strip()]
        if len(group_urls) > 1:
            from learningorchestra_tpu.core.shardstore import ShardedStore

            return ShardedStore([RemoteStore(part) for part in group_urls])
        return RemoteStore(group_urls[0] if group_urls else url)
    data_dir = _str_env("LO_DATA_DIR")
    return InMemoryStore(data_dir=data_dir)


class ReplicationClient:
    """Follower-side WAL shipper: polls the primary's ``GET /wal`` and
    applies new records to the local store — the role Mongo's secondary
    oplog tailing plays in the reference's replica set
    (docker-compose.yml:27-91). On a stale epoch (the primary
    compacted) the local store resets and re-pulls from record 0, where
    the compacted snapshot now lives. ``stop()`` (or ``POST /promote``
    on the follower's server) halts shipping for failover."""

    def __init__(
        self,
        store: InMemoryStore,
        primary_url: str,
        interval: Optional[float] = None,
        batch: int = 10000,
        node_id: Optional[str] = None,
    ):
        self.store = store
        self.primary_url = primary_url.rstrip("/")
        self.interval = (
            interval
            if interval is not None
            else _float_env("LO_REPL_INTERVAL_S", 0.5)
        )
        self.batch = batch
        # identifies this node at the store.net fault point so chaos
        # tests can partition ONE side's server-to-server traffic
        self.node_id = node_id
        self.epoch = -1
        self.offset = 0
        # Takeover bookkeeping: the primary's term (from the /wal feed),
        # whether the last successful poll had drained the feed, the
        # primary's total feed length (loss-window accounting:
        # primary_length - offset = acknowledged records not yet applied
        # here), and how long the primary has been continuously
        # unreachable (None = healthy) — what auto-promotion and the
        # promote response report.
        self.primary_term = 0
        self.caught_up = False
        self.primary_length = 0
        self.last_poll_monotonic: Optional[float] = None
        self.failing_since: Optional[float] = None
        # A resync signal only marks intent; local state is replaced
        # atomically when the replacement records are actually in hand
        # (resync_apply) — never truncated on the signal alone, so a
        # primary that dies mid-resync cannot leave the follower empty.
        self._pending_resync = True
        self.last_error: Optional[str] = None
        self._stop = threading.Event()
        # Serializes apply against stop(): once stop() returns, no
        # further records can land (promote must not race an in-flight
        # poll into applying the old primary's records after new writes).
        self._apply_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    @property
    def lag(self) -> int:
        """Acknowledged WAL records the primary holds that this
        follower has not applied, as of the last successful poll —
        exported as ``lo_store_replication_lag``. Snapshotted under the
        apply lock (LO203): the poller thread writes primary_length and
        offset under it, and a bare read here could pair the new length
        with the pre-apply offset and report a phantom lag spike."""
        with self._apply_lock:
            return max(0, self.primary_length - self.offset)

    def loss_window(self) -> dict:
        """What a takeover right now would cost (docs/replication.md):
        records the primary acknowledged but never shipped, plus how
        stale that measurement is. Writes the primary accepted AFTER
        the last successful poll are unknowable from here — the window
        is a floor, bounded above by ``last_poll_age_s`` of traffic.
        One apply-lock snapshot (LO203): the whole dict must describe
        ONE poll's state, not a mid-apply mixture."""
        import time

        with self._apply_lock:
            primary_length = self.primary_length
            offset = self.offset
            epoch = self.epoch
            last_poll = self.last_poll_monotonic
        age = (
            None
            if last_poll is None
            else round(time.monotonic() - last_poll, 3)
        )
        return {
            "records": max(0, primary_length - offset),
            "primary_wal_length": primary_length,
            "applied_offset": offset,
            "applied_epoch": epoch,
            "last_poll_age_s": age,
        }

    def poll_once(self, wait: bool = False) -> int:
        """One fetch+apply round; returns the number of records
        applied. ``wait=True`` (the background loop) long-polls the
        primary: a caught-up feed parks server-side until a record
        lands, so replication — and with it sync-repl write acks —
        reacts in tens of milliseconds instead of a poll interval.
        Hand-driven pollers (tests, operators) default to the
        immediate answer."""
        import time

        faults.fire(
            "store.net", me=self.node_id, url=self.primary_url, kind="wal"
        )
        # cursor snapshot under the apply lock (LO203): epoch/offset
        # are rewritten under it (apply, resync, self-heal), and a
        # request built from a torn pair would fetch the wrong window
        with self._apply_lock:
            params = {
                "epoch": self.epoch,
                "offset": self.offset,
                "limit": self.batch,
            }
        if wait:
            params["wait"] = round(min(max(self.interval, 0.1), 25.0), 3)
        response = requests.get(
            f"{self.primary_url}/wal",
            params=params,
            timeout=60,
        )
        response.raise_for_status()
        feed = response.json()
        with self._apply_lock:
            if self._stop.is_set():
                return 0
            self.primary_term = max(self.primary_term, feed.get("term", 0))
            self.caught_up = len(feed["records"]) < self.batch
            self.primary_length = feed.get("length", feed.get("next", 0))
            self.last_poll_monotonic = time.monotonic()
            if feed["resync"]:
                self.epoch = feed["epoch"]
                self.offset = 0
                self._pending_resync = True
                return 0
            try:
                if self._pending_resync and feed["offset"] == 0:
                    self.store.resync_apply(feed["records"])
                    self._pending_resync = False
                else:
                    self.store.apply_replicated(feed["records"])
            except Exception:
                # A mid-batch failure (divergence, duplicate id) leaves
                # an ambiguous prefix applied; re-pulling the same batch
                # would fail forever. Self-heal: force a full resync.
                self.epoch = -1
                self.offset = 0
                self._pending_resync = True
                raise
            self.offset = feed["next"]
            return len(feed["records"])

    def run(self) -> None:
        import time

        while not self._stop.is_set():
            started = time.monotonic()
            try:
                applied = self.poll_once(wait=True)
                self.last_error = None
                self.failing_since = None
            except Exception as error:  # primary down: keep serving reads
                self.last_error = str(error)
                if self.failing_since is None:
                    self.failing_since = time.monotonic()
                applied = 0
            if applied == 0 and time.monotonic() - started < self.interval:
                # only sleep when the empty answer came back FAST: a
                # primary honoring the long-poll already waited the
                # interval server-side (sleeping again would re-add the
                # ack latency the long-poll removes); a dead primary or
                # an old one ignoring `wait` returns/fails immediately
                # and must not be hammered
                self._stop.wait(self.interval)

    def start(self) -> "ReplicationClient":
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()
        return self

    def halt(self) -> None:
        """The correctness fence WITHOUT the thread join: on return, no
        further records will be applied — the stop flag is checked
        under the apply lock, so an in-flight poll either finished
        applying before this or discards its response. Bounded by one
        in-flight apply batch, so it is safe to call while holding the
        role lock; the poller thread itself exits on its next wakeup
        (its long-poll request can park for up to 60 s — which is why
        :meth:`stop`'s join must never run under a lock, LO202)."""
        self._stop.set()
        with self._apply_lock:
            pass

    def stop(self) -> None:
        """halt() plus the thread join (bounded, 10 s). Call this only
        OUTSIDE any lock a request handler can take: the join waits on
        a thread that may be mid-long-poll."""
        self.halt()
        if self._thread is not None:
            self._thread.join(timeout=10)


def probe_health(
    url: str, timeout: float = 2.0, origin: Optional[str] = None
) -> Optional[dict]:
    """``/health`` of a peer store, or None when unreachable.
    ``origin`` identifies a SERVER-side caller (monitor, fence) at the
    ``store.net`` fault point so chaos tests can partition one node's
    backend traffic; client-side probes pass no origin and stay
    unaffected — a backend partition does not sever client reach."""
    try:
        if origin is not None:
            faults.fire(
                "store.net", me=origin, url=url.rstrip("/"), kind="health"
            )
        response = requests.get(f"{url.rstrip('/')}/health", timeout=timeout)
        response.raise_for_status()
        return response.json()
    except Exception:
        return None


def request_votes(
    voters: list[str],
    term: int,
    candidate: str,
    origin: Optional[str] = None,
    timeout: float = 2.0,
) -> tuple[int, list[dict]]:
    """Campaign for ``term``: POST /vote to every voter (store peers +
    arbiters). Returns ``(granted_including_self, responses)`` — the
    candidate's own vote is counted here, the caller must have recorded
    it in its ledger first (one vote per term applies to self too)."""
    granted = 1  # self
    responses: list[dict] = []
    for voter in voters:
        url = voter.rstrip("/")
        try:
            if origin is not None:
                faults.fire("store.net", me=origin, url=url, kind="vote")
            response = requests.post(
                f"{url}/vote",
                json={"term": term, "candidate": candidate},
                timeout=timeout,
            )
            payload = response.json()
        except Exception:
            continue
        responses.append(payload)
        if payload.get("granted"):
            granted += 1
    return granted, responses


def serve(
    host: str = "127.0.0.1",
    port: int = DEFAULT_STORE_PORT,
    data_dir: Optional[str] = None,
    replicate: bool = False,
    primary_url: Optional[str] = None,
    peers: Optional[list[str]] = None,
    auto_promote_s: Optional[float] = None,
    arbiters: Optional[list[str]] = None,
    node_id: Optional[str] = None,
    monitor_tick_s: Optional[float] = None,
    quorum_grace_s: Optional[float] = None,
    sync_repl: Optional[bool] = None,
    ack_timeout_s: Optional[float] = None,
) -> ServerThread:
    """Start a store server thread; returns it (caller stops).

    ``replicate=True`` keeps the in-memory WAL buffer so followers can
    ship the log; ``primary_url`` starts THIS server as a follower of
    that primary (read-only until promoted). The server's ``role`` dict
    and poller are attached to the returned thread as ``.store_role`` /
    ``.replication`` for operators and tests.

    ``peers`` (LO_PEERS) enables term fencing: at startup a
    would-be-writable server that finds ANY writable peer joins it as a
    follower (the revived old primary of a completed failover; also
    makes sequential bootstrap of a fresh pair converge on one
    primary); while running, a writable server demotes itself only to
    a writable peer with a strictly higher term. ``auto_promote_s``
    (LO_AUTO_PROMOTE_S) makes a follower promote itself once its
    primary has been unreachable for that long.

    ``arbiters`` (LO_ARBITERS) switches failover to QUORUM mode
    (docs/replication.md): auto-promotion requires a majority of votes
    from the voting population (this server + peers + arbiters), and a
    writable server that cannot reach a majority of voters for
    ``quorum_grace_s`` (LO_QUORUM_GRACE_S) suspends writes — 503 +
    Retry-After, reads keep serving — until quorum returns and no
    superseding primary is visible. ``sync_repl``
    (LO_STORE_SYNC_REPL=1) withholds mutation acks until a follower's
    WAL cursor passes them (bounded by ``ack_timeout_s`` /
    LO_STORE_ACK_TIMEOUT_S) — the zero-lost-acknowledged-writes mode.
    """
    import time

    store = InMemoryStore(
        data_dir=data_dir,
        replicate=replicate or primary_url is not None or bool(peers),
    )
    arbiters = [a.rstrip("/") for a in (arbiters or []) if a]
    writable = primary_url is None
    if writable and peers:
        # Startup fence: a server coming up writable must make sure no
        # peer has taken over while it was down (>= catches the revived
        # old primary of a same-term promote race; a genuinely fresh
        # pair starts follower-less, so no peer answers writable).
        for peer in peers:
            health = probe_health(peer, origin=node_id)
            if health and health.get("writable"):
                writable = False
                primary_url = peer
                break
    import secrets

    if sync_repl is None:
        sync_repl = _flag_env("LO_STORE_SYNC_REPL")
    if ack_timeout_s is None:
        ack_timeout_s = _float_env("LO_STORE_ACK_TIMEOUT_S", 2.0)
    role = {
        "writable": writable,
        "poller": None,
        "term": 1 if writable else 0,
        # equal-term tiebreak for the fence: two fresh servers that
        # both bootstrapped writable (simultaneous start, neither's
        # probe saw the other) deterministically converge on the higher
        # boot id instead of split-braining at term 1 == term 1
        "boot": secrets.token_hex(8),
        "sync_repl": bool(sync_repl),
        "ack_timeout_s": ack_timeout_s,
    }
    me = node_id or role["boot"]
    if primary_url is not None and not writable:
        role["poller"] = ReplicationClient(
            store, primary_url, node_id=me
        ).start()
    server = ServerThread(create_store_app(store, role), host, port).start()
    server.store = store
    server.store_role = role
    server.replication = role["poller"]

    def demote_to(peer: str) -> None:
        """Superseded while writable: rejoin as a follower of ``peer``.
        The fresh poller's epoch mismatch forces a full resync, which
        atomically replaces any diverged local writes."""
        with role["lock"]:
            if not role.get("writable"):
                return
            role["writable"] = False
            role["suspended"] = False
            role["poller"] = ReplicationClient(
                store, peer, node_id=me
            ).start()
            server.replication = role["poller"]
        print(f"store: fenced — rejoining as follower of {peer}", flush=True)

    def refollow(peer: str) -> None:
        """A follower whose primary pointer went stale (its primary
        died and a QUORUM election elsewhere produced a new one)
        re-points its WAL poller at the visible writable peer."""
        with role["lock"]:
            if role.get("writable"):
                return
            old_poller = role.get("poller")
            if (
                old_poller is not None
                and old_poller.primary_url == peer.rstrip("/")
            ):
                return
            if old_poller is not None:
                # fence only (LO202): the join happens outside the
                # lock below — see promote_role
                old_poller.halt()
            role["poller"] = ReplicationClient(
                store, peer, node_id=me
            ).start()
            server.replication = role["poller"]
        if old_poller is not None:
            old_poller.stop()
        print(f"store: re-following new primary {peer}", flush=True)

    quorum = bool(arbiters)
    voters = list(peers or []) + arbiters
    population = 1 + len(voters)
    tick = (
        monitor_tick_s
        if monitor_tick_s is not None
        else _float_env("LO_STORE_MONITOR_TICK_S", 1.0)
    )
    if quorum_grace_s is None:
        quorum_grace_s = _float_env("LO_QUORUM_GRACE_S", None)
        if quorum_grace_s is None:
            # a primary must suspend BEFORE the majority side can have
            # promoted, or a short dual-primary window opens: default
            # the grace under the takeover timer
            quorum_grace_s = (
                min(2.0, auto_promote_s / 2) if auto_promote_s else 2.0
            )

    if peers or auto_promote_s or arbiters:
        monitor_stop = threading.Event()

        def try_takeover(poller) -> None:
            """The follower's promotion decision, quorum-gated when
            arbiters are configured."""
            if not quorum:
                result = promote_role(role)
                server.replication = None
                print(
                    "store: primary gone/unwritable for "
                    f"{auto_promote_s:g}s — self-promoted "
                    f"(term {result['term']}, caught_up="
                    f"{result['caught_up']})",
                    flush=True,
                )
                return
            # the primary may not be GONE — a completed election
            # elsewhere means refollow the winner, not campaign
            for peer in peers or []:
                health = probe_health(peer, origin=me)
                if (
                    health
                    and health.get("writable")
                    and not health.get("suspended")
                ):
                    refollow(peer)
                    return
            with role["lock"]:
                if role.get("writable"):
                    return
                # candidate term AND the self-vote ledger write happen
                # under ONE lock acquisition: computing the term outside
                # would race a concurrent POST /vote granting a higher
                # term, and overwriting voted_term downward would let
                # this node vote twice in that term (two majorities)
                candidate_term = (
                    max(
                        role.get("term", 0),
                        poller.primary_term,
                        role.get("voted_term", 0),
                        1,
                    )
                    + 1
                )
                role["voted_term"] = candidate_term
                role["voted_for"] = me
            granted, _ = request_votes(
                voters, candidate_term, me, origin=me
            )
            if granted * 2 > population:
                result = promote_role(role, term=candidate_term)
                server.replication = None
                print(
                    f"store: quorum takeover ({granted}/{population} "
                    f"votes) — promoted (term {result['term']}, "
                    f"caught_up={result['caught_up']}, "
                    f"loss_window={result['loss_window']})",
                    flush=True,
                )
            else:
                counters["denied"] += 1
                if counters["denied"] % 10 == 1:
                    print(
                        f"store: promotion blocked — {granted} of "
                        f"{population} votes; staying a read-only "
                        "follower",
                        flush=True,
                    )

        counters = {"denied": 0}

        def monitor():
            unwritable_since: Optional[float] = None
            no_quorum_since: Optional[float] = None
            while not monitor_stop.wait(tick):
                poller = role.get("poller")
                if auto_promote_s and poller is not None:
                    # A reachable-but-UNWRITABLE primary counts as down
                    # too: after a failover, a supervisor restart of the
                    # promoted server (original env) can leave both
                    # nodes followers of each other — the /wal polls
                    # succeed, so failing_since alone never fires. Both
                    # sides then self-promote and the term/boot fence
                    # converges on one writer within a few ticks.
                    if poller.failing_since is None:
                        health = probe_health(poller.primary_url, origin=me)
                        if health is not None and not health.get("writable"):
                            if unwritable_since is None:
                                unwritable_since = time.monotonic()
                        else:
                            unwritable_since = None
                    down_since = (
                        poller.failing_since
                        if poller.failing_since is not None
                        else unwritable_since
                    )
                    if (
                        down_since is not None
                        and time.monotonic() - down_since >= auto_promote_s
                    ):
                        try_takeover(poller)
                        if role.get("writable"):
                            unwritable_since = None
                peer_healths: dict[str, Optional[dict]] = {}
                if role.get("writable"):
                    for peer in peers or []:
                        peer_healths[peer] = probe_health(peer, origin=me)
                if quorum and role.get("writable"):
                    # quorum custody: a primary that cannot reach a
                    # majority of voters suspends writes (the minority
                    # side of a partition degrades to read-only instead
                    # of diverging); resumes only once quorum is back
                    # AND no superseding primary is visible
                    reachable = 1
                    superior = False
                    my_term = role.get("term", 0)
                    my_boot = role.get("boot", "")
                    for voter in voters:
                        health = (
                            peer_healths[voter]
                            if voter in peer_healths
                            else probe_health(voter, origin=me)
                        )
                        if not health:
                            continue
                        reachable += 1
                        # ANY voter reporting a higher term — the
                        # arbiter included (its /health carries the
                        # highest term it has voted) — is proof an
                        # election superseded this primary. Counting
                        # only writable peers here would let an
                        # asymmetric partition (primary↔follower link
                        # down, both still reach the arbiter) keep TWO
                        # writers: the follower wins self+arbiter, the
                        # old primary still counts quorum via the
                        # arbiter and never hears about the new term.
                        peer_term = max(
                            health.get("term", 0),
                            health.get("voted_term", 0),
                        )
                        if peer_term > my_term or (
                            health.get("writable")
                            and peer_term == my_term
                            and health.get("boot", "") > my_boot
                        ):
                            superior = True
                    if superior and not role.get("suspended"):
                        # definitive supersession evidence: suspend NOW
                        # (no grace — the other side may already be
                        # accepting writes); the fence below demotes to
                        # the new primary once it becomes visible
                        with role["lock"]:
                            role["suspended"] = True
                        print(
                            "store: a voter reports a higher term — "
                            "superseded; suspending writes until the "
                            "new primary is visible",
                            flush=True,
                        )
                    if reachable * 2 <= population:
                        if no_quorum_since is None:
                            no_quorum_since = time.monotonic()
                        if (
                            time.monotonic() - no_quorum_since
                            >= quorum_grace_s
                            and not role.get("suspended")
                        ):
                            with role["lock"]:
                                role["suspended"] = True
                            print(
                                "store: quorum lost "
                                f"({reachable}/{population} voters "
                                "reachable) — suspending writes, reads "
                                "keep serving",
                                flush=True,
                            )
                    else:
                        no_quorum_since = None
                        if role.get("suspended") and not superior:
                            with role["lock"]:
                                role["suspended"] = False
                            print(
                                "store: quorum restored — resuming "
                                "writes",
                                flush=True,
                            )
                if peers and role.get("writable"):
                    my_term = role.get("term", 0)
                    my_boot = role.get("boot", "")
                    for peer in peers:
                        health = peer_healths.get(peer)
                        if not health or not health.get("writable"):
                            continue
                        peer_term = health.get("term", 0)
                        if peer_term > my_term or (
                            peer_term == my_term
                            and health.get("boot", "") > my_boot
                        ):
                            demote_to(peer)
                            break

        monitor_thread = threading.Thread(target=monitor, daemon=True)
        monitor_thread.start()
        server.monitor_stop = monitor_stop
        # server.stop() must halt the monitor too, or every
        # serve()-and-stop cycle leaks a thread that keeps probing peers
        # (and could promote/demote a stopped server's role)
        original_stop = server.stop

        def stop_with_monitor(*args, **kwargs):
            monitor_stop.set()
            poller = role.get("poller")
            if poller is not None:
                poller.stop()
            return original_stop(*args, **kwargs)

        server.stop = stop_with_monitor
    if replicate or primary_url is not None or peers:
        # The replication feed duplicates the write history in RAM —
        # on the primary AND on every follower (a follower re-logs each
        # applied record so it is promotable with full durability).
        # Compact when it grows past LO_COMPACT_RECORDS: the snapshot
        # replaces the history; on the primary the epoch bump resyncs
        # followers, on a follower compaction is purely local (the
        # poller's cursor tracks the PRIMARY's epoch, not the local
        # one), and a follower promoted later keeps compacting.
        threshold = _int_env("LO_COMPACT_RECORDS", 200000)
        stop = threading.Event()

        def maintain():
            while not stop.wait(10.0):
                if store.wal_length > threshold:
                    store.compact()

        thread = threading.Thread(target=maintain, daemon=True)
        thread.start()
        server.compaction_stop = stop
    return server


def main() -> None:
    try:
        # a typo'd chaos knob must refuse bring-up, not silently not fire
        faults.validate_env()
    except ValueError as error:
        raise SystemExit(f"LO_FAULT_* validation failed: {error}")
    host = _str_env("LO_HOST", "127.0.0.1")
    port = _int_env("LO_STORE_PORT", DEFAULT_STORE_PORT)
    data_dir = _str_env("LO_DATA_DIR")
    replicate = _flag_env("LO_REPLICATE")
    # free-form topology strings (URLs, host lists, node ids): nothing
    # for the run.sh preflight to range-check
    primary_url = _str_env("LO_PRIMARY_URL")  # lo: allow[LO301]
    peers_env = _str_env("LO_PEERS", "")  # lo: allow[LO301]
    peers = [p.strip() for p in peers_env.split(",") if p.strip()] or None
    arbiters_env = _str_env("LO_ARBITERS", "")  # lo: allow[LO301]
    arbiters = [
        a.strip() for a in arbiters_env.split(",") if a.strip()
    ] or None
    auto_promote_s = _float_env("LO_AUTO_PROMOTE_S", None)
    server = serve(
        host,
        port,
        data_dir,
        replicate,
        primary_url,
        peers,
        auto_promote_s,
        arbiters=arbiters,
        node_id=_str_env("LO_NODE_ID"),  # lo: allow[LO301] free-form
    )
    mode = (
        f"follower of {primary_url}"
        if primary_url
        else ("primary (replicating)" if replicate else "standalone")
    )
    if arbiters:
        mode += f", quorum via {len(arbiters)} arbiter(s)"
    print(
        f"store server on {host}:{server.port} (data_dir={data_dir}, {mode})",
        flush=True,
    )
    server._thread.join()


if __name__ == "__main__":
    main()
