"""The store as a network service: HTTP wire protocol + client backend.

The reference's only data plane is a MongoDB replica set every service
container points at via ``DATABASE_URL`` (reference:
docker-compose.yml:27-91 replica set, :188-192 per-service env). This
module is that role for the TPU framework: a store server process
exposing the full :class:`DocumentStore` interface over HTTP, and
:class:`RemoteStore`, the client backend the seven services use to run
as independent processes/containers against one shared store.

Wire protocol (JSON bodies; both ends are this module, so it is an
internal contract, versioned by the framework):

- ``GET  /collections``                         → ``{"collections": [...]}``
- ``POST /collections/<name>``                  → ``{"created": bool}`` (atomic claim)
- ``DELETE /collections/<name>``                → ``{}``
- ``POST /c/<name>/insert_one``     ``{"document": {...}}``
- ``POST /c/<name>/insert_many``    ``{"documents": [...]}``
- ``POST /c/<name>/insert_columns`` ``{"columns": {...}, "start_id": n|null}``
- ``POST /c/<name>/update_one``     ``{"query": {...}, "new_values": {...}}``
- ``POST /c/<name>/set_field_values`` ``{"field": f, "values": [[id, v], ...]}``
  (id/value pairs, not an object — JSON objects would stringify int ids)
- ``POST /c/<name>/set_column``     ``{"field": f, "values": [...], "start_id": n}``
- ``POST /c/<name>/find``           ``{"query", "skip", "limit"}`` → ``{"documents"}``
- ``POST /c/<name>/read_columns``   ``{"fields": [...]|null}`` → ``{"columns"}``
- ``POST /c/<name>/aggregate``      ``{"pipeline": [...]}`` → ``{"results"}``
- ``GET  /c/<name>/count``                      → ``{"count": n}``
- ``GET  /health``                              → ``{"ok": true}``

Error mapping: ``KeyError`` (duplicate ids/collections) → 409;
``UnsupportedQueryError`` → 400 with ``kind: unsupported_query``; other
``ValueError`` → 400. :class:`RemoteStore` re-raises the same exception
types, so service code behaves identically on a local or remote store.

Durability/replication posture: the server runs one WAL-backed
:class:`InMemoryStore` (SURVEY §2 notes replication is the external
store's concern in the reference; here the WAL is the durability story
and the server is the single writer).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Iterator, Optional

import requests

from learningorchestra_tpu.core.store import (
    DocumentStore,
    InMemoryStore,
    UnsupportedQueryError,
)
from learningorchestra_tpu.utils.web import ServerThread, WebApp

DEFAULT_STORE_PORT = 27027


def create_store_app(store: DocumentStore) -> WebApp:
    app = WebApp("store")

    def guarded(handler):
        def wrapped(request, **kwargs):
            try:
                return handler(request, **kwargs)
            except KeyError as error:
                return {"error": str(error)}, 409
            except UnsupportedQueryError as error:
                return {"error": str(error), "kind": "unsupported_query"}, 400
            except ValueError as error:
                return {"error": str(error)}, 400

        wrapped.__name__ = handler.__name__
        return wrapped

    @app.route("/health", methods=("GET",))
    def health(request):
        return {"ok": True}, 200

    @app.route("/collections", methods=("GET",))
    def list_collections(request):
        return {"collections": store.list_collections()}, 200

    @app.route("/collections/<name>", methods=("POST",))
    def create_collection(request, name):
        return {"created": store.create_collection(name)}, 200

    @app.route("/collections/<name>", methods=("DELETE",))
    def drop(request, name):
        store.drop(name)
        return {}, 200

    @app.route("/c/<name>/insert_one", methods=("POST",))
    @guarded
    def insert_one(request, name):
        store.insert_one(name, request.get_json()["document"])
        return {}, 200

    @app.route("/c/<name>/insert_many", methods=("POST",))
    @guarded
    def insert_many(request, name):
        store.insert_many(name, request.get_json()["documents"])
        return {}, 200

    @app.route("/c/<name>/insert_columns", methods=("POST",))
    @guarded
    def insert_columns(request, name):
        body = request.get_json()
        store.insert_columns(name, body["columns"], start_id=body.get("start_id"))
        return {}, 200

    @app.route("/c/<name>/update_one", methods=("POST",))
    @guarded
    def update_one(request, name):
        body = request.get_json()
        store.update_one(name, body["query"], body["new_values"])
        return {}, 200

    @app.route("/c/<name>/set_field_values", methods=("POST",))
    @guarded
    def set_field_values(request, name):
        body = request.get_json()
        store.set_field_values(name, body["field"], dict(body["values"]))
        return {}, 200

    @app.route("/c/<name>/set_column", methods=("POST",))
    @guarded
    def set_column(request, name):
        body = request.get_json()
        store.set_column(
            name, body["field"], body["values"], start_id=body.get("start_id", 1)
        )
        return {}, 200

    @app.route("/c/<name>/find", methods=("POST",))
    @guarded
    def find(request, name):
        body = request.get_json()
        documents = list(
            store.find(
                name,
                body.get("query") or {},
                skip=body.get("skip", 0),
                limit=body.get("limit"),
            )
        )
        return {"documents": documents}, 200

    @app.route("/c/<name>/read_columns", methods=("POST",))
    @guarded
    def read_columns(request, name):
        body = request.get_json()
        columns = store.read_columns(
            name,
            body.get("fields"),
            start=body.get("start", 0),
            limit=body.get("limit"),
        )
        return {"columns": columns}, 200

    @app.route("/c/<name>/aggregate", methods=("POST",))
    @guarded
    def aggregate(request, name):
        try:
            results = store.aggregate(name, request.get_json()["pipeline"])
        except NotImplementedError as error:
            return {"error": str(error)}, 400
        return {"results": results}, 200

    @app.route("/c/<name>/count", methods=("GET",))
    def count(request, name):
        return {"count": store.count(name)}, 200

    return app


class RemoteStore(DocumentStore):
    """A :class:`DocumentStore` over the store server's wire protocol.

    Drop-in for :class:`InMemoryStore` in every service — this is what
    turns the single-process runner into the reference's seven
    independent containers sharing one database (reference:
    docker-compose.yml:173-330)."""

    def __init__(
        self,
        base_url: str,
        timeout: float = 600.0,
        wire_rows: Optional[int] = None,
    ):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        # Rows per read_columns wire chunk (LO_WIRE_ROWS): bounds every
        # JSON body the data plane ships, mirroring the write batching
        # in core/table.py insert_columns_batched.
        self.wire_rows = max(
            1, wire_rows or int(os.environ.get("LO_WIRE_ROWS", "100000"))
        )
        self._local = threading.local()

    # one session per thread: requests.Session pools connections but is
    # not formally thread-safe
    @property
    def _session(self) -> requests.Session:
        session = getattr(self._local, "session", None)
        if session is None:
            session = requests.Session()
            self._local.session = session
        return session

    def _raise_for(self, response) -> None:
        if response.status_code == 409:
            raise KeyError(response.json().get("error", "duplicate"))
        if response.status_code == 400:
            payload = response.json()
            if payload.get("kind") == "unsupported_query":
                raise UnsupportedQueryError(payload.get("error", "bad query"))
            raise ValueError(payload.get("error", "bad request"))
        response.raise_for_status()

    def _post(self, path: str, body: dict) -> dict:
        response = self._session.post(
            f"{self.base_url}{path}",
            data=json.dumps(body),
            headers={"Content-Type": "application/json"},
            timeout=self.timeout,
        )
        self._raise_for(response)
        return response.json()

    def _get(self, path: str) -> dict:
        response = self._session.get(f"{self.base_url}{path}", timeout=self.timeout)
        self._raise_for(response)
        return response.json()

    def _delete(self, path: str) -> dict:
        response = self._session.delete(f"{self.base_url}{path}", timeout=self.timeout)
        self._raise_for(response)
        return response.json()

    # --- DocumentStore implementation -----------------------------------------
    def list_collections(self) -> list[str]:
        return self._get("/collections")["collections"]

    def create_collection(self, collection: str) -> bool:
        return self._post(f"/collections/{collection}", {})["created"]

    def drop(self, collection: str) -> None:
        self._delete(f"/collections/{collection}")

    def insert_one(self, collection: str, document: dict) -> None:
        self._post(f"/c/{collection}/insert_one", {"document": document})

    def insert_many(self, collection: str, documents: list[dict]) -> None:
        self._post(f"/c/{collection}/insert_many", {"documents": documents})

    def insert_columns(
        self,
        collection: str,
        columns: dict[str, list],
        start_id: Optional[int] = None,
    ) -> None:
        self._post(
            f"/c/{collection}/insert_columns",
            {"columns": columns, "start_id": start_id},
        )

    def update_one(self, collection: str, query: dict, new_values: dict) -> None:
        self._post(
            f"/c/{collection}/update_one",
            {"query": query, "new_values": new_values},
        )

    def set_field_values(
        self, collection: str, field: str, values_by_id: dict
    ) -> None:
        self._post(
            f"/c/{collection}/set_field_values",
            {"field": field, "values": list(values_by_id.items())},
        )

    def set_column(
        self, collection: str, field: str, values: list, start_id: int = 1
    ) -> None:
        self._post(
            f"/c/{collection}/set_column",
            {"field": field, "values": values, "start_id": start_id},
        )

    def find(
        self,
        collection: str,
        query: Optional[dict] = None,
        skip: int = 0,
        limit: Optional[int] = None,
    ) -> Iterator[dict]:
        payload = self._post(
            f"/c/{collection}/find",
            {"query": query or {}, "skip": skip, "limit": limit},
        )
        return iter(payload["documents"])

    def read_columns(
        self,
        collection: str,
        fields: Optional[list[str]] = None,
        start: int = 0,
        limit: Optional[int] = None,
    ) -> dict[str, list]:
        """Paged on the wire: rows travel in ``wire_rows`` chunks (the
        read half of ``insert_columns_batched``'s write batching), so a
        10M-row dataset never rides one giant JSON body. The chunk loop
        stops at a short chunk; an explicit ``limit`` caps the total."""
        out: dict[str, list] = {}
        fetched = 0
        while True:
            chunk_limit = self.wire_rows
            if limit is not None:
                chunk_limit = min(chunk_limit, limit - fetched)
                if chunk_limit <= 0:
                    break
            chunk = self._post(
                f"/c/{collection}/read_columns",
                {
                    "fields": fields,
                    "start": start + fetched,
                    "limit": chunk_limit,
                },
            )["columns"]
            if not out:
                out = {name: list(values) for name, values in chunk.items()}
            else:
                for name, values in chunk.items():
                    out[name].extend(values)
            chunk_rows = max((len(v) for v in chunk.values()), default=0)
            fetched += chunk_rows
            # Short chunk = exhausted; empty chunk breaks unconditionally
            # so a degenerate chunk_limit can never spin forever.
            if chunk_rows < chunk_limit or chunk_rows == 0:
                break
        return out

    def aggregate(self, collection: str, pipeline: list[dict]) -> list[dict]:
        return self._post(f"/c/{collection}/aggregate", {"pipeline": pipeline})[
            "results"
        ]

    def count(self, collection: str) -> int:
        return self._get(f"/c/{collection}/count")["count"]


def connect(url: Optional[str] = None) -> DocumentStore:
    """The services' store factory: a :class:`RemoteStore` when a store
    URL is configured (``LO_STORE_URL`` — the analogue of the reference's
    ``DATABASE_URL``), else a process-local WAL-backed store."""
    url = url if url is not None else os.environ.get("LO_STORE_URL")
    if url:
        return RemoteStore(url)
    data_dir = os.environ.get("LO_DATA_DIR")
    return InMemoryStore(data_dir=data_dir)


def serve(
    host: str = "127.0.0.1",
    port: int = DEFAULT_STORE_PORT,
    data_dir: Optional[str] = None,
) -> ServerThread:
    """Start a store server thread; returns it (caller stops)."""
    store = InMemoryStore(data_dir=data_dir)
    return ServerThread(create_store_app(store), host, port).start()


def main() -> None:
    host = os.environ.get("LO_HOST", "127.0.0.1")
    port = int(os.environ.get("LO_STORE_PORT", DEFAULT_STORE_PORT))
    data_dir = os.environ.get("LO_DATA_DIR")
    server = serve(host, port, data_dir)
    print(f"store server on {host}:{server.port} (data_dir={data_dir})", flush=True)
    server._thread.join()


if __name__ == "__main__":
    main()
