"""``ShardedStore``: one DocumentStore over N shard groups.

The client half of horizontal sharding (core/shardmap.py holds the
placement math and the shard-map service contract; docs/dataplane.md
the operator story). Each child store is one shard GROUP — in
production a :class:`~learningorchestra_tpu.core.store_service.
RemoteStore` whose URL list names the group's primary and follower, so
every group keeps the replicated-failover machinery untouched; in
tests the children can be plain :class:`~learningorchestra_tpu.core.
store.InMemoryStore` instances.

Routing contract:

- **Columnar block rows** are striped across ALL groups by the
  consistent-hash layout; ids are translated global↔local so each
  group's block stays dense from local id 1 (the block-append
  contiguity invariant holds per group — which is also why sharded
  blocks must start at global id 1, the only start the system writes).
- **Row documents** (the ``_id: 0`` metadata document, out-of-band
  inserts, ring collections, the scheduler journal) all live on the
  META group (group 0) with their GLOBAL ids — document collections
  behave byte-identically to the unsharded store.
- **Reads scatter-gather**: a positional columnar read decomposes into
  ONE contiguous per-group run, fetched concurrently (each group's
  RemoteStore brings its own paged prefetch, zero-copy wire-v2 decode,
  and shm ring), then reassembled stripe-by-stripe in global order.
  Cross-group reads are not atomic — the same cursor guarantee the
  unsharded paged read already gives under concurrent writes.
- **Ordering**: block rows sort before overlay documents. Overlay int
  ids are always past the block (the block-append duplicate guard
  enforces it), so the merged stream matches the unsharded ``_id``
  order for every collection the system writes.

``connect()`` (core/store_service.py) builds one of these when
``LO_STORE_URL`` lists shard groups separated by ``;`` — a single
group degenerates to a plain ``RemoteStore``, keeping the default
wire path byte-identical to the unsharded deployment.
"""

from __future__ import annotations

import heapq
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Iterator, Optional

import numpy as np

from learningorchestra_tpu.core import shardmap as _shardmap
from learningorchestra_tpu.core.columns import Column
from learningorchestra_tpu.core.shardmap import ShardMapClient
from learningorchestra_tpu.core.store import (
    METADATA_ID,
    ROW_ID,
    ColumnInput,
    DocumentStore,
    _group_count,
    _is_int_id,
    as_column,
    matches,
)


def _query_mentions_id(query: dict) -> bool:
    """True when the query constrains ``_id`` anywhere — such a query
    cannot be pushed down to a shard, whose block ids are local."""
    for key, condition in query.items():
        if key == ROW_ID:
            return True
        if key in ("$or", "$and", "$nor") and isinstance(
            condition, (list, tuple)
        ):
            if any(
                isinstance(sub, dict) and _query_mentions_id(sub)
                for sub in condition
            ):
                return True
    return False


def _id_sort_key(doc_id: Any) -> tuple:
    """The unsharded store's id order: ints ascending, then everything
    else by string."""
    if _is_int_id(doc_id):
        return (0, doc_id, "")
    return (1, 0, str(doc_id))


def _slice_concat(column: Column, segments: list[tuple[int, int]]) -> Column:
    """Concatenate ``column``'s ``[offset, offset+count)`` slices — the
    per-shard payload of a decomposed global range (slices of numeric
    kinds are O(1) views, so this never copies the source block)."""
    offset, count = segments[0]
    out = column.slice(offset, offset + count)
    for offset, count in segments[1:]:
        out = out.append_column(column.slice(offset, offset + count))
    return out


def _occupancy_of(group) -> dict:
    """A group's occupancy dict, whichever store kind it is: remote
    groups expose ``occupancy_stats`` (the /health surface), local ones
    ``telemetry_stats``."""
    for accessor in ("occupancy_stats", "telemetry_stats"):
        probe = getattr(group, accessor, None)
        if probe is not None:
            try:
                stats = probe()
            except Exception:
                return {}
            return stats if isinstance(stats, dict) else {}
    return {}


class ShardedStore(DocumentStore):
    """Scatter-gather DocumentStore over shard groups (group 0 = meta)."""

    def __init__(
        self,
        groups: list,
        stripe_rows: Optional[int] = None,
        map_ttl_s: Optional[float] = None,
    ):
        if not groups:
            raise ValueError("ShardedStore needs at least one group")
        self.groups = list(groups)
        self.shards = len(self.groups)
        configured_stripe = (
            _shardmap.stripe_rows() if stripe_rows is None else stripe_rows
        )
        self._map = ShardMapClient(
            self.groups[0], self.shards, configured_stripe, ttl_s=map_ttl_s
        )
        # devcache scope dimension: a topology change must invalidate
        # every cached entry (core/devcache.py store_token)
        self.shard_signature = f"sh{self.shards}x{configured_stripe}"
        # scatter-gather fan-out observer; telemetry/metrics.py
        # register_sharded_store points this at its histogram
        self.on_fanout = None
        self._pool: Optional[ThreadPoolExecutor] = None
        self._pool_lock = threading.Lock()

    # --- plumbing -------------------------------------------------------------
    @property
    def _meta(self):
        return self.groups[0]

    def layout(self) -> _shardmap.ShardLayout:
        layout = self._map.layout()
        self.shard_signature = f"sh{layout.shards}x{layout.stripe_rows}"
        return layout

    def shardmap_rev(self) -> int:
        """Last observed shard-map collection rev (telemetry surface)."""
        return self._map.rev

    def shard_occupancy(self) -> list[dict]:
        """Per-group occupancy dicts, meta group first (telemetry)."""
        return self._scatter(
            [(lambda g=group: _occupancy_of(g)) for group in self.groups]
        )

    def _executor(self) -> ThreadPoolExecutor:
        with self._pool_lock:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=self.shards, thread_name_prefix="lo-shard"
                )
            return self._pool

    def _scatter(self, calls: list) -> list:
        """Run thunks concurrently (one per group at most); a single
        call runs inline with no pool round-trip."""
        if len(calls) == 1:
            return [calls[0]()]
        futures = [self._executor().submit(call) for call in calls]
        results: list = []
        first_error: Optional[BaseException] = None
        for future in futures:
            try:
                results.append(future.result())
            except BaseException as error:  # noqa: BLE001 — re-raised
                results.append(None)
                if first_error is None:
                    first_error = error
        if first_error is not None:
            raise first_error
        return results

    def _observe_fanout(self, width: int) -> None:
        hook = self.on_fanout
        if hook is not None:
            hook(width)

    def close(self) -> None:
        with self._pool_lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            # outside the lock: shutdown joins no threads (wait=False)
            # but even the teardown handshake must not park a
            # concurrent _executor() caller on the lock
            pool.shutdown(wait=False)
        for group in self.groups:
            close = getattr(group, "close", None)
            if close is not None:
                close()

    @staticmethod
    def _block_rows_of(group, collection: str) -> int:
        probe = getattr(group, "collection_block_rows", None)
        if probe is None:
            return 0
        return max(0, probe(collection))

    def _group_block_rows(self, collection: str) -> list[int]:
        """Per-group block row counts (one concurrent probe sweep)."""
        return self._scatter(
            [
                (lambda g=group: self._block_rows_of(g, collection))
                for group in self.groups
            ]
        )

    # --- collection lifecycle -------------------------------------------------
    def list_collections(self) -> list[str]:
        names: list[str] = []
        for listed in self._scatter(
            [group.list_collections for group in self.groups]
        ):
            for name in listed:
                if name not in names:
                    names.append(name)
        return names

    def create_collection(self, collection: str) -> bool:
        # the meta group is the claim authority (atomic winner); data
        # groups follow idempotently — a lost race there just means a
        # concurrent creator already materialized the shard
        won = self._meta.create_collection(collection)
        if won:
            for group in self.groups[1:]:
                group.create_collection(collection)
        return won

    def drop(self, collection: str) -> None:
        self._scatter(
            [(lambda g=group: g.drop(collection)) for group in self.groups]
        )

    def trim_collection(self, collection: str, max_docs: int) -> int:
        # rings are row-document collections: meta-group only
        return self._meta.trim_collection(collection, max_docs)

    # --- writes ---------------------------------------------------------------
    def insert_one(self, collection: str, document: dict) -> None:
        self._meta.insert_one(collection, document)

    def insert_many(self, collection: str, documents: list[dict]) -> None:
        self._meta.insert_many(collection, documents)

    def insert_columns(
        self,
        collection: str,
        columns: dict[str, ColumnInput],
        start_id: Optional[int] = None,
    ) -> None:
        if ROW_ID in columns:
            raise ValueError("_id is implicit in insert_columns (start_id..)")
        typed = {name: as_column(values) for name, values in columns.items()}
        lengths = {len(values) for values in typed.values()}
        if len(lengths) > 1:
            raise ValueError("ragged columns")
        self.insert_column_arrays(collection, typed, start_id=start_id)

    def insert_column_arrays(
        self,
        collection: str,
        columns: dict[str, Column],
        start_id: Optional[int] = None,
    ) -> None:
        if self.shards == 1:
            self._meta.insert_column_arrays(collection, columns, start_id)
            return
        layout = self.layout()
        rows = len(next(iter(columns.values()))) if columns else 0
        if start_id is None:
            # the global append position: one past the striped block
            start_id = 1 + sum(self._group_block_rows(collection))
        if rows == 0:
            self._meta.insert_column_arrays(collection, columns, start_id)
            return
        runs = layout.decompose(start_id, rows)
        self._observe_fanout(len(runs))

        def write(run: dict) -> None:
            payload = {
                name: _slice_concat(column, run["segments"])
                for name, column in columns.items()
            }
            self.groups[run["shard"]].insert_column_arrays(
                collection, payload, start_id=run["local_start"]
            )

        self._scatter([(lambda r=run: write(r)) for run in runs])

    def set_column(
        self,
        collection: str,
        field: str,
        values: ColumnInput,
        start_id: int = 1,
    ) -> None:
        if self.shards == 1:
            self._meta.set_column(collection, field, values, start_id)
            return
        typed = as_column(values)
        runs = self.layout().decompose(start_id, len(typed))
        if not runs:
            return
        self._observe_fanout(len(runs))

        def write(run: dict) -> None:
            self.groups[run["shard"]].set_column(
                collection,
                field,
                _slice_concat(typed, run["segments"]),
                start_id=run["local_start"],
            )

        self._scatter([(lambda r=run: write(r)) for run in runs])

    def set_field_values(
        self, collection: str, field: str, values_by_id: dict
    ) -> None:
        if self.shards == 1:
            self._meta.set_field_values(collection, field, values_by_id)
            return
        layout = self.layout()
        block_stop = 1 + sum(self._group_block_rows(collection))
        per_target: dict[int, dict] = {}
        for doc_id, value in values_by_id.items():
            if _is_int_id(doc_id) and 1 <= doc_id < block_stop:
                shard, local = layout.global_to_local(doc_id)
                per_target.setdefault(shard, {})[local] = value
            else:  # metadata / overlay / non-int ids live on meta
                per_target.setdefault(-1, {})[doc_id] = value
        if not per_target:
            return
        self._observe_fanout(len(per_target))

        def write(shard: int, batch: dict) -> None:
            target = self._meta if shard == -1 else self.groups[shard]
            target.set_field_values(collection, field, batch)

        self._scatter(
            [
                (lambda s=shard, b=batch: write(s, b))
                for shard, batch in per_target.items()
            ]
        )

    def update_one(
        self, collection: str, query: dict, new_values: dict
    ) -> None:
        if self.shards == 1:
            self._meta.update_one(collection, query, new_values)
            return
        if list(query.keys()) == [ROW_ID] and not isinstance(
            query[ROW_ID], dict
        ):
            doc_id = query[ROW_ID]
        else:
            found = self.find_one(collection, query)
            if found is None:
                return
            doc_id = found.get(ROW_ID)
        if _is_int_id(doc_id) and doc_id >= 1:
            block_stop = 1 + sum(self._group_block_rows(collection))
            if doc_id < block_stop:
                shard, local = self.layout().global_to_local(doc_id)
                self.groups[shard].update_one(
                    collection, {ROW_ID: local}, new_values
                )
                return
        self._meta.update_one(collection, {ROW_ID: doc_id}, new_values)

    # --- reads ----------------------------------------------------------------
    def _find_literal(
        self, collection: str, doc_id: Any, limit: Optional[int]
    ) -> Iterator[dict]:
        """Point lookup by literal id — 2 RPCs, no scatter.

        A global id beyond the block could translate onto a local id a
        shard DOES hold (for a different global row), so existence is
        decided against the meta group's block size: group 0's block
        occupies local ids ``1..meta_block`` and overlay ids are always
        past the whole global block (> meta_block), which makes every
        branch below unambiguous.
        """
        if limit == 0:
            return iter(())
        if _is_int_id(doc_id) and doc_id >= 1:
            layout = self.layout()
            meta_block = self._block_rows_of(self._meta, collection)
            shard, local = layout.global_to_local(doc_id)
            if shard == 0:
                if local <= meta_block:
                    found = self._meta.find_one(collection, {ROW_ID: local})
                    if found is None:
                        return iter(())
                    found = dict(found)
                    found[ROW_ID] = doc_id
                    return iter((found,))
            else:
                found = self.groups[shard].find_one(
                    collection, {ROW_ID: local}
                )
                if found is not None:
                    found = dict(found)
                    found[ROW_ID] = doc_id
                    return iter((found,))
            if doc_id <= meta_block:
                # would collide with a meta block row's local id; an
                # overlay doc can never sit this low
                return iter(())
        found = self._meta.find_one(collection, {ROW_ID: doc_id})
        return iter(()) if found is None else iter((found,))

    def find(
        self,
        collection: str,
        query: Optional[dict] = None,
        skip: int = 0,
        limit: Optional[int] = None,
    ) -> Iterator[dict]:
        query = query or {}
        if self.shards == 1:
            return self._meta.find(collection, query, skip=skip, limit=limit)
        if (
            list(query.keys()) == [ROW_ID]
            and not isinstance(query[ROW_ID], dict)
            and skip == 0
        ):
            return self._find_literal(collection, query[ROW_ID], limit)
        layout = self.layout()
        meta_block = self._block_rows_of(self._meta, collection)
        # an id-constrained query cannot push down (shard ids are
        # local): scatter unfiltered and re-filter on translated docs
        push_down = not _query_mentions_id(query)
        shard_query = query if push_down else {}

        def data_stream(shard: int) -> Iterator[tuple]:
            for doc in self.groups[shard].find(collection, shard_query):
                doc_id = doc.get(ROW_ID)
                if not _is_int_id(doc_id) or doc_id == METADATA_ID:
                    continue  # data groups hold block rows only
                doc = dict(doc)
                doc[ROW_ID] = layout.local_to_global(shard, doc_id)
                if push_down or matches(doc, query):
                    yield (_id_sort_key(doc[ROW_ID]), doc)

        def meta_stream() -> Iterator[tuple]:
            # group 0 plays both roles: its block rows carry LOCAL ids
            # (<= meta_block), its overlay documents global ones
            for doc in self._meta.find(collection, shard_query):
                doc_id = doc.get(ROW_ID)
                if _is_int_id(doc_id) and 1 <= doc_id <= meta_block:
                    doc = dict(doc)
                    doc[ROW_ID] = layout.local_to_global(0, doc_id)
                if push_down or matches(doc, query):
                    yield (_id_sort_key(doc.get(ROW_ID)), doc)

        streams = [meta_stream()] + [
            data_stream(shard) for shard in range(1, self.shards)
        ]
        self._observe_fanout(len(streams))

        def generate() -> Iterator[dict]:
            produced = 0
            skipped = 0
            for _, doc in heapq.merge(*streams, key=lambda item: item[0]):
                if skipped < skip:
                    skipped += 1
                    continue
                if limit is not None and produced >= limit:
                    return
                produced += 1
                yield doc

        return generate()

    def count(self, collection: str) -> int:
        return sum(
            self._scatter(
                [
                    (lambda g=group: g.count(collection))
                    for group in self.groups
                ]
            )
        )

    def collection_rev(self, collection: str) -> int:
        revs = self._scatter(
            [
                (lambda g=group: g.collection_rev(collection))
                for group in self.groups
            ]
        )
        live = [rev for rev in revs if rev >= 0]
        if not live:
            return -1  # missing everywhere IS missing
        if len(live) < len(revs):
            return -1  # any group unable to report opts cached readers out
        return sum(live)

    def collection_block_rows(self, collection: str) -> int:
        return sum(self._group_block_rows(collection))

    def aggregate(self, collection: str, pipeline: list[dict]) -> list[dict]:
        if self.shards == 1:
            return self._meta.aggregate(collection, pipeline)
        if any(
            "$match" in stage and _query_mentions_id(stage["$match"])
            for stage in pipeline
        ):
            # id-constrained $match cannot push down: run the pipeline
            # client-side over the translated merged stream
            results: list[dict] = [
                doc
                for doc in self.find(collection)
                if doc.get(ROW_ID) != METADATA_ID
            ]
            for stage in pipeline:
                if "$match" in stage:
                    results = [
                        doc
                        for doc in results
                        if matches(doc, stage["$match"])
                    ]
                elif "$group" in stage:
                    key_expr = stage["$group"].get("_id")
                    if not (
                        isinstance(key_expr, str) and key_expr.startswith("$")
                    ):
                        raise NotImplementedError(
                            f"unsupported $group key {key_expr!r}"
                        )
                    results = _group_count(iter(results), key_expr[1:])
                else:
                    raise NotImplementedError(
                        f"unsupported pipeline stage {stage}"
                    )
            return results
        group_field = None
        for stage in pipeline:
            if "$group" in stage:
                key_expr = stage["$group"].get("_id")
                if isinstance(key_expr, str) and key_expr.startswith("$"):
                    group_field = key_expr[1:]
        layout = self.layout()
        meta_block = self._block_rows_of(self._meta, collection)
        partials = self._scatter(
            [
                (lambda g=group: g.aggregate(collection, pipeline))
                for group in self.groups
            ]
        )
        self._observe_fanout(len(partials))
        merged: dict[tuple, int] = {}
        for shard, results in enumerate(partials):
            for entry in results:
                key = entry["_id"]
                if group_field == ROW_ID and _is_int_id(key):
                    # data-shard keys are always local block ids; on
                    # meta only ids within its block are (overlay keys
                    # are global already, past the whole block)
                    if shard > 0 or 1 <= key <= meta_block:
                        key = layout.local_to_global(shard, key)
                tagged = (isinstance(key, bool), key)
                merged[tagged] = merged.get(tagged, 0) + entry["count"]
        entries = [
            {"_id": key, "count": count}
            for (_, key), count in merged.items()
        ]
        if group_field == ROW_ID:
            entries.sort(key=lambda entry: _id_sort_key(entry["_id"]))
        return entries

    def read_columns(
        self,
        collection: str,
        fields: Optional[list[str]] = None,
        start: int = 0,
        limit: Optional[int] = None,
    ) -> dict[str, list]:
        arrays = self.read_column_arrays(collection, fields, start, limit)
        return {name: column.tolist() for name, column in arrays.items()}

    def read_column_arrays(
        self,
        collection: str,
        fields: Optional[list[str]] = None,
        start: int = 0,
        limit: Optional[int] = None,
    ) -> dict[str, Column]:
        if self.shards == 1:
            return self._meta.read_column_arrays(
                collection, fields, start=start, limit=limit
            )
        layout = self.layout()
        group_rows = self._group_block_rows(collection)
        block_total = sum(group_rows)
        data_fields = (
            None
            if fields is None
            else [name for name in fields if name != ROW_ID]
        )
        # positional row space: the block occupies [0, block_total),
        # the meta group's overlay tail follows (matching the unsharded
        # merged-id page order)
        stop = None if limit is None else start + limit
        block_lo = min(max(start, 0), block_total)
        block_hi = block_total if stop is None else min(max(stop, 0), block_total)
        runs: list[dict] = []
        fetched: dict[int, dict[str, Column]] = {}
        if block_hi > block_lo:
            runs = layout.decompose(block_lo + 1, block_hi - block_lo)

            def fetch(run: dict) -> dict[str, Column]:
                return self.groups[run["shard"]].read_column_arrays(
                    collection,
                    data_fields,
                    start=run["local_start"] - 1,
                    limit=run["rows"],
                )

            for run, result in zip(
                runs,
                self._scatter([(lambda r=run: fetch(r)) for run in runs]),
            ):
                fetched[run["shard"]] = result
        overlay: dict[str, Column] = {}
        if stop is None or stop > block_total:
            # the overlay tail sits on meta AFTER its own block rows,
            # so its positional window starts past them
            overlay_start = group_rows[0] + max(start - block_total, 0)
            overlay_limit = (
                None if stop is None else stop - max(start, block_total)
            )
            overlay = self._meta.read_column_arrays(
                collection, fields, start=overlay_start, limit=overlay_limit
            )
            if not any(len(column) for column in overlay.values()):
                overlay = {}
        self._observe_fanout(len(runs) + (1 if overlay else 0))
        if fields is not None:
            names = list(fields)
        else:
            names = []
            for run in runs:
                for name in fetched[run["shard"]]:
                    if name not in names:
                        names.append(name)
            for name in overlay:
                if name not in names:
                    names.append(name)
        # reassemble in global stripe order: each shard's fetched run
        # is consumed sequentially while segments interleave by offset
        interleaved: list[tuple[int, int, int]] = []
        for run in runs:
            for offset, count in run["segments"]:
                interleaved.append((offset, count, run["shard"]))
        interleaved.sort()
        out: dict[str, Column] = {}
        for name in names:
            if name == ROW_ID:
                # never shipped from a shard — synthesized from the
                # global range, then the overlay's real ids appended
                column = Column.from_numpy(
                    np.arange(block_lo + 1, block_hi + 1, dtype=np.int64)
                )
                if name in overlay:
                    column = column.append_column(overlay[name])
                out[name] = column
                continue
            parts: list[Column] = []
            taken = {run["shard"]: 0 for run in runs}
            for _, count, shard in interleaved:
                source = fetched[shard].get(name)
                position = taken[shard]
                taken[shard] = position + count
                if source is None:
                    parts.append(Column.pads(count))
                else:
                    parts.append(source.slice(position, position + count))
            if name in overlay:
                parts.append(overlay[name])
            if not parts:
                out[name] = Column.pads(0)
                continue
            column = parts[0]
            for part in parts[1:]:
                column = column.append_column(part)
            out[name] = column
        return out
