"""Horizontal sharding: the hash ring, stripe arithmetic, and the
shard-map service contract (docs/dataplane.md "Horizontal sharding").

The reference's data plane stops at one MongoDB replica set; ours
stopped at one replicated store group. ``LO_SHARDS=N`` partitions every
collection's columnar block across N shard GROUPS (each group is the
existing primary+follower+arbiter unit — all of the failover machinery
is reused untouched, per group):

- **Stripes, not rows.** Row ``_id``s are striped in runs of
  ``LO_SHARD_STRIPE_ROWS`` (stripe ``k`` covers global ids
  ``k*S+1 .. (k+1)*S``) and each stripe is placed by a consistent hash
  of its index on a 64-vnode ring. Striping keeps per-request fan-out
  bounded (one contiguous run per shard per call) where per-row hashing
  would shatter every wire frame.
- **Local contiguity.** A shard stores its stripes as ONE dense local
  block: stripe ``k``'s local position is determined by how many
  earlier stripes hashed to the same shard (a prefix count), so the
  per-shard store never sees a gap and the block-append contiguity
  contract (core/store.py) holds unchanged. Global↔local id translation
  is pure arithmetic over the memoized ring walk — no lookup table is
  ever shipped.
- **The meta group (shard 0)** additionally owns every row-DOCUMENT:
  the ``_id: 0`` metadata document, out-of-band inserts, ring
  collections, and the scheduler journal. Document ids stay global —
  only block rows are translated — so document collections behave
  byte-identically to the unsharded store.
- **The shard map** is one document in the ``__lo_shardmap__``
  collection on the meta group, seeded by the first writer through the
  store's atomic ``create_collection`` claim and cached client-side
  rev-style like the devcache: cached values serve for
  ``LO_SHARDMAP_TTL_S`` seconds, then the collection's rev is probed
  and a mismatch re-reads the document. The map is authoritative for
  the stripe width — a client configured differently adopts the map's
  values, so one fleet can never run two geometries.

Rebalancing is a declared NON-goal: the ring is fixed at the map's
shard count for the life of the deployment (drain and re-ingest to
re-shard; the scheduler journal's topology-suffixed scopes make old
entries foreign on a changed topology, sched/journal.py).
"""

from __future__ import annotations

import bisect
import hashlib
import os
import threading
import time
from typing import Optional

SHARDMAP_COLLECTION = "__lo_shardmap__"
SHARDMAP_DOC_ID = 1

DEFAULT_STRIPE_ROWS = 8192
DEFAULT_MAP_TTL_S = 5.0
_RING_VNODES = 64


def stripe_rows() -> int:
    """``LO_SHARD_STRIPE_ROWS`` validated (deploy/run.sh preflights
    this): rows per placement stripe, strictly integral >= 1. Only the
    SEEDING writer's value matters — every later client adopts the
    shard map's stripe width."""
    # lo: allow[LO305] this IS the validated accessor preflight calls
    raw = os.environ.get("LO_SHARD_STRIPE_ROWS", "").strip()
    if not raw:
        return DEFAULT_STRIPE_ROWS
    try:
        value = int(raw)
    except ValueError:
        raise ValueError(
            f"LO_SHARD_STRIPE_ROWS must be an integer, got {raw!r}"
        ) from None
    if value < 1:
        raise ValueError(
            f"LO_SHARD_STRIPE_ROWS must be >= 1, got {value}"
        )
    return value


def map_ttl_s() -> float:
    """``LO_SHARDMAP_TTL_S`` validated (deploy/run.sh preflights this):
    seconds a cached shard map serves before its rev is revalidated.
    ``0`` revalidates on every routed call."""
    # lo: allow[LO305] this IS the validated accessor preflight calls
    raw = os.environ.get("LO_SHARDMAP_TTL_S", "").strip()
    if not raw:
        return DEFAULT_MAP_TTL_S
    try:
        value = float(raw)
    except ValueError:
        raise ValueError(
            f"LO_SHARDMAP_TTL_S must be seconds >= 0, got {raw!r}"
        ) from None
    if value < 0:
        raise ValueError(f"LO_SHARDMAP_TTL_S must be >= 0, got {value}")
    return value


def validate_env() -> None:
    """Entry-point preflight (deploy/run.sh): a typo'd shard knob must
    refuse bring-up, never silently run an unintended geometry."""
    stripe_rows()
    map_ttl_s()


def _ring_hash(key: str) -> int:
    # blake2b over md5: no usedforsecurity gymnastics on FIPS builds,
    # and 8 bytes of digest is plenty of ring resolution
    return int.from_bytes(
        hashlib.blake2b(key.encode(), digest_size=8).digest(), "big"
    )


class ShardLayout:
    """Stripe→shard placement plus global↔local id arithmetic.

    The ring walk is memoized per instance: ``_stripe_shard[k]`` is
    stripe ``k``'s shard, ``_local_index[k]`` its prefix count within
    that shard (how many earlier stripes share it), and
    ``_stripes_of[s]`` the ordered global stripes of shard ``s`` — the
    inverse map local→global translation needs. All three grow together
    under one lock; every public method is thread-safe.
    """

    def __init__(self, shards: int, stripe_rows: int):
        if shards < 1:
            raise ValueError(f"shards must be >= 1, got {shards}")
        if stripe_rows < 1:
            raise ValueError(
                f"stripe_rows must be >= 1, got {stripe_rows}"
            )
        self.shards = shards
        self.stripe_rows = stripe_rows
        points = []
        for shard in range(shards):
            for vnode in range(_RING_VNODES):
                points.append((_ring_hash(f"shard:{shard}:{vnode}"), shard))
        points.sort()
        self._ring_points = [point for point, _ in points]
        self._ring_shards = [shard for _, shard in points]
        self._stripe_shard: list[int] = []
        self._local_index: list[int] = []
        self._stripes_of: list[list[int]] = [[] for _ in range(shards)]
        self._lock = threading.Lock()

    def shard_of_stripe(self, stripe: int) -> int:
        return self._placement(stripe)[0]

    def stripe_of(self, gid: int) -> int:
        if gid < 1:
            raise ValueError(f"block row ids start at 1, got {gid}")
        return (gid - 1) // self.stripe_rows

    def shard_of_id(self, gid: int) -> int:
        return self.shard_of_stripe(self.stripe_of(gid))

    def _grow_one(self) -> None:
        # caller holds self._lock (both call sites enter it first)
        k = len(self._stripe_shard)  # lo: allow[LO203]
        if self.shards == 1:
            shard = 0
        else:
            point = _ring_hash(f"stripe:{k}")
            index = bisect.bisect_right(self._ring_points, point)
            shard = self._ring_shards[index % len(self._ring_shards)]
        self._stripe_shard.append(shard)
        self._local_index.append(len(self._stripes_of[shard]))  # lo: allow[LO203]
        self._stripes_of[shard].append(k)

    def _placement(self, stripe: int) -> tuple[int, int]:
        """``(shard, prefix_index)`` of a stripe: the memoized ring walk
        grows AND is read under the one lock, so callers never touch the
        grow-lists themselves."""
        with self._lock:
            while len(self._stripe_shard) <= stripe:
                self._grow_one()
            return self._stripe_shard[stripe], self._local_index[stripe]

    def global_to_local(self, gid: int) -> tuple[int, int]:
        """``(shard, local_id)`` for global block row ``gid``."""
        shard, prefix = self._placement(self.stripe_of(gid))
        local = (
            prefix * self.stripe_rows + (gid - 1) % self.stripe_rows + 1
        )
        return shard, local

    def local_to_global(self, shard: int, local_id: int) -> int:
        """Inverse translation for rows a shard reports with LOCAL ids
        (find results, group keys). Grows the ring walk until the
        shard's stripe list covers the local stripe."""
        if local_id < 1:
            raise ValueError(f"block row ids start at 1, got {local_id}")
        m = (local_id - 1) // self.stripe_rows
        with self._lock:
            while len(self._stripes_of[shard]) <= m:
                self._grow_one()
            stripe = self._stripes_of[shard][m]
        return stripe * self.stripe_rows + (local_id - 1) % self.stripe_rows + 1

    def decompose(self, start_gid: int, rows: int) -> list[dict]:
        """A contiguous global id range as one run per shard.

        Because the range is contiguous, the stripes that land on a
        given shard are consecutive in that shard's local order, so the
        whole per-shard slice is ONE locally-contiguous write/read:
        ``[{"shard", "local_start", "segments": [(offset, count), ...],
        "rows"}]`` where each segment's ``offset`` is relative to
        ``start_gid`` (the caller's slice coordinates), emitted in
        global order.
        """
        if rows <= 0:
            return []
        runs: dict[int, dict] = {}
        stop_gid = start_gid + rows
        stripe = self.stripe_of(start_gid)
        gid = start_gid
        while gid < stop_gid:
            stripe_stop = (stripe + 1) * self.stripe_rows + 1
            seg_stop = min(stop_gid, stripe_stop)
            shard = self.shard_of_stripe(stripe)
            run = runs.get(shard)
            if run is None:
                run = {
                    "shard": shard,
                    "local_start": self.global_to_local(gid)[1],
                    "segments": [],
                    "rows": 0,
                }
                runs[shard] = run
            run["segments"].append((gid - start_gid, seg_stop - gid))
            run["rows"] += seg_stop - gid
            gid = seg_stop
            stripe += 1
        return sorted(runs.values(), key=lambda run: run["shard"])


class ShardMapClient:
    """The client half of the shard-map service: one document on the
    meta group, seeded through the atomic collection claim, cached with
    TTL + rev revalidation (the devcache's pull-invalidation contract —
    a store server cannot call into every client)."""

    def __init__(
        self,
        meta_store,
        shards: int,
        stripe_rows: int,
        ttl_s: Optional[float] = None,
    ):
        self._meta = meta_store
        self._shards = shards
        self._stripe_rows = stripe_rows
        self._ttl_s = map_ttl_s() if ttl_s is None else ttl_s
        self._lock = threading.Lock()
        self._doc: Optional[dict] = None
        self._doc_rev = -1
        self._checked_at = 0.0

    @property
    def rev(self) -> int:
        """The map collection's last observed rev (telemetry surface)."""
        with self._lock:
            return self._doc_rev

    def document(self) -> dict:
        """The live map document, seeding it on first contact."""
        now = time.monotonic()
        with self._lock:
            if (
                self._doc is not None
                and now - self._checked_at < self._ttl_s
            ):
                return self._doc
            live_rev = self._meta.collection_rev(SHARDMAP_COLLECTION)
            if self._doc is not None and live_rev == self._doc_rev:
                self._checked_at = now
                return self._doc
            doc = self._meta.find_one(
                SHARDMAP_COLLECTION, {"_id": SHARDMAP_DOC_ID}
            )
            if doc is None:
                # first contact: claim-then-seed; a lost claim means a
                # concurrent seeder won — read their document instead
                if self._meta.create_collection(SHARDMAP_COLLECTION):
                    doc = {
                        "_id": SHARDMAP_DOC_ID,
                        "shards": self._shards,
                        "stripe_rows": self._stripe_rows,
                    }
                    self._meta.insert_one(SHARDMAP_COLLECTION, doc)
                else:
                    doc = self._meta.find_one(
                        SHARDMAP_COLLECTION, {"_id": SHARDMAP_DOC_ID}
                    )
                    if doc is None:  # claimed but not yet seeded: ours
                        doc = {
                            "_id": SHARDMAP_DOC_ID,
                            "shards": self._shards,
                            "stripe_rows": self._stripe_rows,
                        }
                        self._meta.insert_one(SHARDMAP_COLLECTION, doc)
            if doc["shards"] != self._shards:
                raise ValueError(
                    f"shard map says {doc['shards']} shard groups but "
                    f"this client is wired to {self._shards} — "
                    "LO_STORE_URL does not match the deployed topology"
                )
            self._doc = doc
            self._doc_rev = self._meta.collection_rev(SHARDMAP_COLLECTION)
            self._checked_at = now
            return doc

    def layout(self) -> ShardLayout:
        doc = self.document()
        layout = getattr(self, "_layout", None)
        if (
            layout is None
            or layout.stripe_rows != doc["stripe_rows"]
        ):
            layout = ShardLayout(doc["shards"], doc["stripe_rows"])
            self._layout = layout
        return layout
