"""Columnar tables: the bridge between the document store and the TPU.

The reference moves data between Mongo and compute row-at-a-time (one RPC
per document: reference microservices/model_builder_image/
model_builder.py:237-247, data_type_handler_image/data_type_handler.py:
47-77). Here a dataset is materialised once into a :class:`ColumnTable`
— a dict of equal-length columns — and all ops/estimators consume columns
(numpy host-side, ``jax.Array`` on device). Strings are dictionary-encoded
(:meth:`ColumnTable.encoded`) before any device transfer, because TPUs
compute on numbers.
"""

from __future__ import annotations

from typing import Iterable, Optional, Sequence

import numpy as np

from learningorchestra_tpu.core.columns import Column
from learningorchestra_tpu.core.store import METADATA_ID, ROW_ID, DocumentStore

NUMBER = "number"
STRING = "string"


def _is_number(value) -> bool:
    return isinstance(value, (int, float, np.integer, np.floating)) and not isinstance(
        value, bool
    )


def column_type(values: Iterable) -> str:
    """A column is numeric iff every non-null value is a number."""
    saw_number = False
    for value in values:
        if value is None:
            continue
        if _is_number(value):
            if isinstance(value, float) and np.isnan(value):
                continue
            saw_number = True
            continue
        return STRING
    return NUMBER if saw_number else STRING


def as_column(values: Sequence) -> np.ndarray:
    """Materialise raw values as float64 (None→NaN) or object array."""
    if column_type(values) == NUMBER:
        return np.array(
            [np.nan if value is None else float(value) for value in values],
            dtype=np.float64,
        )
    return np.array(values, dtype=object)


class ColumnTable:
    """An ordered dict of equal-length columns.

    Numeric columns are ``float64`` numpy arrays with NaN for missing;
    string columns are object arrays with ``None`` for missing.
    """

    def __init__(self, columns: dict[str, np.ndarray]):
        lengths = {len(col) for col in columns.values()}
        if len(lengths) > 1:
            raise ValueError(f"ragged columns: { {k: len(v) for k, v in columns.items()} }")
        self.columns = dict(columns)
        self.num_rows = lengths.pop() if lengths else 0

    # --- constructors ---------------------------------------------------------
    @classmethod
    def from_lists(cls, raw: dict[str, Sequence]) -> "ColumnTable":
        return cls({name: as_column(values) for name, values in raw.items()})

    @classmethod
    def from_csv(cls, path: str) -> "ColumnTable":
        """Direct CSV → columnar table (bypassing the document store):
        the host-side loader feeding device transfer (SURVEY.md §2's
        connector replacement). Uses the native C++ parser when built
        (native/loader.py), Python otherwise."""
        from learningorchestra_tpu.native.loader import read_csv_columns

        return cls(read_csv_columns(path))

    @classmethod
    def from_store(
        cls,
        store: DocumentStore,
        collection: str,
        fields: Optional[list[str]] = None,
    ) -> "ColumnTable":
        """Bulk columnar read of a dataset (excludes the metadata row).

        Rides the typed-column plane (``read_column_arrays``): numeric
        kinds hand their float64 buffers over directly — zero per-cell
        conversion between storage and the design matrix."""
        arrays = store.read_column_arrays(collection, fields)
        columns: dict[str, np.ndarray] = {}
        for name, column in arrays.items():
            if column.kind in ("f8", "i8", "num"):
                columns[name] = column.to_float64()
            else:
                # str/obj/bool/empty keep object semantics (bools and
                # all-null columns are STRING-typed here, matching
                # column_type's contract)
                columns[name] = column.to_object()
        return cls(columns)

    # --- basic relational verbs -----------------------------------------------
    @property
    def field_names(self) -> list[str]:
        return list(self.columns.keys())

    def dtype_of(self, field: str) -> str:
        column = self.columns[field]
        return NUMBER if column.dtype == np.float64 else STRING

    def string_fields(self) -> list[str]:
        return [f for f in self.field_names if self.dtype_of(f) == STRING]

    def number_fields(self) -> list[str]:
        return [f for f in self.field_names if self.dtype_of(f) == NUMBER]

    def select(self, fields: list[str]) -> "ColumnTable":
        return ColumnTable({field: self.columns[field] for field in fields})

    def take(self, mask_or_index: np.ndarray) -> "ColumnTable":
        return ColumnTable(
            {name: col[mask_or_index] for name, col in self.columns.items()}
        )

    def dropna(self) -> "ColumnTable":
        keep = np.ones(self.num_rows, dtype=bool)
        for column in self.columns.values():
            if column.dtype == np.float64:
                keep &= ~np.isnan(column)
            else:
                keep &= np.array([v is not None for v in column], dtype=bool)
        return self.take(keep)

    # --- device-bound transforms ----------------------------------------------
    def encoded(self) -> tuple["ColumnTable", dict[str, list]]:
        """Dictionary-encode string columns to ordinal float codes.

        Equivalent of the per-column sklearn ``LabelEncoder`` loop the
        reference runs before PCA/t-SNE (reference:
        microservices/pca_image/pca.py:79-85): codes are assigned in
        sorted-value order. Returns the numeric table and the per-field
        vocabularies.
        """
        out: dict[str, np.ndarray] = {}
        vocabularies: dict[str, list] = {}
        for name, column in self.columns.items():
            if column.dtype == np.float64:
                out[name] = column
                continue
            present = [v for v in column if v is not None]
            vocabulary = sorted(set(present), key=str)
            index = {value: code for code, value in enumerate(vocabulary)}
            out[name] = np.array(
                [np.nan if v is None else float(index[v]) for v in column],
                dtype=np.float64,
            )
            vocabularies[name] = vocabulary
        return ColumnTable(out), vocabularies

    def matrix(self, fields: Optional[list[str]] = None) -> np.ndarray:
        """Stack numeric columns into an ``(num_rows, n_fields)`` float64
        design matrix (row-major for device transfer)."""
        fields = fields or self.field_names
        bad = [f for f in fields if self.dtype_of(f) != NUMBER]
        if bad:
            raise TypeError(f"non-numeric fields in matrix(): {bad}")
        if not fields:
            return np.zeros((self.num_rows, 0), dtype=np.float64)
        return np.stack([self.columns[f] for f in fields], axis=1)

    # --- store round-trip -----------------------------------------------------
    def store_columns(self) -> dict[str, Column]:
        """Columns as typed :class:`Column` carriers (float64 NaN →
        null mask) — the zero-conversion shape ``insert_column_arrays``
        takes."""
        out: dict[str, Column] = {}
        for name, column in self.columns.items():
            if column.dtype == np.float64:
                out[name] = Column.from_numpy(column)
            else:
                out[name] = Column.from_values(column.tolist())
        return out

    def value_columns(self) -> dict[str, list]:
        """Columns as plain Python lists with the store's missing-value
        convention (numeric NaN → ``None``) — the shape
        ``DocumentStore.insert_columns`` takes."""
        out: dict[str, list] = {}
        for name, column in self.columns.items():
            if column.dtype == np.float64:
                out[name] = [
                    None if np.isnan(value) else float(value) for value in column
                ]
            else:
                out[name] = column.tolist()
        return out

    def documents(self, start_id: int = 1) -> list[dict]:
        """Row-major view as store documents with ``_id`` ``start_id..``."""
        names = self.field_names
        columns = [self.columns[name] for name in names]
        out = []
        for i in range(self.num_rows):
            document = {}
            for name, column in zip(names, columns):
                value = column[i]
                if column.dtype == np.float64:
                    value = None if np.isnan(value) else float(value)
                document[name] = value
            document[ROW_ID] = start_id + i
            out.append(document)
        return out


BATCH_SIZE = 4096
# Typed columns batch far wider: the per-batch cost is one buffer slice
# + one WAL record, not per-value JSON.
ARRAY_BATCH_SIZE = 1 << 20


def _write_initial_metadata(store: DocumentStore, collection: str, meta: dict) -> None:
    initial = dict(meta)
    initial["finished"] = False
    store.insert_one(collection, initial)


def num_column_rows(columns: dict) -> int:
    return len(next(iter(columns.values()))) if columns else 0


def insert_columns_batched(
    store: DocumentStore,
    collection: str,
    columns: dict,
    start_id: int = 1,
    batch_size: Optional[int] = None,
) -> int:
    """Append ``columns`` as rows ``start_id..`` in ``batch_size`` slices
    (bounds per-call WAL record / wire message sizes). Returns the row
    count. The one batching loop every columnar writer shares — values
    may be plain lists or typed :class:`Column` carriers (which slice
    by buffer and batch ~256× wider)."""
    num_rows = num_column_rows(columns)
    typed = any(isinstance(values, Column) for values in columns.values())
    if batch_size is None:
        batch_size = ARRAY_BATCH_SIZE if typed else BATCH_SIZE

    def part(values, start: int, stop: int):
        if isinstance(values, Column):
            return values.slice(start, stop)
        return values[start:stop]

    for start in range(0, num_rows, batch_size):
        stop = min(start + batch_size, num_rows)
        store.insert_columns(
            collection,
            {name: part(values, start, stop) for name, values in columns.items()},
            start_id=start_id + start,
        )
    return num_rows


def write_documents(
    store: DocumentStore,
    collection: str,
    documents: list[dict],
    metadata: dict,
    batch_size: int = BATCH_SIZE,
) -> None:
    """Write row documents plus an ``_id: 0`` metadata document.

    The ``finished``-flag wire contract: the metadata document is
    inserted with ``finished: false`` first, rows land in ``insert_many``
    batches, and the caller's final metadata (including ``finished:
    true`` if requested) is applied only after the last row — so a
    concurrent poller never observes a "finished" dataset with partial
    rows.
    """
    meta = dict(metadata)
    meta[ROW_ID] = METADATA_ID
    _write_initial_metadata(store, collection, meta)
    for start in range(0, len(documents), batch_size):
        store.insert_many(collection, documents[start : start + batch_size])
    store.update_one(collection, {ROW_ID: METADATA_ID}, meta)


def write_columns(
    store: DocumentStore,
    collection: str,
    columns: dict,
    metadata: dict,
    ids: Optional[Sequence] = None,
    batch_size: Optional[int] = None,
) -> None:
    """Write a dataset column-major under the same ``finished`` contract
    as :func:`write_documents` — the fast path: the store keeps the body
    as a columnar block, no per-row dicts anywhere. ``columns`` values
    may be lists or typed :class:`Column` carriers.

    ``ids`` (when given) must be the contiguous ``1..N`` range a block
    requires; non-contiguous ids take the row-document fallback.
    """
    num_rows = num_column_rows(columns)
    meta = dict(metadata)
    meta[ROW_ID] = METADATA_ID

    contiguous_start = 1
    if ids is not None:
        first = int(ids[0]) if num_rows else 1
        contiguous = True
        if isinstance(ids, np.ndarray) and np.issubdtype(ids.dtype, np.number):
            contiguous = bool(
                np.array_equal(ids, np.arange(first, first + num_rows))
            )
        else:
            contiguous = all(
                int(ids[i]) == first + i for i in range(num_rows)
            )
        if not contiguous:
            value_lists = {
                name: (
                    values.tolist() if isinstance(values, Column) else values
                )
                for name, values in columns.items()
            }
            documents = []
            for i in range(num_rows):
                document = {
                    name: values[i] for name, values in value_lists.items()
                }
                doc_id = ids[i]
                document[ROW_ID] = (
                    doc_id.item() if isinstance(doc_id, np.generic) else doc_id
                )
                documents.append(document)
            write_documents(
                store, collection, documents, metadata, batch_size or BATCH_SIZE
            )
            return
        contiguous_start = first

    _write_initial_metadata(store, collection, meta)
    insert_columns_batched(store, collection, columns, contiguous_start, batch_size)
    store.update_one(collection, {ROW_ID: METADATA_ID}, meta)


def write_table(
    store: DocumentStore,
    collection: str,
    table: ColumnTable,
    metadata: dict,
    batch_size: Optional[int] = None,
) -> None:
    """Write a :class:`ColumnTable` to the store under the ``finished``
    contract, column-major over the typed plane (see
    :func:`write_columns`)."""
    write_columns(
        store, collection, table.store_columns(), metadata, batch_size=batch_size
    )
