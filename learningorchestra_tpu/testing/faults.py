"""Fault injection: named fault points for chaos-testing the data plane.

The replicated store's failover claims (docs/replication.md) are only
worth what can be demonstrated under faults, so the fault points are
first-class and live in the production code paths they test: the store
wire, the WAL feed, the promotion path, and the server-to-server
network calls (peer probes, WAL polls, quorum votes). Each point is a
named call to :func:`fire` (or :func:`torn` for sites that corrupt
bytes themselves); with no faults installed a point costs one list
read — nothing on the data-plane scale.

Two ways to arm a fault:

- **Environment knobs** (``LO_FAULT_<POINT>``, dots as underscores,
  upper-cased — e.g. ``LO_FAULT_STORE_WIRE_MUTATE="kill:5"``): for
  subprocess chaos, where the faulted process is a real store server
  that must actually die mid write burst. Validated by
  ``deploy/run.sh``'s preflight via :func:`validate_env` so a typo'd
  point or spec fails bring-up instead of silently not firing.
- **Programmatic installs** (:func:`install`): for in-process tests,
  where a ``where={...}`` match narrows the fault to one side of a
  simulated partition (ctx keys like ``me``/``url`` must all match).

Spec grammar — ``ACTION[:ARG][@N]``:

- ``kill[:nth]``      ``os._exit(137)`` on the *nth* hit (default 1) —
  the kill-primary-mid-write-burst fault.
- ``delay:seconds[@n]``  sleep before proceeding, on the first *n* hits
  (default: every hit) — delayed WAL shipping.
- ``error[@n]``       raise :class:`FaultInjected` on the first *n*
  hits (default: every hit) — partitions and transient wire failures.
- ``torn[@n]``        site-owned corruption (a truncated wire frame) on
  the first *n* hits (default 1); :func:`fire` never raises for it —
  the instrumented site asks :func:`torn` and mangles its own bytes.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

# Every known fault point, with where it is threaded. validate_env and
# install() reject anything else — a chaos run that names a point that
# no longer exists must fail loudly, not silently test nothing.
FAULT_POINTS = {
    "store.wire.mutate": (
        "store server, before a mutation handler applies (a kill here "
        "loses an unacknowledged, unapplied write)"
    ),
    "store.wire.mutate.applied": (
        "store server, after a mutation applied but before it is "
        "acknowledged (a kill here loses the ack, not the write — the "
        "landed-ok retry path)"
    ),
    "store.wire.read_chunk": (
        "store server, binary read chunk about to be returned "
        "(supports torn: the frame is truncated mid-buffer)"
    ),
    "store.wal.feed": "store server, GET /wal handler (WAL shipping)",
    "store.promote": "inside promote_role, before the role flips",
    "store.net": (
        "server-to-server call: peer health probe, follower WAL poll, "
        "quorum vote request (ctx: me, url, kind)"
    ),
    # Compute-plane points (the crash-resume chaos harness,
    # docs/robustness.md): where a kill proves segment checkpointing
    # and resume-aware recovery, and an error proves the partial-results
    # and per-member delivery contracts.
    "builder.phase": (
        "model builder, at a phase boundary of one classifier's "
        "train (ctx: phase=load_data|preprocess|fit|checkpoint|"
        "evaluate|write, classificator when per-classifier — a kill "
        "here orphans the build mid-flight; an error fails one member)"
    ),
    "sched.journal.append": (
        "job journal, before a lifecycle/progress document is inserted "
        "(ctx: job, event — journal writes are best-effort, so an "
        "error here loses an audit line, never the job)"
    ),
    "coalesce.dispatch": (
        "job coalescer, before a fused batch dispatches (ctx: jobs — "
        "an error here must become per-member failures, not a wedge)"
    ),
    "serve.forward": (
        "serving batcher, before a request group's forward pass "
        "(ctx: path, requests — an error here must become per-request "
        "errors, not a dropped group)"
    ),
    "serve.route": (
        "fleet router, after admission but before the predict proxies "
        "upstream (ctx: model — an error here must answer a clean JSON "
        "503, never take the router down; a delay holds the routing "
        "decision open while a replica dies, the in-flight-failover "
        "chaos drill)"
    ),
}

_ACTIONS = ("kill", "delay", "error", "torn")


class FaultInjected(ConnectionError):
    """An ``error`` fault fired. Subclasses :class:`ConnectionError` so
    server-to-server callers (peer probes, WAL polls) treat an injected
    partition exactly like a real unreachable peer."""


class _Fault:
    __slots__ = ("point", "action", "arg", "count", "where", "hits")

    def __init__(self, point, action, arg, count, where):
        self.point = point
        self.action = action
        self.arg = arg  # delay seconds, or kill's nth hit
        self.count = count  # first-N budget (None = unlimited)
        self.where = where or {}
        self.hits = 0

    def matches(self, ctx: dict) -> bool:
        return all(ctx.get(key) == value for key, value in self.where.items())


_LOCK = threading.Lock()
_FAULTS: list[_Fault] = []
_ENV_LOADED = False


def _point_env_name(point: str) -> str:
    return "LO_FAULT_" + point.upper().replace(".", "_")


_ENV_NAMES = {_point_env_name(point): point for point in FAULT_POINTS}


def parse_spec(point: str, spec: str) -> _Fault:
    """One ``ACTION[:ARG][@N]`` spec → a :class:`_Fault`; raises
    ``ValueError`` with an actionable message on anything malformed."""
    if point not in FAULT_POINTS:
        raise ValueError(
            f"unknown fault point {point!r} (have: "
            f"{', '.join(sorted(FAULT_POINTS))})"
        )
    text = spec.strip()
    count: Optional[int] = None
    if "@" in text:
        text, _, count_text = text.partition("@")
        try:
            count = int(count_text)
        except ValueError:
            count = -1
        if count < 1:
            raise ValueError(
                f"{point}: '@{count_text}' must be a positive hit count"
            )
    action, _, arg_text = text.partition(":")
    action = action.strip()
    if action not in _ACTIONS:
        raise ValueError(
            f"{point}: unknown action {action!r} "
            f"(have: {', '.join(_ACTIONS)})"
        )
    arg: Optional[float] = None
    if action == "kill":
        arg = 1.0
        if arg_text:
            try:
                arg = float(int(arg_text))
            except ValueError:
                arg = 0.0
            if arg < 1:
                raise ValueError(f"{point}: kill:<nth> must be >= 1")
        if count is not None:
            raise ValueError(f"{point}: kill takes ':nth', not '@n'")
    elif action == "delay":
        try:
            arg = float(arg_text)
        except ValueError:
            arg = -1.0
        if arg <= 0:
            raise ValueError(f"{point}: delay needs ':<seconds>' > 0")
    elif arg_text:
        raise ValueError(f"{point}: {action} takes no ':' argument")
    if action == "torn" and count is None:
        count = 1  # a torn stream that never heals would defeat retries
    return _Fault(point, action, arg, count, None)


def validate_env(environ=None) -> dict[str, str]:
    """Parse every ``LO_FAULT_*`` variable, raising ``ValueError`` on an
    unknown point or malformed spec; returns ``{point: spec}``. The
    deploy preflight calls this so a chaos knob typo fails bring-up."""
    environ = os.environ if environ is None else environ
    out: dict[str, str] = {}
    problems: list[str] = []
    for name, value in sorted(environ.items()):
        if not name.startswith("LO_FAULT_") or not value.strip():
            continue
        point = _ENV_NAMES.get(name)
        if point is None:
            problems.append(
                f"{name}: no such fault point (have: "
                + ", ".join(sorted(_ENV_NAMES))
                + ")"
            )
            continue
        try:
            parse_spec(point, value)
        except ValueError as error:
            problems.append(f"{name}: {error}")
            continue
        out[point] = value.strip()
    if problems:
        raise ValueError("; ".join(problems))
    return out


def _ensure_env_loaded() -> None:
    global _ENV_LOADED
    if _ENV_LOADED:
        return
    with _LOCK:
        if _ENV_LOADED:
            return
        try:
            armed = validate_env()
        except ValueError as error:
            # fire() runs inside production request handlers: raising
            # here would turn a typo'd knob into an error on EVERY
            # mutation (and spurious failovers from failing WAL polls).
            # Process ENTRY points (store_service/arbiter/stack main,
            # run.sh preflight) call validate_env() and refuse to come
            # up; a library embedder just gets one loud warning and no
            # armed faults.
            import sys

            print(
                f"faults: ignoring invalid LO_FAULT_* config: {error}",
                file=sys.stderr,
                flush=True,
            )
            armed = {}
        for point, spec in armed.items():
            _FAULTS.append(parse_spec(point, spec))
        _ENV_LOADED = True


def install(point: str, spec: str, where: Optional[dict] = None) -> _Fault:
    """Arm a fault programmatically (tests). ``where`` narrows it to
    fire() calls whose ctx carries equal values for every given key —
    how an in-process test partitions ONE node's server-to-server
    traffic while the others keep talking."""
    fault = parse_spec(point, spec)
    fault.where = dict(where or {})
    with _LOCK:
        _FAULTS.append(fault)
    return fault


def reset() -> None:
    """Disarm everything (programmatic installs AND env-derived faults;
    the env is re-read on the next fire). Test fixtures call this."""
    global _ENV_LOADED
    with _LOCK:
        _FAULTS.clear()
        _ENV_LOADED = False


def _consume(fault: _Fault) -> int:
    with _LOCK:
        fault.hits += 1
        return fault.hits


def fire(point: str, **ctx) -> None:
    """Hit a fault point. No-op unless a matching fault is armed; then
    kills the process, sleeps, or raises :class:`FaultInjected`
    according to the armed spec. ``torn`` faults never act here — the
    site corrupts its own bytes via :func:`torn`."""
    _ensure_env_loaded()
    if not _FAULTS:
        return
    for fault in list(_FAULTS):
        if fault.point != point or not fault.matches(ctx):
            continue
        if fault.action == "torn":
            continue
        hit = _consume(fault)
        if fault.action == "kill":
            if hit == int(fault.arg):
                os._exit(137)
        elif fault.count is not None and hit > fault.count:
            continue
        elif fault.action == "delay":
            time.sleep(fault.arg)
        elif fault.action == "error":
            raise FaultInjected(f"injected fault at {point}")


def torn(point: str, **ctx) -> bool:
    """True when a ``torn`` fault is armed at ``point`` with budget
    left — the instrumented site then corrupts its own output (e.g.
    truncates the wire frame). Consumes one hit of the budget."""
    _ensure_env_loaded()
    for fault in list(_FAULTS):
        if (
            fault.point == point
            and fault.action == "torn"
            and fault.matches(ctx)
        ):
            if _consume(fault) <= (fault.count or 1):
                return True
    return False
