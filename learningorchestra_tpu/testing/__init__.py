"""Test-and-chaos machinery that ships WITH the framework.

The reference proves nothing about its failover story — the Mongo
replica set is assumed to work (docker-compose.yml:27-91). This package
is the machinery that lets US prove ours: named fault points threaded
through the store wire, WAL feed, and promotion path
(:mod:`learningorchestra_tpu.testing.faults`), driven either by
``LO_FAULT_*`` environment knobs (subprocess chaos — kill a primary mid
write burst) or programmatic installs (in-process partition tests).
Production code imports :mod:`faults` unconditionally; with nothing
installed every fault point is a dict lookup that misses — no
measurable cost on the data plane.
"""

from learningorchestra_tpu.testing.faults import (  # noqa: F401
    FAULT_POINTS,
    FaultInjected,
    fire,
    install,
    reset,
    torn,
    validate_env,
)
