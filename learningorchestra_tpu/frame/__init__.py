"""Host-side columnar dataframe with the PySpark surface the reference
exposes to user preprocessing code.

The reference ``exec()``s user-supplied PySpark against ``training_df`` /
``testing_df`` (reference: microservices/model_builder_image/
model_builder.py:144-149) and documents exactly which verbs that code may
use (reference: docs/model_builder.md "preprocessor_code example"):
withColumn / withColumnRenamed / replace / na.fill / drop / randomSplit,
the functions ``col, lit, when, regexp_extract, split, mean``, and the
feature stages ``StringIndexer`` / ``VectorAssembler`` (plus ``Pipeline``).
That documented surface is the compatibility contract — full PySpark
emulation is explicitly out of scope.

Design: eager numpy columns (numeric → float64 with NaN, strings →
object with None, assembled vectors → 2-D float64), expression trees
evaluated per-frame. Preprocessing is host work; the device path starts
when the assembled ``features`` matrix reaches an estimator.
"""

from learningorchestra_tpu.frame.dataframe import DataFrame
from learningorchestra_tpu.frame.expressions import (
    col,
    lit,
    mean,
    regexp_extract,
    split,
    when,
)
from learningorchestra_tpu.frame.feature import (
    Pipeline,
    StringIndexer,
    VectorAssembler,
)

__all__ = [
    "DataFrame",
    "col",
    "lit",
    "mean",
    "regexp_extract",
    "split",
    "when",
    "Pipeline",
    "StringIndexer",
    "VectorAssembler",
]
