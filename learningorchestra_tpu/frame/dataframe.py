"""Eager columnar DataFrame with the PySpark verb surface.

Columns: float64 numpy arrays (NaN = null), object arrays (None = null),
or 2-D float64 matrices (assembled feature vectors). Immutable —
every verb returns a new frame sharing unchanged column arrays.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from learningorchestra_tpu.core.table import ColumnTable
from learningorchestra_tpu.frame.expressions import (
    Expression,
    _is_null_array,
)


class Row(dict):
    """``first()`` result: dict with attribute access, like Spark's Row."""

    def __getattr__(self, name):
        try:
            return self[name]
        except KeyError as error:
            raise AttributeError(name) from error


class Schema:
    def __init__(self, names: list[str]):
        self.names = names


class NaFunctions:
    """The ``df.na`` namespace (fill only — the documented surface)."""

    def __init__(self, df: "DataFrame"):
        self._df = df

    def fill(self, value, subset: Optional[list[str]] = None) -> "DataFrame":
        if isinstance(value, dict):
            replacements = value
        else:
            names = subset if subset is not None else self._df.columns
            replacements = {name: value for name in names}
        columns = dict(self._df._columns)
        for name, fill_value in replacements.items():
            if name not in columns:
                continue
            column = columns[name]
            if column.ndim != 1:
                continue
            nulls = _is_null_array(column)
            if not nulls.any():
                continue
            # Spark only fills when the value type matches the column
            # type: string fills touch string columns, numeric fills
            # touch numeric columns; mismatches are skipped silently.
            fill_is_string = isinstance(fill_value, str)
            if fill_is_string != (column.dtype == object):
                continue
            patched = column.copy()
            patched[nulls] = fill_value if fill_is_string else float(fill_value)
            columns[name] = patched
        return DataFrame(columns)


class DataFrame:
    def __init__(self, columns: dict[str, np.ndarray]):
        lengths = {col.shape[0] for col in columns.values()}
        if len(lengths) > 1:
            raise ValueError(
                f"ragged columns: { {k: v.shape for k, v in columns.items()} }"
            )
        self._columns = dict(columns)
        self._num_rows = lengths.pop() if lengths else 0

    # --- constructors -------------------------------------------------------
    @classmethod
    def from_table(cls, table: ColumnTable) -> "DataFrame":
        return cls(dict(table.columns))

    def to_table(self) -> ColumnTable:
        return ColumnTable(
            {name: col for name, col in self._columns.items() if col.ndim == 1}
        )

    # --- introspection ------------------------------------------------------
    @property
    def columns(self) -> list[str]:
        return list(self._columns.keys())

    @property
    def schema(self) -> Schema:
        return Schema(self.columns)

    def count(self) -> int:
        return self._num_rows

    def first(self) -> Optional[Row]:
        if self._num_rows == 0:
            return None
        row = {}
        for name, column in self._columns.items():
            value = column[0]
            if column.ndim > 1:
                value = np.asarray(value)
            elif column.dtype != object:
                value = None if np.isnan(value) else float(value)
            row[name] = value
        return Row(row)

    def _column(self, name: str) -> np.ndarray:
        if name not in self._columns:
            raise KeyError(f"no such column: {name!r}")
        return self._columns[name]

    def __getitem__(self, name: str):
        from learningorchestra_tpu.frame.expressions import col

        self._column(name)  # existence check, Spark raises here too
        return col(name)

    # --- verbs --------------------------------------------------------------
    def _materialize(self, value) -> np.ndarray:
        if isinstance(value, Expression):
            result = value.evaluate(self)
        else:
            result = value
        result = np.asarray(result)
        if result.ndim == 0:
            result = np.full(self._num_rows, result.item())
        if result.dtype == bool:
            result = result.astype(np.float64)
        elif result.dtype != object and result.dtype != np.float64 and result.ndim == 1:
            result = result.astype(np.float64)
        return result

    def withColumn(self, name: str, value) -> "DataFrame":
        columns = dict(self._columns)
        columns[name] = self._materialize(value)
        return DataFrame(columns)

    def withColumnRenamed(self, existing: str, new: str) -> "DataFrame":
        columns = {}
        for name, column in self._columns.items():
            columns[new if name == existing else name] = column
        return DataFrame(columns)

    def drop(self, *names: str) -> "DataFrame":
        return DataFrame(
            {n: c for n, c in self._columns.items() if n not in names}
        )

    def select(self, *names) -> "DataFrame":
        flat: list[str] = []
        for name in names:
            if isinstance(name, (list, tuple)):
                flat.extend(name)
            else:
                flat.append(name)
        return DataFrame({name: self._column(name) for name in flat})

    def filter(self, condition: Expression) -> "DataFrame":
        mask = np.asarray(condition.evaluate(self), dtype=bool)
        return self._take(mask)

    where = filter

    def _take(self, mask_or_index: np.ndarray) -> "DataFrame":
        return DataFrame(
            {name: column[mask_or_index] for name, column in self._columns.items()}
        )

    def dropna(self, subset: Optional[list[str]] = None) -> "DataFrame":
        names = subset if subset is not None else self.columns
        keep = np.ones(self._num_rows, dtype=bool)
        for name in names:
            column = self._columns[name]
            if column.ndim == 1:
                keep &= ~_is_null_array(column)
            else:
                keep &= ~np.isnan(column).any(axis=1)
        return self._take(keep)

    def replace(self, to_replace, value=None, subset=None) -> "DataFrame":
        """``df.replace(list, list)`` — value substitution in string
        columns (the documented example replaces misspelled titles,
        docs/model_builder.md)."""
        if isinstance(to_replace, dict):
            mapping = to_replace
        else:
            if not isinstance(to_replace, (list, tuple)):
                to_replace = [to_replace]
            if not isinstance(value, (list, tuple)):
                value = [value] * len(to_replace)
            mapping = dict(zip(to_replace, value))
        names = subset if subset is not None else self.columns
        columns = dict(self._columns)
        for name in names:
            column = columns[name]
            if column.ndim != 1 or column.dtype != object:
                continue
            columns[name] = np.array(
                [mapping.get(v, v) for v in column], dtype=object
            )
        return DataFrame(columns)

    @property
    def na(self) -> NaFunctions:
        return NaFunctions(self)

    def randomSplit(
        self, weights: Sequence[float], seed: Optional[int] = None
    ) -> list["DataFrame"]:
        """Per-row uniform draw bucketed by cumulative weights (Spark's
        randomSplit semantics — split sizes are stochastic)."""
        weights = np.asarray(weights, dtype=np.float64)
        cumulative = np.cumsum(weights / weights.sum())
        draws = np.random.default_rng(seed).uniform(size=self._num_rows)
        buckets = np.searchsorted(cumulative, draws, side="right")
        return [self._take(buckets == i) for i in range(len(weights))]

    # --- estimator bridge ---------------------------------------------------
    def feature_matrix(self, features_col: str = "features") -> np.ndarray:
        matrix = self._column(features_col)
        if matrix.ndim != 2:
            raise TypeError(
                f"column {features_col!r} is not an assembled vector column"
            )
        return matrix

    def label_vector(self, label_col: str = "label") -> np.ndarray:
        labels = self._column(label_col).astype(np.float64)
        if np.isnan(labels).any():
            raise ValueError(
                f"null labels in column {label_col!r}; drop or impute "
                "them in preprocessor_code before fitting"
            )
        return labels.astype(np.int32)

    def device_matrix(self, features_col: str, mesh=None):
        """The assembled feature matrix padded + row-sharded on the
        mesh, cached twice: on the frame (when N classifiers predict
        over the same test/eval frame, the host→device transfer happens
        ONCE, not per model — the reference re-reads its dataframes per
        evaluator instead, model_builder.py:205-224) and in the
        process-wide device cache, content-addressed (core/devcache.py)
        — so the SAME bytes across requests (a rebuilt frame from an
        unchanged collection + preprocessor) reuse one device copy
        instead of paying H2D per request."""
        import threading

        from learningorchestra_tpu.core.devcache import content_device_matrix
        from learningorchestra_tpu.ml.base import resolve_mesh

        mesh = resolve_mesh(mesh)
        cache = self.__dict__.setdefault("_device_matrices", {})
        lock = self.__dict__.setdefault("_device_lock", threading.Lock())
        key = (features_col, id(mesh))
        with lock:
            cached = cache.get(key)
            if cached is None:
                cached = content_device_matrix(
                    self.feature_matrix(features_col), mesh
                )
                cache[key] = cached
        return cached

    def device_labels(self, label_col: str, mesh=None):
        """The label vector padded + row-sharded on the mesh, cached on
        the frame and content-addressed in the process-wide device
        cache (see :meth:`device_matrix`)."""
        import threading

        from learningorchestra_tpu.core.devcache import content_device_labels
        from learningorchestra_tpu.ml.base import resolve_mesh

        mesh = resolve_mesh(mesh)
        cache = self.__dict__.setdefault("_device_matrices", {})
        lock = self.__dict__.setdefault("_device_lock", threading.Lock())
        key = ("labels:" + label_col, id(mesh))
        with lock:
            cached = cache.get(key)
            if cached is None:
                cached = content_device_labels(
                    self.label_vector(label_col), mesh
                )
                cache[key] = cached
        return cached
