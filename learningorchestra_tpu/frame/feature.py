"""Feature stages: StringIndexer, VectorAssembler, Pipeline.

The `pyspark.ml.feature` subset the documented preprocessor example uses
(reference docs/model_builder.md): per-column label indexing and dense
feature assembly feeding the classifiers.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from learningorchestra_tpu.frame.dataframe import DataFrame
from learningorchestra_tpu.frame.expressions import _is_null_array

ERROR = "error"
SKIP = "skip"
KEEP = "keep"


class StringIndexerModel:
    def __init__(self, input_col: str, output_col: str, labels: list, handle_invalid: str):
        self.inputCol = input_col
        self.outputCol = output_col
        self.labels = labels
        self._index = {label: float(code) for code, label in enumerate(labels)}
        self.handle_invalid = handle_invalid

    def transform(self, df: DataFrame) -> DataFrame:
        column = df._column(self.inputCol)
        codes = np.empty(len(column), dtype=np.float64)
        keep = np.ones(len(column), dtype=bool)
        for i, value in enumerate(column):
            code = self._index.get(value)
            if code is None:
                if self.handle_invalid == ERROR:
                    raise ValueError(
                        f"StringIndexer: unseen or null label {value!r} in "
                        f"column {self.inputCol!r}"
                    )
                if self.handle_invalid == SKIP:
                    keep[i] = False
                    code = np.nan
                else:  # keep: unseen bucket = num labels
                    code = float(len(self.labels))
            codes[i] = code
        out = df.withColumn(self.outputCol, codes)
        if self.handle_invalid == SKIP:
            return out._take(keep)
        return out


class StringIndexer:
    """Orders labels by descending frequency, ties broken
    lexicographically — Spark's default ``frequencyDesc`` order, so
    indexed features match the reference's encoding."""

    def __init__(
        self,
        inputCol: Optional[str] = None,
        outputCol: Optional[str] = None,
        handleInvalid: str = ERROR,
    ):
        self.inputCol = inputCol
        self.outputCol = outputCol or (f"{inputCol}_index" if inputCol else None)
        self.handleInvalid = handleInvalid

    def setHandleInvalid(self, value: str) -> "StringIndexer":
        self.handleInvalid = value
        return self

    def fit(self, df: DataFrame) -> StringIndexerModel:
        column = df._column(self.inputCol)
        nulls = _is_null_array(column)
        counts: dict = {}
        for value, is_null in zip(column, nulls):
            if is_null:
                continue
            counts[value] = counts.get(value, 0) + 1
        labels = sorted(counts, key=lambda v: (-counts[v], str(v)))
        return StringIndexerModel(
            self.inputCol, self.outputCol, labels, self.handleInvalid
        )


class VectorAssembler:
    """Stacks numeric columns into one 2-D ``outputCol`` matrix — the
    bridge from the host dataframe to the device design matrix."""

    def __init__(
        self,
        inputCols: Optional[list[str]] = None,
        outputCol: str = "features",
        handleInvalid: str = ERROR,
    ):
        self.inputCols = list(inputCols or [])
        self.outputCol = outputCol
        self.handleInvalid = handleInvalid

    def setHandleInvalid(self, value: str) -> "VectorAssembler":
        if value not in (ERROR, SKIP, KEEP):
            raise ValueError(f"invalid handleInvalid {value!r}")
        self.handleInvalid = value
        return self

    def transform(self, df: DataFrame) -> DataFrame:
        stacked = []
        for name in self.inputCols:
            column = df._column(name)
            if column.ndim == 2:
                stacked.append(column)
                continue
            if column.dtype == object:
                nulls = _is_null_array(column)
                numeric = np.array(
                    [np.nan if null else float(v) for v, null in zip(column, nulls)],
                    dtype=np.float64,
                )
            else:
                numeric = column.astype(np.float64)
            stacked.append(numeric[:, None])
        matrix = (
            np.concatenate(stacked, axis=1)
            if stacked
            else np.zeros((df.count(), 0))
        )
        invalid = np.isnan(matrix).any(axis=1)
        if invalid.any():
            if self.handleInvalid == ERROR:
                raise ValueError(
                    "VectorAssembler: null/NaN in input columns "
                    "(handleInvalid='error')"
                )
            if self.handleInvalid == SKIP:
                keep = ~invalid
                return df._take(keep).withColumn(self.outputCol, matrix[keep])
        return df.withColumn(self.outputCol, matrix)


class Pipeline:
    """Minimal stage chainer (fit/transform protocol)."""

    def __init__(self, stages: Optional[list] = None):
        self.stages = list(stages or [])

    def fit(self, df: DataFrame) -> "PipelineModel":
        fitted = []
        current = df
        for stage in self.stages:
            if hasattr(stage, "fit"):
                model = stage.fit(current)
                current = model.transform(current)
                fitted.append(model)
            else:
                current = stage.transform(current)
                fitted.append(stage)
        return PipelineModel(fitted)


class PipelineModel:
    def __init__(self, stages: list):
        self.stages = stages

    def transform(self, df: DataFrame) -> DataFrame:
        for stage in self.stages:
            df = stage.transform(df)
        return df
