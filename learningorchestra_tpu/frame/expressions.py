"""Column expression trees (`pyspark.sql.functions` compatibility).

Expressions are unresolved (name-based, like Spark's ``col``): they bind
to a concrete :class:`~learningorchestra_tpu.frame.dataframe.DataFrame`
only at ``evaluate`` time. Null semantics follow the column conventions:
NaN in float columns, ``None`` in object columns; comparisons involving
null are False (Spark's null predicate folding under ``when``).
"""

from __future__ import annotations

import re
from typing import Any, Callable, Optional

import numpy as np


def _is_null_array(values: np.ndarray) -> np.ndarray:
    if values.dtype == object:
        return np.array([v is None for v in values], dtype=bool)
    return np.isnan(values)


def _as_array(value, n: int) -> np.ndarray:
    """Broadcast a scalar evaluation result to column length."""
    if isinstance(value, np.ndarray) and value.ndim >= 1:
        return value
    if isinstance(value, str) or value is None:
        return np.array([value] * n, dtype=object)
    return np.full(n, float(value), dtype=np.float64)


class Expression:
    def evaluate(self, df) -> np.ndarray:
        raise NotImplementedError

    # --- operators ----------------------------------------------------------
    def _binary(self, other, fn: Callable, comparison: bool = False):
        return BinaryOp(self, other, fn, comparison)

    def __add__(self, other):
        return self._binary(other, np.add)

    def __radd__(self, other):
        return BinaryOp(lit(other), self, np.add)

    def __sub__(self, other):
        return self._binary(other, np.subtract)

    def __rsub__(self, other):
        return BinaryOp(lit(other), self, np.subtract)

    def __mul__(self, other):
        return self._binary(other, np.multiply)

    def __rmul__(self, other):
        return BinaryOp(lit(other), self, np.multiply)

    def __truediv__(self, other):
        return self._binary(other, np.divide)

    def __rtruediv__(self, other):
        return BinaryOp(lit(other), self, np.divide)

    def __neg__(self):
        return BinaryOp(lit(0.0), self, np.subtract)

    def __eq__(self, other):  # type: ignore[override]
        return self._binary(other, None, comparison=True)

    def __ne__(self, other):  # type: ignore[override]
        return self._binary(other, "ne", comparison=True)

    def __gt__(self, other):
        return self._binary(other, np.greater, comparison=True)

    def __ge__(self, other):
        return self._binary(other, np.greater_equal, comparison=True)

    def __lt__(self, other):
        return self._binary(other, np.less, comparison=True)

    def __le__(self, other):
        return self._binary(other, np.less_equal, comparison=True)

    def __and__(self, other):
        return BinaryOp(self, other, np.logical_and, comparison=True)

    def __or__(self, other):
        return BinaryOp(self, other, np.logical_or, comparison=True)

    def __invert__(self):
        return UnaryOp(self, np.logical_not)

    def __hash__(self):
        return id(self)

    # --- pyspark Column methods --------------------------------------------
    def isNull(self):
        return UnaryOp(self, _is_null_array)

    def isNotNull(self):
        return UnaryOp(self, lambda v: ~_is_null_array(v))

    def getItem(self, index: int):
        return GetItem(self, index)

    def alias(self, name: str):
        return Alias(self, name)

    def otherwise(self, value):
        raise TypeError("otherwise() is only valid on when(...) expressions")


class Column(Expression):
    def __init__(self, name: str):
        self.name = name

    def evaluate(self, df) -> np.ndarray:
        return df._column(self.name)


class Literal(Expression):
    def __init__(self, value):
        self.value = value

    def evaluate(self, df) -> np.ndarray:
        return _as_array(self.value, df.count())


class Alias(Expression):
    def __init__(self, child: Expression, name: str):
        self.child = child
        self.name = name

    def evaluate(self, df) -> np.ndarray:
        return self.child.evaluate(df)


class BinaryOp(Expression):
    def __init__(self, left, right, fn: Optional[Callable], comparison: bool = False):
        self.left = left if isinstance(left, Expression) else Literal(left)
        self.right = right if isinstance(right, Expression) else Literal(right)
        self.fn = fn
        self.comparison = comparison

    def evaluate(self, df) -> np.ndarray:
        left = _as_array(self.left.evaluate(df), df.count())
        right = _as_array(self.right.evaluate(df), df.count())
        if self.fn in (None, "ne"):  # (in)equality, null-is-false
            if left.dtype == object or right.dtype == object:
                equal = np.array(
                    [
                        a is not None and b is not None and a == b
                        for a, b in zip(left, right)
                    ],
                    dtype=bool,
                )
                non_null = ~_is_null_array(left) & ~_is_null_array(right)
            else:
                with np.errstate(invalid="ignore"):
                    equal = np.equal(left, right)
                non_null = ~np.isnan(left) & ~np.isnan(right)
            if self.fn == "ne":
                # Spark: null != x is null → row predicate False, same
                # null-is-false folding as equality.
                return ~equal & non_null
            return equal & non_null
        if self.comparison and self.fn in (
            np.greater,
            np.greater_equal,
            np.less,
            np.less_equal,
        ):
            left_f = left.astype(np.float64)
            right_f = right.astype(np.float64)
            with np.errstate(invalid="ignore"):
                result = self.fn(left_f, right_f)
            return result & ~np.isnan(left_f) & ~np.isnan(right_f)
        return self.fn(left, right)


class UnaryOp(Expression):
    def __init__(self, child: Expression, fn: Callable):
        self.child = child
        self.fn = fn

    def evaluate(self, df) -> np.ndarray:
        return self.fn(_as_array(self.child.evaluate(df), df.count()))


class When(Expression):
    """``when(cond, value)`` chain with ``.when`` / ``.otherwise``.

    Without ``otherwise``, unmatched rows are null (Spark semantics).
    """

    def __init__(self, branches: list[tuple[Expression, Any]], default=None):
        self.branches = branches
        self.default = default

    def when(self, condition, value) -> "When":
        return When(self.branches + [(condition, value)], self.default)

    def otherwise(self, value) -> "When":
        return When(self.branches, value)

    def evaluate(self, df) -> np.ndarray:
        n = df.count()
        evaluated = []
        for condition, value in self.branches:
            value_expr = value if isinstance(value, Expression) else Literal(value)
            evaluated.append(
                (
                    np.asarray(condition.evaluate(df), dtype=bool),
                    _as_array(value_expr.evaluate(df), n),
                )
            )
        any_object = any(values.dtype == object for _, values in evaluated)
        if self.default is None and not any_object:
            # Unmatched numeric rows are null → float64 NaN, keeping the
            # frame's null convention (not an object column of None).
            result = np.full(n, np.nan, dtype=np.float64)
        else:
            default = (
                self.default
                if isinstance(self.default, Expression)
                else Literal(self.default)
            )
            result = _as_array(default.evaluate(df), n).copy()
            any_object = any_object or result.dtype == object
        decided = np.zeros(n, dtype=bool)
        for match, values in evaluated:
            match = match & ~decided
            if any_object and result.dtype != object:
                result = result.astype(object)
            if any_object and values.dtype != object:
                values = values.astype(object)
            result[match] = values[match]
            decided |= match
        return result


class RegexpExtract(Expression):
    def __init__(self, child: Expression, pattern: str, group: int):
        self.child = child
        self.pattern = re.compile(pattern)
        self.group = group

    def evaluate(self, df) -> np.ndarray:
        values = _as_array(self.child.evaluate(df), df.count())

        def extract(value):
            if value is None:
                return None
            match = self.pattern.search(str(value))
            return match.group(self.group) if match else ""

        return np.array([extract(v) for v in values], dtype=object)


class Split(Expression):
    def __init__(self, child: Expression, pattern: str):
        self.child = child
        self.pattern = re.compile(pattern)

    def evaluate(self, df) -> np.ndarray:
        values = _as_array(self.child.evaluate(df), df.count())
        # Per-slot assignment: np.array(list-of-equal-length-lists) would
        # silently build a 2-D object matrix instead of a list column.
        out = np.empty(len(values), dtype=object)
        for i, value in enumerate(values):
            out[i] = None if value is None else self.pattern.split(str(value))
        return out


class GetItem(Expression):
    def __init__(self, child: Expression, index: int):
        self.child = child
        self.index = index

    def evaluate(self, df) -> np.ndarray:
        values = self.child.evaluate(df)
        out = []
        for value in values:
            try:
                out.append(value[self.index])
            except (TypeError, IndexError):
                out.append(None)
        return np.array(out, dtype=object)


class Mean(Expression):
    def __init__(self, child: Expression):
        self.child = child

    def evaluate(self, df) -> np.ndarray:
        values = _as_array(self.child.evaluate(df), df.count())
        return np.full(df.count(), np.nanmean(values.astype(np.float64)))


# --- public constructors (pyspark.sql.functions names) ---------------------

def col(name: str) -> Column:
    return Column(name)


def lit(value) -> Literal:
    return Literal(value)


def when(condition: Expression, value) -> When:
    return When([(condition, value)])


def regexp_extract(column, pattern: str, group: int) -> RegexpExtract:
    if isinstance(column, str):
        column = col(column)
    return RegexpExtract(column, pattern, group)


def split(column, pattern: str) -> Split:
    if isinstance(column, str):
        column = col(column)
    return Split(column, pattern)


def mean(column) -> Mean:
    if isinstance(column, str):
        column = col(column)
    return Mean(column)
