"""`pyspark` import shim + the preprocessor-code runner.

The reference ``exec()``s user code that begins with real PySpark imports
(``from pyspark.ml import Pipeline``, ``from pyspark.sql.functions import
...``, ``from pyspark.ml.feature import ...`` — reference
docs/model_builder.md example). For that exact code to run unchanged on
this framework, those module paths must resolve — so this module
registers lightweight ``pyspark.*`` modules in ``sys.modules`` backed by
our expression/feature implementations.

Running user-supplied code is the reference's documented contract
(model_builder.py:144-145 ``exec(preprocessor_code, ...)``), arbitrary
code execution included; deployments that need isolation should sandbox
the model-builder service process, exactly as they would the reference's.
"""

from __future__ import annotations

import sys
import types

from learningorchestra_tpu.frame import expressions as _expressions
from learningorchestra_tpu.frame import feature as _feature


def _module(name: str, **attrs) -> types.ModuleType:
    module = types.ModuleType(name)
    for key, value in attrs.items():
        setattr(module, key, value)
    return module


def install_pyspark_shim() -> None:
    """Register ``pyspark`` module aliases (idempotent; no-op when a real
    pyspark is importable first — it isn't in this framework's image)."""
    if "pyspark" in sys.modules:
        return
    functions = _module(
        "pyspark.sql.functions",
        col=_expressions.col,
        lit=_expressions.lit,
        when=_expressions.when,
        mean=_expressions.mean,
        split=_expressions.split,
        regexp_extract=_expressions.regexp_extract,
    )
    feature = _module(
        "pyspark.ml.feature",
        StringIndexer=_feature.StringIndexer,
        VectorAssembler=_feature.VectorAssembler,
    )
    ml = _module("pyspark.ml", Pipeline=_feature.Pipeline, feature=feature)
    sql = _module("pyspark.sql", functions=functions)
    pyspark = _module("pyspark", ml=ml, sql=sql)
    sys.modules["pyspark"] = pyspark
    sys.modules["pyspark.ml"] = ml
    sys.modules["pyspark.ml.feature"] = feature
    sys.modules["pyspark.sql"] = sql
    sys.modules["pyspark.sql.functions"] = functions


def fields_from_dataframe(dataframe, is_string: bool) -> list[str]:
    """The helper the reference exposes to preprocessor code
    (model_builder.py:118-131): classify columns by the type of the
    first row's value."""
    first_row = dataframe.first()
    names = []
    for column in dataframe.schema.names:
        value = first_row[column] if first_row is not None else None
        if is_string == isinstance(value, str):
            names.append(column)
    return names


def run_preprocessor(code: str, training_df, testing_df) -> dict:
    """Execute user preprocessing code with the reference's environment
    contract (docs/model_builder.md): ``training_df``/``testing_df`` in
    scope; the code must bind ``features_training``, ``features_testing``
    and ``features_evaluation`` (None allowed)."""
    install_pyspark_shim()

    class _SelfProxy:
        """The reference exec()s code inside a method, so user code can
        call ``self.fields_from_dataframe(...)``."""

        @staticmethod
        def fields_from_dataframe(dataframe, is_string):
            return fields_from_dataframe(dataframe, is_string)

    scope = {
        "training_df": training_df,
        "testing_df": testing_df,
        "self": _SelfProxy(),
        "fields_from_dataframe": fields_from_dataframe,
    }
    exec(code, scope, scope)
    missing = [
        name
        for name in ("features_training", "features_testing", "features_evaluation")
        if name not in scope
    ]
    if missing:
        raise KeyError(
            f"preprocessor_code must define {missing} "
            "(reference contract, docs/model_builder.md)"
        )
    return {
        "features_training": scope["features_training"],
        "features_testing": scope["features_testing"],
        "features_evaluation": scope["features_evaluation"],
    }
