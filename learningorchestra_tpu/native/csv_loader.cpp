// Native columnar CSV loader.
//
// The reference's storage<->compute data plane is the JVM mongo-spark
// connector (reference: model_builder.py:74-76, projection.py:58-61);
// this framework's equivalent is a host-side columnar loader feeding
// jax.device_put (SURVEY.md section 2). This C++ core does the
// byte-level work — one pass over the file building a cell index with
// RFC-4180 quote handling, plus vectorized numeric column extraction —
// so Python never iterates rows character by character.
//
// C ABI (ctypes-consumed, see native/loader.py):
//   csv_open(path)            -> handle (0 on failure)
//   csv_num_rows/cols(h)      -> dimensions (rows exclude the header)
//   csv_cell(h, row, col, &n) -> unquoted cell bytes (row -1 = header)
//   csv_col_is_numeric(h, c)  -> 1 iff every cell parses as double/empty
//   csv_fill_numeric(h, c, out) -> doubles, NaN for empty cells
//   csv_close(h)

#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <string>
#include <vector>

namespace {

struct Cell {
  uint64_t offset;
  uint32_t length;
  bool quoted;
};

struct CsvFile {
  std::string data;        // whole file
  std::string unquoted;    // scratch storage for dequoted cells
  std::vector<Cell> cells; // row-major, including header row
  size_t num_cols = 0;
  size_t num_rows = 0;     // excluding header
};

// Parse the raw bytes into the cell index. Handles quoted fields with
// embedded commas/newlines and doubled quotes.
bool parse(CsvFile* f) {
  const std::string& s = f->data;
  size_t i = 0, n = s.size();
  std::vector<Cell> row;
  bool first_row = true;
  while (i <= n) {
    // parse one cell starting at i
    Cell cell{i, 0, false};
    if (i < n && s[i] == '"') {
      cell.quoted = true;
      cell.offset = i + 1;
      size_t j = i + 1;
      while (j < n) {
        if (s[j] == '"') {
          if (j + 1 < n && s[j + 1] == '"') { j += 2; continue; }
          break;
        }
        ++j;
      }
      cell.length = static_cast<uint32_t>(j - cell.offset);
      i = (j < n) ? j + 1 : j;  // past closing quote
    } else {
      size_t j = i;
      while (j < n && s[j] != ',' && s[j] != '\n' && s[j] != '\r') ++j;
      cell.length = static_cast<uint32_t>(j - cell.offset);
      i = j;
    }
    row.push_back(cell);
    if (i >= n) {
      bool empty_tail =
          row.size() == 1 && row[0].length == 0 && !row[0].quoted;
      if (!empty_tail) {
        if (first_row) { f->num_cols = row.size(); first_row = false; }
        else ++f->num_rows;
        f->cells.insert(f->cells.end(), row.begin(), row.end());
        // pad short rows so the index stays rectangular
        for (size_t k = row.size(); k < f->num_cols; ++k)
          f->cells.push_back(Cell{0, 0, false});
      }
      break;
    }
    if (s[i] == ',') { ++i; continue; }
    // row terminator (\n, \r\n or \r)
    if (s[i] == '\r') { ++i; if (i < n && s[i] == '\n') ++i; }
    else if (s[i] == '\n') ++i;
    bool blank_line = row.size() == 1 && row[0].length == 0 && !row[0].quoted;
    if (!blank_line) {
      if (first_row) { f->num_cols = row.size(); first_row = false; }
      else ++f->num_rows;
      f->cells.insert(f->cells.end(), row.begin(), row.end());
      for (size_t k = row.size(); k < f->num_cols; ++k)
        f->cells.push_back(Cell{0, 0, false});
      if (f->num_cols && row.size() > f->num_cols) return false; // ragged wide
    }
    row.clear();
  }
  return f->num_cols > 0;
}

const Cell* cell_at(const CsvFile* f, long long row, size_t col) {
  // row -1 addresses the header
  size_t index = static_cast<size_t>(row + 1) * f->num_cols + col;
  if (col >= f->num_cols || index >= f->cells.size()) return nullptr;
  return &f->cells[index];
}

}  // namespace

extern "C" {

void* csv_open(const char* path) {
  std::ifstream in(path, std::ios::binary);
  if (!in) return nullptr;
  auto* f = new CsvFile();
  in.seekg(0, std::ios::end);
  f->data.resize(static_cast<size_t>(in.tellg()));
  in.seekg(0);
  in.read(&f->data[0], static_cast<std::streamsize>(f->data.size()));
  if (!parse(f)) { delete f; return nullptr; }
  return f;
}

void csv_close(void* handle) { delete static_cast<CsvFile*>(handle); }

uint64_t csv_num_rows(void* handle) {
  return static_cast<CsvFile*>(handle)->num_rows;
}

uint64_t csv_num_cols(void* handle) {
  return static_cast<CsvFile*>(handle)->num_cols;
}

// Returns a pointer to the cell's bytes and writes its length. Quoted
// cells containing doubled quotes are unescaped into scratch storage.
const char* csv_cell(void* handle, long long row, uint64_t col,
                     uint32_t* length) {
  auto* f = static_cast<CsvFile*>(handle);
  const Cell* c = cell_at(f, row, col);
  if (!c) { *length = 0; return nullptr; }
  const char* p = f->data.data() + c->offset;
  if (c->quoted && memchr(p, '"', c->length)) {
    f->unquoted.clear();
    for (uint32_t i = 0; i < c->length; ++i) {
      f->unquoted.push_back(p[i]);
      if (p[i] == '"' && i + 1 < c->length && p[i + 1] == '"') ++i;
    }
    *length = static_cast<uint32_t>(f->unquoted.size());
    return f->unquoted.data();
  }
  *length = c->length;
  return p;
}

// Matches Python float() semantics (the fallback path's parser): no hex
// literals, cells longer than 511 bytes are treated as strings by both
// paths (loader.py applies the same cap to the fallback).
int csv_col_is_numeric(void* handle, uint64_t col) {
  auto* f = static_cast<CsvFile*>(handle);
  for (size_t r = 0; r < f->num_rows; ++r) {
    const Cell* c = cell_at(f, static_cast<long long>(r), col);
    if (!c || c->length == 0) continue;  // empty = missing, allowed
    char buf[512];
    if (c->length >= sizeof(buf)) return 0;
    memcpy(buf, f->data.data() + c->offset, c->length);
    buf[c->length] = '\0';
    if (memchr(buf, 'x', c->length) || memchr(buf, 'X', c->length)) return 0;
    char* end = nullptr;
    strtod(buf, &end);
    while (end && *end && isspace(static_cast<unsigned char>(*end))) ++end;
    if (!end || *end != '\0' || end == buf) return 0;
  }
  return 1;
}

// Total bytes needed by csv_fill_strings for this column (cells +
// one NUL separator per cell).
uint64_t csv_col_string_bytes(void* handle, uint64_t col) {
  auto* f = static_cast<CsvFile*>(handle);
  uint64_t total = 0;
  for (size_t r = 0; r < f->num_rows; ++r) {
    const Cell* c = cell_at(f, static_cast<long long>(r), col);
    if (c) total += c->length;
    total += 1;  // separator
  }
  return total;
}

// Writes every cell of the column into `out`, NUL-separated, unescaping
// doubled quotes. One bulk call instead of num_rows ctypes round trips.
void csv_fill_strings(void* handle, uint64_t col, char* out) {
  auto* f = static_cast<CsvFile*>(handle);
  for (size_t r = 0; r < f->num_rows; ++r) {
    const Cell* c = cell_at(f, static_cast<long long>(r), col);
    if (c && c->length) {
      const char* p = f->data.data() + c->offset;
      if (c->quoted && memchr(p, '"', c->length)) {
        for (uint32_t i = 0; i < c->length; ++i) {
          *out++ = p[i];
          if (p[i] == '"' && i + 1 < c->length && p[i + 1] == '"') ++i;
        }
      } else {
        memcpy(out, p, c->length);
        out += c->length;
      }
    }
    *out++ = '\0';
  }
}

void csv_fill_numeric(void* handle, uint64_t col, double* out) {
  auto* f = static_cast<CsvFile*>(handle);
  for (size_t r = 0; r < f->num_rows; ++r) {
    const Cell* c = cell_at(f, static_cast<long long>(r), col);
    if (!c || c->length == 0) { out[r] = NAN; continue; }
    char buf[512];
    if (c->length >= sizeof(buf)) { out[r] = NAN; continue; }
    memcpy(buf, f->data.data() + c->offset, c->length);
    buf[c->length] = '\0';
    out[r] = strtod(buf, nullptr);
  }
}

}  // extern "C"
