"""Native runtime components (C++), bound via ctypes.

The compute path is JAX/XLA; the IO path around it is native, like the
reference's (its data plane was the JVM mongo-spark connector,
SURVEY.md §2). ``loader.py`` exposes the C++ columnar CSV parser with a
pure-Python fallback, so the framework degrades gracefully on hosts
without a toolchain.
"""

from learningorchestra_tpu.native.loader import (
    NativeCsv,
    native_available,
    read_csv_columns,
)

__all__ = ["NativeCsv", "native_available", "read_csv_columns"]
