"""ctypes bindings for the native CSV loader, with lazy build + fallback.

The shared object is compiled on first use with g++ (``-O3 -shared
-fPIC``) into the package directory; hosts without a toolchain (or where
the build fails) transparently fall back to the Python csv module with
identical results — the native path is a performance feature, not a
correctness dependency.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SOURCE = os.path.join(_HERE, "csv_loader.cpp")
_LIBRARY = os.path.join(_HERE, "_csv_loader.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> Optional[ctypes.CDLL]:
    global _build_failed
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SOURCE, "-o", _LIBRARY],
            check=True,
            capture_output=True,
            timeout=120,
        )
    except (OSError, subprocess.SubprocessError):
        _build_failed = True
        return None
    return _load(_LIBRARY)


def _load(path: str) -> ctypes.CDLL:
    lib = ctypes.CDLL(path)
    lib.csv_open.restype = ctypes.c_void_p
    lib.csv_open.argtypes = [ctypes.c_char_p]
    lib.csv_close.argtypes = [ctypes.c_void_p]
    lib.csv_num_rows.restype = ctypes.c_uint64
    lib.csv_num_rows.argtypes = [ctypes.c_void_p]
    lib.csv_num_cols.restype = ctypes.c_uint64
    lib.csv_num_cols.argtypes = [ctypes.c_void_p]
    lib.csv_cell.restype = ctypes.c_void_p
    lib.csv_cell.argtypes = [
        ctypes.c_void_p,
        ctypes.c_longlong,
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint32),
    ]
    lib.csv_col_is_numeric.restype = ctypes.c_int
    lib.csv_col_is_numeric.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.csv_fill_numeric.argtypes = [
        ctypes.c_void_p,
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_double),
    ]
    lib.csv_col_string_bytes.restype = ctypes.c_uint64
    lib.csv_col_string_bytes.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.csv_fill_strings.argtypes = [
        ctypes.c_void_p,
        ctypes.c_uint64,
        ctypes.c_char_p,
    ]
    return lib


def _get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is None and not _build_failed:
            have_library = os.path.exists(_LIBRARY)
            have_source = os.path.exists(_SOURCE)
            if have_library and (
                not have_source
                or os.path.getmtime(_LIBRARY) >= os.path.getmtime(_SOURCE)
            ):
                # Prebuilt .so shipped without source: load it directly.
                _lib = _load(_LIBRARY)
            elif have_source:
                _lib = _build()
            else:
                _build_failed = True
        return _lib


def native_available() -> bool:
    return _get_lib() is not None


class NativeCsv:
    """A parsed CSV file: header, cells, columnar numeric extraction."""

    def __init__(self, path: str):
        lib = _get_lib()
        if lib is None:
            raise RuntimeError("native CSV loader unavailable")
        self._lib = lib
        self._handle = lib.csv_open(path.encode())
        if not self._handle:
            raise OSError(f"cannot parse CSV at {path!r}")
        self.num_rows = lib.csv_num_rows(self._handle)
        self.num_cols = lib.csv_num_cols(self._handle)

    def close(self) -> None:
        if self._handle:
            self._lib.csv_close(self._handle)
            self._handle = None

    def __enter__(self) -> "NativeCsv":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def cell(self, row: int, col: int) -> str:
        """Cell text; ``row == -1`` reads the header."""
        length = ctypes.c_uint32()
        pointer = self._lib.csv_cell(self._handle, row, col, ctypes.byref(length))
        if not pointer or length.value == 0:
            return ""
        return ctypes.string_at(pointer, length.value).decode("utf-8")

    def header(self) -> list[str]:
        return [self.cell(-1, j) for j in range(self.num_cols)]

    def column_is_numeric(self, col: int) -> bool:
        return bool(self._lib.csv_col_is_numeric(self._handle, col))

    def numeric_column(self, col: int) -> np.ndarray:
        out = np.empty(self.num_rows, dtype=np.float64)
        self._lib.csv_fill_numeric(
            self._handle,
            col,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        )
        return out

    def string_column(self, col: int) -> np.ndarray:
        """One bulk NUL-joined copy out of C, one decode, one split —
        no per-cell ctypes round trips."""
        total = self._lib.csv_col_string_bytes(self._handle, col)
        buffer = ctypes.create_string_buffer(int(total))
        self._lib.csv_fill_strings(self._handle, col, buffer)
        cells = buffer.raw[: int(total)].decode("utf-8").split("\x00")
        if len(cells) != self.num_rows + 1:
            # a cell contained a literal NUL: the separator protocol
            # over-splits — take the exact per-cell path instead.
            out = np.empty(self.num_rows, dtype=object)
            for i in range(self.num_rows):
                out[i] = self.cell(i, col)
            return out
        out = np.empty(self.num_rows, dtype=object)
        out[:] = cells[: self.num_rows]
        return out


MAX_NUMERIC_CELL = 511  # both paths treat longer cells as strings


def _python_read(path: str) -> dict[str, np.ndarray]:
    import csv

    with open(path, encoding="utf-8", newline="") as handle:
        reader = csv.reader(handle)
        header = next(reader)
        rows = [row for row in reader if row]
    columns: dict[str, np.ndarray] = {}
    for j, name in enumerate(header):
        raw = [row[j] if j < len(row) else "" for row in rows]
        try:
            # Reject what strtod rejects so both paths agree: oversized
            # cells, underscore separators ("1_000"), non-ASCII digits.
            if any(
                len(cell) > MAX_NUMERIC_CELL or "_" in cell or not cell.isascii()
                for cell in raw
            ):
                raise ValueError("cell outside the shared numeric grammar")
            columns[name] = np.array(
                [np.nan if cell == "" else float(cell) for cell in raw],
                dtype=np.float64,
            )
        except ValueError:
            columns[name] = _strings_column(raw)
    return columns


def _strings_column(cells: list[str]) -> np.ndarray:
    """Object column with the ColumnTable missing-value convention:
    empty cells become None, not ''."""
    out = np.empty(len(cells), dtype=object)
    for i, cell in enumerate(cells):
        out[i] = None if cell == "" else cell
    return out


def read_csv_string_columns(path: str):
    """Header plus every column as an Arrow-layout string
    :class:`~learningorchestra_tpu.core.columns.Column`, built straight
    from the native parser's NUL-joined bulk export — raw cell strings
    (``""`` for empty, the ingest contract, reference database.py:
    156-169) with **zero Python string objects materialized**. Returns
    ``None`` when the native parser is unavailable or rejects the file.
    """
    from learningorchestra_tpu.core.columns import Column

    lib = _get_lib()
    if lib is None:
        return None
    try:
        parsed = NativeCsv(path)
    except OSError:
        return None
    with parsed:
        header = parsed.header()
        columns = []
        for j in range(parsed.num_cols):
            total = int(lib.csv_col_string_bytes(parsed._handle, j))
            buffer = ctypes.create_string_buffer(total)
            lib.csv_fill_strings(parsed._handle, j, buffer)
            try:
                columns.append(
                    Column.from_nul_joined(buffer.raw[:total], parsed.num_rows)
                )
            except ValueError:
                # a cell contained a literal NUL: exact per-cell path
                columns.append(
                    Column.from_strings(
                        [parsed.cell(i, j) for i in range(parsed.num_rows)]
                    )
                )
    return header, columns


def read_csv_raw_columns(path: str) -> Optional[tuple[list[str], list[list[str]]]]:
    """Header plus every column as raw cell strings (``""`` for empty) —
    the ingest contract, which stores values untyped (reference:
    microservices/database_api_image/database.py:156-169; the fieldtypes
    service converts later). Returns ``None`` when the native parser is
    unavailable or rejects the file (caller falls back to Python)."""
    lib = _get_lib()
    if lib is None:
        return None
    try:
        parsed = NativeCsv(path)
    except OSError:
        return None
    with parsed:
        header = parsed.header()
        columns = [
            parsed.string_column(j).tolist() for j in range(parsed.num_cols)
        ]
    return header, columns


def read_csv_columns(path: str) -> dict[str, np.ndarray]:
    """CSV → columns: float64 (NaN for empty) where every cell parses as
    a number, object strings otherwise. Native when available, Python
    fallback with identical semantics."""
    lib = _get_lib()
    if lib is None:
        return _python_read(path)
    try:
        parsed = NativeCsv(path)
    except OSError:
        # e.g. ragged-wide rows the strict native parser rejects — the
        # tolerant Python path still handles them.
        return _python_read(path)
    with parsed:
        header = parsed.header()
        columns: dict[str, np.ndarray] = {}
        for j, name in enumerate(header):
            if parsed.column_is_numeric(j):
                columns[name] = parsed.numeric_column(j)
            else:
                column = parsed.string_column(j)
                column[column == ""] = None  # missing-value convention
                columns[name] = column
        return columns
