"""Estimators: the TPU-native replacement for Spark MLlib classifiers.

The reference trains five ``pyspark.ml.classification`` models (reference:
microservices/model_builder_image/model_builder.py:7-13,151-157):
LogisticRegression, DecisionTreeClassifier, RandomForestClassifier,
GBTClassifier, NaiveBayes. Each estimator here reproduces that
capability as batched JAX programs designed for the MXU — matmuls and
histogram scatters over row-sharded device arrays — instead of JVM
iterators.

All estimators share one contract (``ml/base.py``): ``fit(X, y)`` returns
a fitted model with ``predict``/``predict_proba``; ``mesh=`` shards rows
over the ``data`` axis so multi-chip is a constructor knob, not a code
change.
"""

from learningorchestra_tpu.ml.base import CLASSIFIER_NAMES, make_classifier
from learningorchestra_tpu.ml.evaluation import accuracy_score, f1_score
from learningorchestra_tpu.ml.logistic import LogisticRegression
from learningorchestra_tpu.ml.naive_bayes import NaiveBayes

__all__ = [
    "CLASSIFIER_NAMES",
    "make_classifier",
    "accuracy_score",
    "f1_score",
    "LogisticRegression",
    "NaiveBayes",
]
