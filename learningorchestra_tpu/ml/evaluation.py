"""Multiclass evaluation metrics: accuracy and weighted F1.

Replaces the JVM ``MulticlassClassificationEvaluator`` the reference uses
with ``metricName`` "f1" and "accuracy" (reference:
microservices/model_builder_image/model_builder.py:205-224). Spark's
"f1" is the *weighted* F1: per-class F1 averaged with true-class support
weights. Both metrics reduce to one confusion matrix, built on device
with a single scatter-add.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("num_classes",))
def confusion_matrix(
    y_true: jax.Array, y_pred: jax.Array, num_classes: int
) -> jax.Array:
    """``(num_classes, num_classes)`` counts, rows = true class."""
    index = y_true.astype(jnp.int32) * num_classes + y_pred.astype(jnp.int32)
    flat = jnp.zeros(num_classes * num_classes, dtype=jnp.float32).at[index].add(1.0)
    return flat.reshape(num_classes, num_classes)


@partial(jax.jit, static_argnames=("num_classes",))
def masked_metrics(
    y_true: jax.Array, y_pred: jax.Array, weights: jax.Array, num_classes: int
):
    """``(accuracy, weighted_f1)`` over the valid rows only — the
    device-resident evaluation the builder fuses into the forward pass
    (``FittedModel.evaluate``): padded rows carry weight 0, so sharded
    padded predictions never bias the confusion matrix."""
    index = y_true.astype(jnp.int32) * num_classes + y_pred.astype(jnp.int32)
    flat = (
        jnp.zeros(num_classes * num_classes, dtype=jnp.float32)
        .at[index]
        .add(weights.astype(jnp.float32))
    )
    return _metrics_from_cm(flat.reshape(num_classes, num_classes))


@jax.jit
def _metrics_from_cm(cm: jax.Array):
    total = cm.sum()
    accuracy = jnp.trace(cm) / total
    true_positive = jnp.diag(cm)
    support = cm.sum(axis=1)          # actual count per class
    predicted = cm.sum(axis=0)        # predicted count per class
    precision = jnp.where(predicted > 0, true_positive / predicted, 0.0)
    recall = jnp.where(support > 0, true_positive / support, 0.0)
    f1 = jnp.where(
        precision + recall > 0, 2 * precision * recall / (precision + recall), 0.0
    )
    weighted_f1 = (f1 * support).sum() / total
    return accuracy, weighted_f1


@partial(jax.jit, static_argnames=("num_classes",))
def _metrics(y_true: jax.Array, y_pred: jax.Array, num_classes: int):
    cm = confusion_matrix(y_true, y_pred, num_classes)
    return _metrics_from_cm(cm)


def evaluate_both(y_true: np.ndarray, y_pred: np.ndarray) -> tuple[float, float]:
    """``(accuracy, weighted_f1)`` in ONE device dispatch — the builder's
    evaluate phase calls this instead of two separate metric programs
    (one confusion matrix serves both, exactly like the reference's two
    evaluators over one prediction frame, model_builder.py:205-224)."""
    num_classes = int(max(np.max(y_true), np.max(y_pred))) + 1
    accuracy, weighted_f1 = _metrics(
        jnp.asarray(y_true, jnp.int32), jnp.asarray(y_pred, jnp.int32), num_classes
    )
    return float(accuracy), float(weighted_f1)


def accuracy_score(y_true: np.ndarray, y_pred: np.ndarray) -> float:
    num_classes = int(max(np.max(y_true), np.max(y_pred))) + 1
    accuracy, _ = _metrics(
        jnp.asarray(y_true, jnp.int32), jnp.asarray(y_pred, jnp.int32), num_classes
    )
    return float(accuracy)


def f1_score(y_true: np.ndarray, y_pred: np.ndarray) -> float:
    """Weighted multiclass F1 (Spark ``metricName="f1"`` semantics)."""
    num_classes = int(max(np.max(y_true), np.max(y_pred))) + 1
    _, weighted_f1 = _metrics(
        jnp.asarray(y_true, jnp.int32), jnp.asarray(y_pred, jnp.int32), num_classes
    )
    return float(weighted_f1)
