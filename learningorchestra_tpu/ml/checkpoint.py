"""Model checkpoint/resume.

The reference never serializes models — only predictions and metrics
survive a run, and a killed fit loses everything (reference:
model_builder.py:232-247; SURVEY.md §5 "Checkpoint / resume: absent").
This module adds what the reference lacks: every fitted model saves to
one ``.npz`` (device arrays fetched to host) plus a JSON header typing
it, and loads back into a predict-capable model on any host — TPU
training, CPU serving included.
"""

from __future__ import annotations

import json
import os
import zipfile
from typing import Optional

import numpy as np
from jax.sharding import Mesh

from learningorchestra_tpu.ml.base import resolve_mesh
from learningorchestra_tpu.ml.logistic import LogisticRegressionModel
from learningorchestra_tpu.ml.naive_bayes import NaiveBayesModel
from learningorchestra_tpu.ml.trees import GBTModel, _TreeEnsembleModel

_HEADER = "__model__.json"

# One artifact naming scheme shared by the builder (which writes) and
# the model_builder service (which lists/loads): <models_dir>/<name>.model
CHECKPOINT_SUFFIX = ".model"


def checkpoint_path(models_dir: str, name: str) -> str:
    return os.path.join(models_dir, name + CHECKPOINT_SUFFIX)


def _fetch(value) -> np.ndarray:
    """Device → host for a (possibly multi-host-sharded) parameter.

    ``np.asarray`` raises on arrays spanning non-addressable devices
    (model-axis-sharded LR classes / RF trees on a multi-host mesh);
    ``parallel.multihost.fetch`` process_allgathers those — and every
    process enters train_one, so the collective lines up."""
    from learningorchestra_tpu.parallel.multihost import fetch

    return np.asarray(fetch(value))


def _arrays_of(model) -> tuple[str, dict[str, np.ndarray], dict]:
    if isinstance(model, LogisticRegressionModel):
        return (
            "logistic",
            {
                "w": _fetch(model.params["w"]),
                "b": _fetch(model.params["b"]),
                "mean": _fetch(model.mean),
                "scale": _fetch(model.scale),
            },
            {},
        )
    if isinstance(model, NaiveBayesModel):
        return (
            "naive_bayes",
            {"theta": _fetch(model.theta), "prior": _fetch(model.prior)},
            {},
        )
    if isinstance(model, GBTModel):
        return (
            "gbt",
            {
                "features_heap": _fetch(model.features_heap),
                "thresholds_heap": _fetch(model.thresholds_heap),
                "leaf_values": _fetch(model.leaf_values),
            },
            {
                "f0": float(_fetch(model.f0)),
                "step": float(model.step),
                "max_depth": int(model.max_depth),
            },
        )
    if isinstance(model, _TreeEnsembleModel):
        return (
            "tree_ensemble",
            {
                "features_heap": _fetch(model.features_heap),
                "thresholds_heap": _fetch(model.thresholds_heap),
                "leaf_probs": _fetch(model.leaf_probs),
            },
            {"max_depth": int(model.max_depth)},
        )
    raise TypeError(f"unknown model type {type(model).__name__}")


def gather_model(model) -> tuple[str, dict[str, np.ndarray], dict]:
    """Fetch a fitted model's parameters to host memory.

    On a multi-host mesh with model-axis sharding this enters a
    process_allgather, so EVERY process must call it at the same point
    (the builder runs it on all processes; only the coordinator then
    writes the file — parallel/spmd.py's compute-global/IO-local rule).
    """
    return _arrays_of(model)


def write_checkpoint(
    gathered: tuple[str, dict[str, np.ndarray], dict], path: str
) -> None:
    """Write gathered model arrays to ``path`` (.npz format, any
    extension). The write is atomic (temp file + ``os.replace``): a
    concurrent reader never sees a partial archive, and a crash
    mid-save never leaves a corrupt artifact at the published path."""
    kind, arrays, scalars = gathered
    tmp_path = path + ".tmp"
    # Write through a file object: np.savez given a *name* appends
    # ".npz", which would split the archive from the header below.
    with open(tmp_path, "wb") as handle:
        np.savez(handle, **arrays)
    header = json.dumps({"kind": kind, "scalars": scalars})
    with zipfile.ZipFile(tmp_path, "a") as archive:
        archive.writestr(_HEADER, header)
    os.replace(tmp_path, path)


def save_model(model, path: str) -> None:
    """Single-host convenience: :func:`gather_model` +
    :func:`write_checkpoint` in one call."""
    write_checkpoint(gather_model(model), path)


def load_model(path: str, mesh: Optional[Mesh] = None):
    """Load a model saved by :func:`save_model`; predict-ready."""
    import jax.numpy as jnp

    mesh = resolve_mesh(mesh)
    with zipfile.ZipFile(path) as archive:
        header = json.loads(archive.read(_HEADER))
    data = np.load(path)
    kind = header["kind"]
    scalars = header["scalars"]
    if kind == "logistic":
        params = {"w": jnp.asarray(data["w"]), "b": jnp.asarray(data["b"])}
        return LogisticRegressionModel(
            params, jnp.asarray(data["mean"]), jnp.asarray(data["scale"]), mesh
        )
    if kind == "naive_bayes":
        return NaiveBayesModel(
            jnp.asarray(data["theta"]), jnp.asarray(data["prior"]), mesh
        )
    if kind == "gbt":
        return GBTModel(
            jnp.float32(scalars["f0"]),
            jnp.asarray(data["features_heap"]),
            jnp.asarray(data["thresholds_heap"]),
            jnp.asarray(data["leaf_values"]),
            scalars["step"],
            mesh,
            scalars["max_depth"],
        )
    if kind == "tree_ensemble":
        return _TreeEnsembleModel(
            jnp.asarray(data["features_heap"]),
            jnp.asarray(data["thresholds_heap"]),
            jnp.asarray(data["leaf_probs"]),
            mesh,
            scalars["max_depth"],
        )
    raise ValueError(f"unknown checkpoint kind {kind!r}")
